"""Checkpoint end-to-end integrity: CRC manifests, COMMIT markers,
restore-time verification with rollback.

Commit protocol (extends agent/ckpt_saver.py's done-marker scheme, in
the spirit of Orbax's distributed commit — every shard durable and
checksummed before the step becomes visible)::

    <ckpt_dir>/step-<N>/node_<id>.bin          shard bytes (atomic write)
    <ckpt_dir>/step-<N>/node_<id>.meta.json    leaf metas + crc32/bin_bytes
    <ckpt_dir>/step-<N>/done_<id>_w<W>         per-writer marker, now
                                               carrying {"crc32", "bytes"}
    <ckpt_dir>/step-<N>/commit_w<W>            terminal COMMIT marker:
                                               the full shard manifest,
                                               written by rank-0's agent
                                               AFTER all done markers
    <ckpt_dir>/latest                          tracker (unchanged)

Restore-time verification (``resolve_restore_step``) starts from the
tracker and accepts a step only when its COMMIT manifest is complete
and every listed shard's bytes match their recorded CRC32; a corrupt or
incomplete step is journaled (``ckpt_verify_failed``) and the search
rolls back through older step directories to the newest step that
verifies (``ckpt_rollback``). Before this layer, a flipped bit in a
shard restored silently; now it costs at most one checkpoint interval.

Pre-integrity checkpoints (no COMMIT marker, empty done markers) are
still accepted on done-marker completeness alone — they carry no CRCs
to check, and refusing them would strand every checkpoint written
before the upgrade.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import zlib

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_verify_failed_total = registry().counter(
    "dlrover_tpu_ckpt_verify_failed_total",
    "checkpoint steps rejected by restore-time verification, by kind",
    label_names=("kind",),
)
_rollback_total = registry().counter(
    "dlrover_tpu_ckpt_rollback_total",
    "restores rolled back past a corrupt/incomplete newest step",
)
_shard_rollback_total = registry().counter(
    "dlrover_tpu_ckpt_shard_rollback_total",
    "restores that skipped a corrupt shard file because every piece it "
    "held verifies on a replica twin (per-shard, not whole-step, "
    "rollback)",
)

STEP_DIR_RE = re.compile(r"^step-(\d+)$")
_COMMIT_RE = re.compile(r"^commit_w(\d+)$")
_DONE_RE = re.compile(r"^done_(.+)_w(\d+)$")


def crc32_bytes(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def commit_marker(num_shards: int) -> str:
    """Like done markers, the COMMIT is world-size-qualified: a re-save
    of the same step after an elastic reshape must not be validated
    against a previous incarnation's manifest."""
    return f"commit_w{num_shards}"


def write_commit(storage, sdir: str, step: int, num_shards: int,
                 shards: dict, extra: dict | None = None,
                 group: str = "") -> None:
    """Terminal COMMIT: ``shards`` maps node id (str) -> {"crc32",
    "bytes", "pieces": {key: {"crc32", "path", "index", "replica"}}}
    as collected from the persist acks (or done markers). The piece
    map is what quorum verification + per-shard rollback reason over;
    legacy entries without it degrade to whole-file semantics.
    ``extra`` merges additional top-level manifest fields (the
    embedding fabric records its hash-shard identity there — ring
    members, table geometry, applied version — so ``import_`` can
    reassemble any saved ring size onto the current one; verification
    ignores unknown fields). Atomic via the storage's tmp+fsync+rename
    write. ``group`` names the ack ledger this commit drew from (the
    embedding fabric passes "embedding"), so the §30 trail auditor can
    cross-check every committed step against its ``persist_ack``
    trail."""
    manifest = {"step": step, "num_shards": num_shards,
                "shards": shards}
    for key, value in (extra or {}).items():
        manifest.setdefault(key, value)
    storage.write(
        json.dumps(manifest),
        os.path.join(sdir, commit_marker(num_shards)),
    )
    get_journal().emit("ckpt_commit", step=int(step),
                       num_shards=int(num_shards),
                       shards=len(shards), group=group)


def _shard_crc(storage, path: str) -> tuple[int, int]:
    """(crc32, size). Streams local files so verifying a multi-GB shard
    never materializes it in memory. Under an installed chaos plan the
    read goes through ``storage.read`` instead, so ``storage_read``
    faults hit verification exactly like any other consumer."""
    from dlrover_tpu import chaos
    from dlrover_tpu.common.storage import PosixDiskStorage

    if isinstance(storage, PosixDiskStorage) and not chaos.ENABLED:
        crc = 0
        size = 0
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = zlib.crc32(chunk, crc)
                size += len(chunk)
        return crc & 0xFFFFFFFF, size
    blob = storage.read(path)
    return crc32_bytes(blob), len(blob)


@dataclasses.dataclass
class StepVerdict:
    """Outcome of quorum verification of one step directory.

    ``fail_kind`` None means the step is restorable. ``bad_pieces``
    maps writer node id -> the set of its piece keys that must NOT be
    read (``None`` = the whole shard file is unusable); every such
    piece verified on a replica twin held by another writer, or the
    step would have failed. ``rollbacks`` is the per-shard-rollback
    evidence (bad writer, failure kind, pieces recovered via twins).
    """

    fail_kind: str | None = None
    bad_pieces: dict[str, set | None] = dataclasses.field(
        default_factory=dict)
    rollbacks: list[dict] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.fail_kind is None


def _piece_key(entry: dict) -> tuple:
    return (entry.get("path", ""),
            json.dumps(entry.get("index", []), sort_keys=True))


def _verify_writer_pieces(storage, sdir: str, nid: str,
                          pieces: dict) -> set | None:
    """Which of a corrupt shard file's pieces are INDIVIDUALLY bad,
    checked against the per-piece CRCs in the meta via ranged reads;
    None when per-piece verification is impossible (missing meta /
    pre-piece writer) — then the whole file is unusable."""
    meta_path = os.path.join(sdir, f"node_{nid}.meta.json")
    try:
        header = json.loads(storage.read_text(meta_path))
        metas = dict(header.get("metas", {}))
    except (ValueError, OSError, TypeError, FileNotFoundError):
        return None
    bin_path = os.path.join(sdir, f"node_{nid}.bin")
    bad: set = set()
    for key, entry in pieces.items():
        info = metas.get(key) or {}
        want = (entry or {}).get("crc32", info.get("crc32"))
        offset, nbytes = info.get("offset"), info.get("nbytes")
        if want is None or offset is None or nbytes is None:
            return None  # can't attribute the damage; whole file bad
        try:
            blob = storage.read_range(bin_path, int(offset), int(nbytes))
        except (OSError, FileNotFoundError):
            return None
        if len(blob) != int(nbytes) or crc32_bytes(blob) != int(want):
            bad.add(key)
    return bad


def verify_step_quorum(storage, sdir: str, num_shards: int
                       ) -> StepVerdict:
    """Quorum semantics: a step is restorable iff its COMMIT manifest
    is complete AND every piece it lists verifies on at least one
    writer. A corrupt shard file no longer condemns the whole step when
    a replica twin (``DLROVER_TPU_CKPT_PERSIST_REPLICAS`` >= 2) holds
    verified copies of every piece the file contributed — that is the
    per-shard rollback. Without a COMMIT marker: legacy acceptance on
    done-marker count alone (those checkpoints carry no CRCs)."""
    files = storage.listdir(sdir)
    marker = commit_marker(num_shards)
    if marker not in files:
        done = [
            f for f in files
            if f.startswith("done_") and f.endswith(f"_w{num_shards}")
        ]
        if len(done) >= num_shards:
            return StepVerdict()
        return StepVerdict(fail_kind="missing_commit")
    try:
        manifest = json.loads(
            storage.read_text(os.path.join(sdir, marker))
        )
        shards = dict(manifest.get("shards", {}))
    except (ValueError, OSError, TypeError):
        return StepVerdict(fail_kind="corrupt_commit")
    if len(shards) < int(manifest.get("num_shards", num_shards)):
        return StepVerdict(fail_kind="incomplete_manifest")
    bad_pieces: dict[str, set | None] = {}
    fail_kinds: dict[str, str] = {}
    for nid, entry in shards.items():
        entry = entry or {}
        bin_path = os.path.join(sdir, f"node_{nid}.bin")
        meta_path = os.path.join(sdir, f"node_{nid}.meta.json")
        if not storage.exists(bin_path) or not storage.exists(meta_path):
            bad_pieces[nid] = None
            fail_kinds[nid] = "missing_shard"
            continue
        want = entry.get("crc32")
        if want is None:
            continue  # mixed-version writer: nothing to check against
        try:
            crc, size = _shard_crc(storage, bin_path)
        except (OSError, FileNotFoundError):
            bad_pieces[nid] = None
            fail_kinds[nid] = "missing_shard"
            continue
        want_bytes = entry.get("bytes")
        if want_bytes is not None and size != int(want_bytes):
            fail_kinds[nid] = "truncated_shard"
        elif crc != int(want):
            fail_kinds[nid] = "crc_mismatch"
        else:
            continue
        # whole-file damage: per-piece CRCs decide WHICH pieces died
        # (read-side bit flips are transient — the range re-read can
        # verify clean even though the streaming pass did not)
        pieces = dict(entry.get("pieces") or {})
        bad = (_verify_writer_pieces(storage, sdir, nid, pieces)
               if pieces else None)
        if bad == set():
            # every piece individually verifies on re-read: the damage
            # was transient (or outside any piece's bytes); keep the
            # writer but note the anomaly
            logger.warning(
                "shard node_%s in %s failed the whole-file CRC but "
                "every piece verifies on ranged re-read; keeping it",
                nid, sdir,
            )
            continue
        bad_pieces[nid] = bad
    if not bad_pieces:
        return StepVerdict()
    # quorum: every piece listed by a BAD writer must verify somewhere
    # else. Build piece -> surviving-holder coverage over good writers
    # (and the undamaged pieces of partially-bad writers).
    held: dict[tuple, int] = {}
    legacy_bad = False
    for nid, entry in shards.items():
        pieces = dict((entry or {}).get("pieces") or {})
        if nid in bad_pieces and not pieces:
            legacy_bad = True  # pre-piece writer: no coverage algebra
            continue
        bad = bad_pieces.get(nid, set())
        for key, pentry in pieces.items():
            if bad is None or key in bad:
                continue
            held[_piece_key(pentry)] = held.get(_piece_key(pentry), 0) + 1
    if legacy_bad:
        worst = next(iter(fail_kinds.values()), "crc_mismatch")
        return StepVerdict(fail_kind=worst, bad_pieces=bad_pieces)
    rollbacks: list[dict] = []
    for nid, bad in bad_pieces.items():
        pieces = dict((shards.get(nid) or {}).get("pieces") or {})
        lost = [key for key, pentry in pieces.items()
                if (bad is None or key in bad)
                and held.get(_piece_key(pentry), 0) == 0]
        if lost:
            return StepVerdict(
                fail_kind=fail_kinds.get(nid, "crc_mismatch"),
                bad_pieces=bad_pieces,
            )
        rollbacks.append({
            "writer": nid,
            "kind": fail_kinds.get(nid, "crc_mismatch"),
            "pieces": len(pieces) if bad is None else len(bad),
        })
    return StepVerdict(bad_pieces=bad_pieces, rollbacks=rollbacks)


def verify_step_dir(storage, sdir: str, num_shards: int) -> str | None:
    """None when the step verifies (possibly via per-shard twin
    rollback); else a short failure kind."""
    return verify_step_quorum(storage, sdir, num_shards).fail_kind


def _dir_worlds(files: list[str]) -> list[int]:
    """Candidate writer world sizes recorded in a step dir's markers."""
    worlds = set()
    for f in files:
        m = _COMMIT_RE.match(f) or _DONE_RE.match(f)
        if m:
            worlds.add(int(m.group(m.lastindex)))
    return sorted(worlds, reverse=True)


def _reject(step: int, kind: str) -> None:
    _verify_failed_total.labels(kind).inc()
    get_journal().emit("ckpt_verify_failed", step=step, kind=kind)
    logger.error("checkpoint step %d failed verification: %s", step, kind)


@dataclasses.dataclass
class RestorePlan:
    """The newest verified step PLUS which shard files to avoid: the
    restore registry must not read pieces a per-shard rollback proved
    corrupt (their replica twins serve those slices instead)."""

    step: int
    num_shards: int
    bad_pieces: dict[str, set | None] = dataclasses.field(
        default_factory=dict)


def resolve_restore_plan(storage, ckpt_dir: str) -> RestorePlan | None:
    """The newest VERIFIED restore plan (quorum semantics).

    Starts at the tracker's step; if that step fails verification (or
    the tracker itself is torn), walks the step directories newest
    first and returns the first that verifies, journaling the
    rollback. A step that verifies only via replica twins journals
    ``ckpt_shard_rollback`` per recovered shard. Returns None when
    nothing restorable exists — the caller starts fresh, which beats
    silently installing corrupt weights.
    """
    from dlrover_tpu.agent.ckpt_saver import read_tracker, step_dir

    tracked: tuple[int, int] | None = None
    try:
        tracked = read_tracker(storage, ckpt_dir)
    except (ValueError, OSError):
        _reject(-1, "corrupt_tracker")
    steps = []
    for name in storage.listdir(ckpt_dir):
        m = STEP_DIR_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    steps.sort(reverse=True)

    checked: set[int] = set()
    candidates: list[tuple[int, int | None]] = []
    if tracked is not None:
        candidates.append(tracked)
    candidates.extend((s, None) for s in steps)
    for step, num_shards in candidates:
        if step in checked:
            continue
        checked.add(step)
        sdir = step_dir(ckpt_dir, step)
        if not storage.exists(sdir):
            _reject(step, "missing_dir")
            continue
        worlds = ([num_shards] if num_shards
                  else _dir_worlds(storage.listdir(sdir)))
        fail_kind = "unverifiable"
        for world in worlds:
            verdict = verify_step_quorum(storage, sdir, world)
            if verdict.ok:
                for rb in verdict.rollbacks:
                    _shard_rollback_total.inc()
                    get_journal().emit(
                        "ckpt_shard_rollback", step=step,
                        writer=str(rb["writer"]), kind=rb["kind"],
                        pieces=rb["pieces"],
                    )
                    logger.warning(
                        "per-shard rollback at step %d: shard node_%s "
                        "failed (%s); its %d piece(s) restore from "
                        "replica twins", step, rb["writer"], rb["kind"],
                        rb["pieces"],
                    )
                if tracked is not None and step != tracked[0]:
                    _rollback_total.inc()
                    get_journal().emit("ckpt_rollback",
                                       from_step=tracked[0], to_step=step)
                    logger.warning(
                        "rolling back restore: step %d failed "
                        "verification, using newest verified step %d",
                        tracked[0], step,
                    )
                return RestorePlan(step=step, num_shards=world,
                                   bad_pieces=verdict.bad_pieces)
            fail_kind = verdict.fail_kind
        _reject(step, fail_kind)
    return None


def resolve_restore_step(storage, ckpt_dir: str
                         ) -> tuple[int, int] | None:
    """(step, num_shards) view of ``resolve_restore_plan`` — the
    compatibility surface for callers that restore whole node files
    (the replicated engine path)."""
    plan = resolve_restore_plan(storage, ckpt_dir)
    return None if plan is None else (plan.step, plan.num_shards)
