"""Orbax interop: flash checkpoints <-> the JAX ecosystem's format.

Reference analog: the reference writes Megatron/DeepSpeed-compatible
tracker files so its flash checkpoints interoperate with those stacks
(ckpt_saver.py:1119-1157 MegatronCheckpointSaver/DeepSpeedCheckpointSaver).
The JAX ecosystem's lingua franca is Orbax: these converters let a flash
checkpoint (fast elastic save/restore path) be exported for consumers
expecting Orbax (eval harnesses, serving, other trainers), and let an
Orbax checkpoint seed a flash-checkpointed elastic run.
"""

from __future__ import annotations

import os
from typing import Any

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def save_orbax(path: str, state: Any) -> None:
    """Write a pytree as an Orbax checkpoint (blocking)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state, force=True)
    ckptr.wait_until_finished()


def load_orbax(path: str, template: Any | None = None,
               shardings: Any | None = None) -> Any:
    """Restore an Orbax checkpoint, optionally onto target shardings."""
    import jax
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if template is None:
        return ckptr.restore(os.path.abspath(path))
    if shardings is not None:
        abstract = jax.tree.map(
            lambda leaf, s: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=s
            ),
            template, shardings,
        )
    else:
        abstract = jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
            template,
        )
    return ckptr.restore(os.path.abspath(path), abstract)


def export_flash_to_orbax(engine, template: Any, out_path: str,
                          shardings: Any | None = None) -> int:
    """Materialize the engine's newest checkpoint as Orbax.

    Works for both the replicated engine (``load``) and the sharded one
    (``load_sharded``). Returns the exported step.
    """
    if hasattr(engine, "load_sharded") and shardings is not None:
        loaded = engine.load_sharded(template, shardings)
    else:
        loaded = engine.load(template)
    if loaded is None:
        raise FileNotFoundError("engine has no checkpoint to export")
    step, state = loaded
    save_orbax(out_path, state)
    logger.info("exported flash checkpoint step %d to orbax %s",
                step, out_path)
    return step


def import_orbax_to_flash(engine, orbax_path: str, step: int,
                          template: Any | None = None,
                          persist: bool = True) -> None:
    """Seed the flash-checkpoint pipeline from an Orbax checkpoint: the
    elastic run then restores it via the normal shm/storage paths."""
    state = load_orbax(orbax_path, template)
    if persist:
        engine.save_to_storage(step, state)
        if not engine.wait_for_persist(step, timeout=300):
            raise TimeoutError(
                f"imported checkpoint (step {step}) was not committed to "
                "storage within 300s — the elastic run would silently "
                "start from scratch on a restart"
            )
    else:
        engine.save_to_memory(step, state)
    logger.info("imported orbax %s as flash checkpoint step %d",
                orbax_path, step)
