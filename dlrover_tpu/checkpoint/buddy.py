"""Buddy-host replication of shm checkpoint snapshots.

Round-2 verdict Missing #4 / SURVEY §7 hard-parts: the shm snapshot
(shm_handler.py) survives *process* death, but TPU preemption takes the
whole host VM — and with it the arena. The reference's restart-in-place
(dlrover/python/elastic_agent/torch/ckpt_saver.py:313) has the same
blind spot; its answer is the storage fallback, which blows the <10s
restore budget. Here every node's agent streams each new snapshot to a
buddy node over DCN; a relaunched node whose shm is gone pulls its
snapshot back from the buddy BEFORE spawning the trainer, so the
trainer's normal restore-from-shm path works unchanged and storage is
only the last resort.

Pairing is a ring over the alive nodes (assigned by the master,
master/servicer.py BuddyQueryRequest): node i pushes to — and after a
relaunch fetches from — the next alive node after i.

Wire protocol (length-delimited, binary-clean — snapshots are hundreds
of MB, so no JSON-wrapped payloads):

    request:  <json line: {"op": "push"|"get", "source": id,
               ["header": {...}, "nbytes": N]}>\\n [N raw bytes]
    response: <json line: {"ok": bool, ["header": ..., "nbytes": N]}>\\n
              [N raw bytes]
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

_MAX_HEADER = 64 * 1024 * 1024  # json lines; snapshots metas can be large


def _max_snapshot_bytes() -> int:
    """Upper bound on one pushed snapshot (refuses runaway/malicious
    nbytes before buffering; a TPU host's training state tops out near
    its host RAM)."""
    return envspec.get_int(EnvKey.BUDDY_MAX_BYTES)


def _read_line(rfile) -> bytes:
    line = rfile.readline(_MAX_HEADER)
    if not line.endswith(b"\n"):
        raise ConnectionError("truncated control line")
    return line


def _read_exact(rfile, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = rfile.read(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-payload ({remaining} bytes short)"
            )
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


class BuddyServer:
    """Agent-side receiver holding peers' snapshots in host memory.

    One slot per source node (latest wins) and at most ``max_sources``
    peers (oldest-pushed evicted): a node is ring-buddy for one peer at
    a time, so anything beyond the reassignment-overlap allowance is a
    stale copy no relaunch can legitimately fetch — it must not pin
    host RAM the trainer needs.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_sources: int = 2):
        self._store: dict[int, tuple[dict, bytes]] = {}
        self._max_sources = max_sources
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    req = json.loads(_read_line(self.rfile))
                    if req["op"] == "push":
                        nbytes = int(req["nbytes"])
                        if nbytes < 0 or nbytes > _max_snapshot_bytes():
                            self.wfile.write(b'{"ok": false}\n')
                            return
                        payload = _read_exact(self.rfile, nbytes)
                        with outer._lock:
                            # dict preserves insertion order: re-insert
                            # so eviction drops the least-recent pusher
                            outer._store.pop(int(req["source"]), None)
                            outer._store[int(req["source"])] = (
                                req["header"], payload
                            )
                            while len(outer._store) > outer._max_sources:
                                evicted = next(iter(outer._store))
                                outer._store.pop(evicted)
                                logger.info(
                                    "evicted stale snapshot of node %d",
                                    evicted,
                                )
                        self.wfile.write(b'{"ok": true}\n')
                    elif req["op"] == "get":
                        with outer._lock:
                            entry = outer._store.get(int(req["source"]))
                        if entry is None:
                            self.wfile.write(b'{"ok": false}\n')
                            return
                        header, payload = entry
                        self.wfile.write(json.dumps({
                            "ok": True, "header": header,
                            "nbytes": len(payload),
                        }).encode() + b"\n")
                        self.wfile.write(payload)
                    else:
                        self.wfile.write(b'{"ok": false}\n')
                except (ConnectionError, json.JSONDecodeError,
                        KeyError, ValueError) as e:
                    logger.warning("buddy request failed: %s", e)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.addr = (
            f"{self._server.server_address[0]}:"
            f"{self._server.server_address[1]}"
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="buddy-server",
            daemon=True,
        )

    def start(self) -> "BuddyServer":
        self._thread.start()
        logger.info("buddy server on %s", self.addr)
        return self

    def holds(self, source: int) -> int | None:
        """Step of the held snapshot for ``source`` (None when absent)."""
        with self._lock:
            entry = self._store.get(source)
        return int(entry[0].get("step", -1)) if entry else None

    def drop(self, source: int) -> None:
        with self._lock:
            self._store.pop(source, None)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _connect(addr: str, timeout_s: float) -> socket.socket:
    host, _, port = addr.rpartition(":")
    return socket.create_connection((host, int(port)), timeout=timeout_s)


def push_snapshot(addr: str, source: int, header: dict, payload: bytes,
                  timeout_s: float = 60.0) -> bool:
    """Stream one snapshot to the buddy at ``addr``. False on any error
    (replication is best-effort; the next snapshot retries)."""
    try:
        with _connect(addr, timeout_s) as sock:
            wfile = sock.makefile("wb")
            rfile = sock.makefile("rb")
            wfile.write(json.dumps({
                "op": "push", "source": source, "header": header,
                "nbytes": len(payload),
            }).encode() + b"\n")
            wfile.write(payload)
            wfile.flush()
            resp = json.loads(_read_line(rfile))
            return bool(resp.get("ok"))
    except (OSError, json.JSONDecodeError, ConnectionError) as e:
        logger.warning("snapshot push to %s failed: %s", addr, e)
        return False


def fetch_snapshot(addr: str, source: int, timeout_s: float = 60.0
                   ) -> tuple[dict, bytes] | None:
    """Pull ``source``'s snapshot back from the buddy at ``addr``."""
    try:
        with _connect(addr, timeout_s) as sock:
            wfile = sock.makefile("wb")
            rfile = sock.makefile("rb")
            wfile.write(json.dumps(
                {"op": "get", "source": source}
            ).encode() + b"\n")
            wfile.flush()
            resp = json.loads(_read_line(rfile))
            if not resp.get("ok"):
                return None
            payload = _read_exact(rfile, int(resp["nbytes"]))
            return resp["header"], payload
    except (OSError, json.JSONDecodeError, ConnectionError) as e:
        logger.warning("snapshot fetch from %s failed: %s", addr, e)
        return None


class BuddyReplicator:
    """Agent thread: pushes every new shm snapshot to the master-assigned
    buddy. Polls the shm header (cheap meta-dict read) instead of hooking
    the trainer, so replication needs zero trainer changes."""

    def __init__(self, shm_handler, master_client,
                 interval_s: float = 2.0):
        self._shm = shm_handler
        self._client = master_client
        self._interval_s = interval_s
        # (step, buddy ADDR) of the last successful push: a ring
        # reassignment — or the same buddy node relaunching with a fresh
        # empty server (new port) — must re-push the CURRENT snapshot,
        # or the node is unprotected until its next snapshot
        self._last_pushed: tuple[int, str] = (-1, "")
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="buddy-replicator", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def replicate_once(self) -> bool:
        """One replication attempt; True when a push happened and
        succeeded."""
        header = self._shm.header()
        if not header:
            return False
        step = int(header.get("step", -1))
        buddy = self._client.query_buddy()
        if not buddy.found:
            return False
        last_step, last_addr = self._last_pushed
        if buddy.addr == last_addr and step <= last_step:
            return False  # same server already holds this (or newer) step
        # bounded lock hold: read header+bytes consistently, then push
        # OUTSIDE the lock (a slow DCN push must not block the trainer's
        # next snapshot)
        if not self._shm.lock.acquire(timeout=10.0):
            return False
        try:
            raw = self._shm.read_raw()
            if raw is None:
                return False
            header, buf = raw
            payload = bytes(buf[: int(header["total_size"])])
        finally:
            self._shm.lock.release()
        step = int(header["step"])
        if push_snapshot(buddy.addr, self._shm.node_id, header, payload):
            self._last_pushed = (step, buddy.addr)
            logger.info("replicated snapshot step %d to buddy node %d "
                        "(%s)", step, buddy.buddy_node_id, buddy.addr)
            return True
        return False

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.replicate_once()
            except Exception:  # noqa: BLE001 - replication is best-effort
                logger.exception("buddy replication failed")
