"""AOT strategy report: compile a training step for a virtual mesh and
print per-device memory/FLOPs/collective volume — no chips needed.

Reference analog: ATorch's dry-runner/analyser sizing a strategy before
committing cluster resources (atorch/auto/analyser/analyser.py:14). XLA
gives the numbers ahead-of-time: ``jit(...).lower().compile()`` yields
memory_analysis()/cost_analysis() for the target program, so a Llama-7B
FSDP plan for a v5p-128 pod can be validated on a laptop.

Usage (the launcher must point JAX at a virtual mesh BEFORE python
starts, e.g.):

    JAX_PLATFORMS=cpu \\
    XLA_FLAGS=--xla_force_host_platform_device_count=128 \\
    python -m dlrover_tpu.parallel.aot_report \\
        --model llama2-7b --strategy fsdp --batch 128 --seq 4096

Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dlrover-tpu aot-report")
    p.add_argument("--model", default="llama2-7b")
    p.add_argument("--strategy", default="fsdp",
                   help="preset name (parallel/strategy.py PRESETS)")
    p.add_argument("--batch", type=int, default=128,
                   help="global batch size")
    p.add_argument("--seq", type=int, default=4096)
    p.add_argument("--remat", default="dots_no_batch")
    p.add_argument("--attention", default="")
    args = p.parse_args(argv)

    import os

    import jax

    from dlrover_tpu.common.constants import EnvKey

    # an eagerly-registered TPU plugin beats the JAX_PLATFORMS env var;
    # the live config does not (same trick as trainer/bootstrap.py)
    platform = os.environ.get(EnvKey.PLATFORM)
    if platform:
        jax.config.update("jax_platforms", platform)
    import numpy as np
    import optax

    from dlrover_tpu.models import transformer as tfm
    from dlrover_tpu.parallel.dry_run import dry_run
    from dlrover_tpu.parallel.strategy import PRESETS
    from dlrover_tpu.trainer.train_step import compile_train

    cfg = tfm.CONFIGS[args.model]
    replace = {"max_seq_len": args.seq}
    if args.remat:
        replace.update(remat_scan=True, remat_policy=args.remat)
    if args.attention:
        replace["attention"] = args.attention
    cfg = dataclasses.replace(cfg, **replace)
    devices = jax.devices()
    strategy = PRESETS[args.strategy]()

    # ONE compiled program feeds both the analytic sizing and the AOT
    # dry-run — two builds would inevitably drift apart
    mesh = strategy.build_mesh(devices)
    compiled = compile_train(
        strategy=strategy, mesh=mesh,
        loss_fn=tfm.make_loss_fn(cfg, strategy, mesh),
        init_params_fn=lambda rng: tfm.init_params(cfg, rng),
        logical_params=tfm.logical_axes(cfg),
        optimizer=optax.adamw(1e-4),
    )
    state_abs = jax.eval_shape(compiled.init, jax.random.PRNGKey(0))

    # analytic per-device train-state footprint straight from the
    # shardings (XLA's memory_analysis on the CPU backend reports
    # global, not per-device, sizes — misleading for pod sizing)
    state_bytes = 0
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(state_abs),
        jax.tree_util.tree_leaves(
            compiled.state_shardings,
            is_leaf=lambda x: hasattr(x, "shard_shape"),
        ),
    ):
        shard = sh.shard_shape(leaf.shape)
        n = 1
        for d in shard:
            n *= d
        state_bytes += n * leaf.dtype.itemsize

    def build_step(_strat):
        state_abstract = jax.tree.map(
            lambda leaf, s: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=s
            ),
            state_abs, compiled.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
        batch_abstract = {
            "tokens": jax.ShapeDtypeStruct(
                (1, args.batch, args.seq + 1), np.int32,
                sharding=compiled.batch_sharding,
            )
        }
        return compiled.step, (state_abstract, batch_abstract)

    t0 = time.monotonic()
    report = dry_run(build_step, strategy)
    line = {
        "model": args.model,
        "strategy": report.strategy_name,
        "devices": len(devices),
        "params": cfg.param_count,
        "batch": args.batch,
        "seq": args.seq,
        "ok": report.ok,
        "error": report.error[:300],
        "state_gb_per_device": round(state_bytes / 2**30, 3),
        # global-view XLA numbers (CPU backend); flops undercounts scan
        # bodies — recorded for cross-round tracking, not for sizing
        "xla_memory_analysis_gb": round(report.hbm_bytes / 2**30, 2),
        "xla_flops": report.flops,
        "comm_bytes": report.comm_bytes,
        "compile_s": round(time.monotonic() - t0, 1),
    }
    # analytic per-op-class FLOPs from the jaxpr (scan-aware, unlike
    # XLA's cost analysis above) — the Analyser's params/flops/memory
    # triple, completing the per-device sizing with true model FLOPs
    try:
        from dlrover_tpu.utils.profiler import flops_breakdown

        # reuse the already-traced state shapes (one build feeds all
        # numbers, per the design note above) rather than re-tracing
        # init, and RESOLVE the config so strategy extras that change
        # the model (attention kind/window, int8, pipeline shape) are
        # the ones counted — resolve_config's documented contract
        params_abs = state_abs.params
        rcfg = tfm.resolve_config(cfg, strategy)
        tokens = jax.ShapeDtypeStruct(
            (args.batch, args.seq + 1), np.int32
        )
        bd = flops_breakdown(
            lambda p, b: tfm.loss_fn(p, b, cfg=rcfg),
            params_abs, {"tokens": tokens},
        )
        line["analytic_fwd_flops"] = bd.get("total", 0.0)
        line["analytic_fwd_matmul_flops"] = bd.get("dot_general", 0.0)
    except Exception as e:  # noqa: BLE001 - sizing must still print
        line["analytic_fwd_flops_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(line))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
