"""Strategy engine as a service: centralized parallel-strategy search.

Reference analog: atorch's AccelerationEngine gRPC service
(atorch/atorch/auto/engine/acceleration_engine.py:13 + servicer/client
over protos/acceleration.proto) — strategy search runs as a service so
expensive tuning is shared across jobs and the trainer only applies the
result. TPU-first shape: the "search" is parallel/auto.py's AOT dry-run
+ roofline ranking, which needs no chips — the service compiles against
a VIRTUAL mesh of the requested size. Because the forced host device
count must be set before the JAX backend initializes, each proposal
runs in a short-lived subprocess (the same trick as bench.py's 7B AOT
report); results are cached per (model, n_devices, batch, seq).

Measured history outranks the model: trainers report real step times
via :class:`~dlrover_tpu.common.messages.StrategyMeasurement`, and the
fastest measured strategy for a key wins later proposals outright —
the engine learns what the roofline can only estimate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any

from dlrover_tpu.autopilot.history import (
    canonical_strategy_json,
    shape_key,
)
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import RpcClient, RpcServer

logger = get_logger(__name__)

_PROPOSE_TIMEOUT_S = 540.0
# client RPC timeout must exceed the subprocess budget or the client
# gives up (and retries, queuing behind the in-flight gate) while the
# search is still legitimately running
_CLIENT_TIMEOUT_S = 600.0
# the search subprocess always runs on the CPU backend, where
# device_hbm_bytes() reports 0 and the fit check would silently be
# skipped — a "found" strategy that never passed any memory check could
# OOM real chips. When the request doesn't carry a budget, assume a
# conservative TPU one (matches device_hbm_bytes' 16 GiB TPU fallback,
# parallel/auto.py:46) and say so in the proposal report.
_DEFAULT_HBM_GB = 16.0


def _search_subprocess(req: m.StrategyProposeRequest) -> dict:
    """Run auto_strategy on a virtual CPU mesh in a child process."""
    payload = {
        "model": req.model,
        "n_devices": req.n_devices,
        "batch": req.batch,
        "seq": req.seq,
        "objective": req.objective,
        "hbm_gb": req.hbm_gb,
    }
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={req.n_devices}"
    ).strip()
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            env.get("PYTHONPATH", "")] if p
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "dlrover_tpu.parallel.engine_service",
             json.dumps(payload)],
            capture_output=True, text=True, timeout=_PROPOSE_TIMEOUT_S,
            env=env,
        )
    except subprocess.TimeoutExpired:
        # transient (host load, cold compile cache) — must not poison
        # the negative cache
        return {"error": f"search exceeded {_PROPOSE_TIMEOUT_S}s",
                "transient": True}
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        return json.loads(line)
    except json.JSONDecodeError:
        return {"error": (proc.stderr or line)[-800:]}


class StrategyEngineService:
    """RPC service: propose strategies, absorb measurements."""

    def __init__(self, port: int = 0, db_path: str = ""):
        self._server = RpcServer(self.handle, port=port)
        self._lock = threading.Lock()
        self._cache: dict[tuple, m.StrategyProposal] = {}
        # key -> (step_time_s, strategy_json)
        self._measured: dict[tuple, tuple[float, str]] = {}
        # every reported measurement per shape key (the persisted
        # surrogate posterior; see parallel/surrogate.py)
        self._observations: dict[tuple, list[dict]] = {}
        # per-key in-flight search locks: N jobs asking at once must
        # run ONE subprocess, not N (the point of a shared engine)
        self._inflight: dict[tuple, threading.Lock] = {}
        # cross-job, cross-restart persistence (the Brain-datastore
        # pattern, reference go/brain/pkg/datastore/): job B's measured
        # search warm-starts from what job A reported even after the
        # engine restarts
        self._db = None
        if db_path:
            import sqlite3

            if db_path != ":memory:":
                os.makedirs(os.path.dirname(db_path) or ".",
                            exist_ok=True)
            self._db = sqlite3.connect(db_path,
                                       check_same_thread=False)
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS strategy_obs ("
                " model TEXT, n_devices INT, batch INT, seq INT,"
                " hbm_gb REAL, strategy_json TEXT, step_time_s REAL,"
                " timestamp REAL, mfu REAL DEFAULT 0,"
                " PRIMARY KEY (model, n_devices, batch, seq, hbm_gb,"
                "              strategy_json))"
            )
            try:
                # dbs written before the autopilot's (step_s, MFU)
                # pairs gain the column in place
                self._db.execute(
                    "ALTER TABLE strategy_obs"
                    " ADD COLUMN mfu REAL DEFAULT 0"
                )
            except sqlite3.OperationalError:
                pass  # column already present
            self._db.commit()
            for row in self._db.execute(
                "SELECT model, n_devices, batch, seq, hbm_gb,"
                " strategy_json, step_time_s, mfu FROM strategy_obs"
                " ORDER BY timestamp"
            ):
                key = shape_key(row[0], row[1], row[2], row[3], row[4])
                self._observations.setdefault(key, []).append(
                    {"strategy_json": row[5], "step_time_s": row[6],
                     "mfu": row[7] or 0.0}
                )
                best = self._measured.get(key)
                if best is None or row[6] < best[0]:
                    self._measured[key] = (row[6], row[5])
            # the same per-key bound the report path enforces: a
            # long-lived db must not balloon memory or RPC payloads
            for obs in self._observations.values():
                del obs[:-256]
            if self._measured:
                logger.info(
                    "engine warm-started from %s: %d shape keys, %d "
                    "observations", db_path, len(self._measured),
                    sum(len(v) for v in self._observations.values()),
                )

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self._server.port}"

    def start(self) -> "StrategyEngineService":
        self._server.start()
        self._started = True
        logger.info("strategy engine serving on %s", self.addr)
        return self

    def stop(self) -> None:
        # an in-process (never-started) engine — the autopilot's
        # PlanHistory db backend — must not call the socketserver's
        # shutdown(): BaseServer.shutdown blocks on an event only
        # serve_forever ever sets
        if getattr(self, "_started", False):
            self._server.stop()
            self._started = False
        if self._db is not None:
            with self._lock:
                self._db.close()
                self._db = None

    def handle(self, msg: Any) -> Any:
        if isinstance(msg, m.StrategyMeasurement):
            # reject garbage before it can be replayed to later clients
            # as a found=True proposal that breaks at Strategy.from_json
            from dlrover_tpu.parallel.strategy import Strategy

            Strategy.from_json(msg.strategy_json)
            # ONE fingerprint vocabulary (autopilot/history.py): the
            # stored key is shape_key and the per-plan identity is the
            # canonical JSON — a search winner and an autopilot lookup
            # can never miss each other over formatting
            sj = canonical_strategy_json(msg.strategy_json)
            key = shape_key(msg.model, msg.n_devices, msg.batch,
                            msg.seq, msg.hbm_gb)
            with self._lock:
                best = self._measured.get(key)
                if best is None or msg.step_time_s < best[0]:
                    self._measured[key] = (msg.step_time_s, sj)
                    logger.info(
                        "measured best for %s: %.4fs", key, msg.step_time_s
                    )
                # full observation log (bounded): the persisted
                # posterior for surrogate warm-starts — dedup by
                # strategy, keeping the newest measurement
                obs = self._observations.setdefault(key, [])
                obs[:] = [o for o in obs
                          if canonical_strategy_json(o["strategy_json"])
                          != sj]
                obs.append({"strategy_json": sj,
                            "step_time_s": msg.step_time_s,
                            "mfu": msg.mfu or 0.0})
                del obs[:-256]
                if self._db is not None:
                    self._db.execute(
                        "INSERT OR REPLACE INTO strategy_obs"
                        " (model, n_devices, batch, seq, hbm_gb,"
                        "  strategy_json, step_time_s, timestamp, mfu)"
                        " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
                        (*key, sj, msg.step_time_s, time.time(),
                         msg.mfu or 0.0),
                    )
                    self._db.commit()
            return m.OkResponse()
        if isinstance(msg, m.StrategyObservationsRequest):
            key = shape_key(msg.model, msg.n_devices, msg.batch,
                            msg.seq, msg.hbm_gb)
            with self._lock:
                return m.StrategyObservations(
                    observations=list(self._observations.get(key, []))
                )
        if isinstance(msg, m.StrategyProposeRequest):
            return self.propose(msg)
        raise TypeError(f"unhandled message type {type(msg).__name__}")

    def propose(self, req: m.StrategyProposeRequest) -> m.StrategyProposal:
        # measured history only applies at the exact shape — at any
        # other batch/seq the strategy hasn't passed a fit check — and
        # only for the "fastest" objective: a measured-fastest pick is
        # exactly what "fastest" asks for, but e.g. "first_fit" callers
        # want preference order, not speed
        measured_key = shape_key(req.model, req.n_devices, req.batch,
                                 req.seq, req.hbm_gb)
        measured = None
        if req.objective == "fastest":
            with self._lock:
                measured = self._measured.get(measured_key)
        if measured is not None:
            return m.StrategyProposal(
                found=True, strategy_json=measured[1], source="measured",
                report={"measured_step_time_s": measured[0]},
            )
        cache_key = (req.model, req.n_devices, req.batch, req.seq,
                     req.objective, req.hbm_gb)
        with self._lock:
            cached = self._cache.get(cache_key)
            if cached is not None:
                return cached
            gate = self._inflight.setdefault(cache_key, threading.Lock())
        with gate:  # followers wait here while the first search runs
            with self._lock:
                cached = self._cache.get(cache_key)
            if cached is not None:
                return cached
            result = _search_subprocess(req)
            if "error" in result:
                proposal = m.StrategyProposal(
                    found=False, error=result["error"]
                )
            else:
                proposal = m.StrategyProposal(
                    found=True,
                    strategy_json=result["strategy_json"],
                    source="dry_run",
                    report=result.get("report", {}),
                )
            # deterministic negatives cache too (a broken model spec
            # must not re-spawn subprocesses per retry); transient
            # failures like timeouts stay uncached so a later propose
            # retries on a quieter host
            if not result.get("transient"):
                with self._lock:
                    self._cache[cache_key] = proposal
            return proposal


class StrategyEngineClient:
    """Trainer/master side of the engine."""

    def __init__(self, addr: str, timeout: float = _CLIENT_TIMEOUT_S):
        self._rpc = RpcClient(addr, timeout=timeout)

    def propose(self, model: str, n_devices: int, *, batch: int = 8,
                seq: int = 128, objective: str = "fastest",
                hbm_gb: float = 0.0) -> m.StrategyProposal:
        return self._rpc.call(m.StrategyProposeRequest(
            model=model, n_devices=n_devices, batch=batch, seq=seq,
            objective=objective, hbm_gb=hbm_gb,
        ))

    def report_measurement(self, model: str, n_devices: int,
                           strategy, step_time_s: float, *,
                           batch: int = 8, seq: int = 128,
                           hbm_gb: float = 0.0,
                           mfu: float = 0.0) -> None:
        # canonical on the wire too, so a service log/db is greppable
        # under the one vocabulary (autopilot/history.py); malformed
        # input passes through raw so the SERVICE stays the one place
        # that rejects it (its Strategy.from_json guard + RpcError)
        try:
            sj = canonical_strategy_json(strategy)
        except (ValueError, TypeError):
            sj = strategy if isinstance(strategy, str) \
                else strategy.to_json()
        self._rpc.call(m.StrategyMeasurement(
            model=model, n_devices=n_devices, batch=batch, seq=seq,
            hbm_gb=hbm_gb, strategy_json=sj, step_time_s=step_time_s,
            mfu=mfu,
        ))

    def get_observations(self, model: str, n_devices: int, *,
                         batch: int = 8, seq: int = 128,
                         hbm_gb: float = 0.0) -> list[dict]:
        """The shape key's full measurement log ([{strategy_json,
        step_time_s}]) — warm-start material for a surrogate fit."""
        resp = self._rpc.call(m.StrategyObservationsRequest(
            model=model, n_devices=n_devices, batch=batch, seq=seq,
            hbm_gb=hbm_gb,
        ))
        return list(resp.observations)

    def close(self) -> None:
        self._rpc.close()


def _main() -> None:
    """Subprocess entry: run the search on the virtual mesh and print
    one JSON line (stdout contract with :func:`_search_subprocess`)."""
    from functools import partial

    import jax
    import numpy as np
    import optax

    from dlrover_tpu.models import transformer as tfm
    from dlrover_tpu.parallel.auto import auto_strategy

    spec = json.loads(sys.argv[1])
    cfg = tfm.CONFIGS[spec["model"]]
    seq = min(cfg.max_seq_len, int(spec["seq"]))
    batch = int(spec["batch"])
    tokens = np.zeros((1, batch, seq + 1), dtype=np.int32)
    hbm_assumed = not spec.get("hbm_gb")
    hbm = int((spec["hbm_gb"] if not hbm_assumed else _DEFAULT_HBM_GB)
              * 2**30)
    strategy, reports = auto_strategy(
        loss_fn_for=lambda s, mesh: tfm.make_loss_fn(cfg, s, mesh),
        init_params_fn=partial(tfm.init_params, cfg),
        logical_params=tfm.logical_axes(cfg),
        optimizer=optax.adamw(1e-3),
        example_batch={"tokens": tokens},
        devices=jax.devices()[:spec["n_devices"]],
        objective=spec.get("objective", "fastest"),
        hbm_capacity_bytes=hbm,
    )
    import dataclasses as dc

    report = {}
    for r in reports:  # DryRunReport dataclasses
        if getattr(r, "strategy_name", None) == strategy.name:
            report = {
                k: v for k, v in dc.asdict(r).items()
                if isinstance(v, (int, float, str, bool))
            }
            break
    if hbm_assumed:
        report["hbm_assumed_gb"] = _DEFAULT_HBM_GB
    print(json.dumps({
        "strategy_json": strategy.to_json(),
        "report": report,
    }))


if __name__ == "__main__":
    _main()
