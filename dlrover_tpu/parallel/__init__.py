from dlrover_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    batch_axes,
    data_parallel_size,
)
from dlrover_tpu.parallel.partition import (  # noqa: F401
    constrain,
    spec_for,
    tree_shardings,
    tree_specs,
)
from dlrover_tpu.parallel.strategy import PRESETS, Strategy  # noqa: F401
from dlrover_tpu.parallel.dry_run import dry_run, pick_strategy  # noqa: F401
