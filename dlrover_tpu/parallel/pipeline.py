"""Pipeline parallelism, pure-SPMD: GPipe + interleaved schedules over a
"pipeline" mesh axis.

Reference analog: ATorch's PiPPy-based pipeline stage split
(atorch/atorch/auto/opt_lib/pipeline_parallel_optimization.py:56) and the
DeepSpeed 3D combination (ds_3d_parallel_optimization.py:55). Those carve the
module graph into per-rank subgraphs driven by an RPC scheduler; on TPU the
idiomatic form keeps ONE jitted SPMD program: the stacked layer dim is
sharded over the "pipeline" mesh axis, each stage's compute is a ``vmap``
over the stage dim, and the stage-to-stage handoff is a ``jnp.roll`` on the
sharded dim which XLA lowers to a collective-permute over ICI. Microbatches
flow through the classic GPipe schedule (M + P - 1 steps, bubble fraction
(P-1)/(M+P-1)); reverse-mode AD of the rolled scan yields the backward
pipeline automatically.

``interleave=v > 1`` runs the Megatron-style interleaved (circular)
schedule instead — the 1F1B-class bubble reduction of the reference's
PiPPy schedules (pipeline_parallel_optimization.py:56), in SPMD-roll
form: each stage holds ``v`` non-contiguous layer chunks and every
microbatch circulates through the stage ring ``v`` times, so per-step
stage work shrinks v-fold while the (P-1)-step fill/drain cost is paid
once. Bubble fraction per direction drops from (P-1)/(M+P-1) to
(P-1)/(vM+P-1); reverse-mode AD mirrors the same schedule for the
backward, halving the total bubble exactly as 1F1B-interleaved does —
without an RPC scheduler, because the schedule is still just data.

No RPC, no per-stage processes, no schedule code — the schedule is data.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

# layer_fn: (x, w) -> x  — one transformer layer given one layer's weights.
LayerFn = Callable[[jax.Array, Any], jax.Array]


def bubble_fraction(num_stages: int, num_microbatches: int = 0,
                    interleave: int = 1) -> float:
    """Idle fraction of stage-time slots for one direction (AD mirrors
    it, so fwd+bwd share the same fraction). GPipe: (P-1)/(M+P-1).
    Interleaved: the ring runs vM+P-1 steps of 1/v-sized stage work, so
    (P-1)/(vM+P-1) — the 1F1B-interleaved bubble, e.g. P=M=4: 0.43 ->
    v=2: 0.27, v=4: 0.16."""
    P = num_stages
    M = num_microbatches or P
    v = max(1, interleave)
    total = v * M + P - 1
    return (P - 1) / total


def pipeline_apply(
    layer_fn: LayerFn,
    layer_params: Any,
    x: jax.Array,
    *,
    num_stages: int,
    num_microbatches: int = 0,
    interleave: int = 1,
    constrain: Callable[[jax.Array, tuple], jax.Array] | None = None,
    logical_axes: tuple = ("batch", "sequence", "embed"),
) -> jax.Array:
    """Run a stacked layer block as a pipeline.

    ``layer_params`` leaves are stacked ``[L, ...]`` (the model's scan
    layout); the leading dim must be divisible by ``num_stages`` and should
    be sharded over the "pipeline" mesh axis (rule ``("layers",
    "pipeline")``) so each stage's slice lives on its own devices.
    ``x`` is the activation ``[B, ...]`` whose trailing dims carry
    ``logical_axes`` names for the sharding constraint; B must be divisible
    by ``num_microbatches`` (default: ``num_stages``).

    ``interleave=v > 1`` selects the interleaved (circular) schedule:
    each stage holds ``v`` layer chunks and microbatches traverse the
    ring ``v`` times (module docstring). Requires ``L % (P*v) == 0`` and
    ``M == P`` — with M=P the ring slot a wrapping microbatch needs is
    exactly the one stage 0 just vacated, so the schedule needs no
    1F1B-style reordering.
    """
    leaves = jax.tree_util.tree_leaves(layer_params)
    n_layers = leaves[0].shape[0]
    P = num_stages
    M = num_microbatches or P
    v = max(1, interleave)
    if n_layers % (P * v):
        raise ValueError(
            f"n_layers={n_layers} not divisible by pipeline_stages={P} "
            f"* interleave={v}"
        )
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch={B} not divisible by microbatches={M}")
    if v > 1 and M != P:
        raise ValueError(
            f"interleaved schedule needs microbatches == stages "
            f"(got M={M}, P={P}): a wrapping microbatch re-enters stage "
            f"0 at t=m+P, which is free only once injection ended at M-1"
        )
    pin = constrain or (lambda a, names: a)
    state_axes = ("stages", *logical_axes)
    if v > 1:
        return _interleaved(layer_fn, layer_params, x, P=P, M=M, v=v,
                            n_layers=n_layers, pin=pin,
                            state_axes=state_axes)

    # [L, ...] -> [P, L/P, ...]: stage s holds layers [s*L/P, (s+1)*L/P).
    stage_ws = jax.tree.map(
        lambda w: w.reshape(P, n_layers // P, *w.shape[1:]), layer_params
    )

    def stage_fn(h: jax.Array, ws: Any) -> jax.Array:
        out, _ = lax.scan(lambda c, w: (layer_fn(c, w), None), h, ws)
        return out

    # [B, ...] -> [M, B/M, ...]
    x_mb = x.reshape(M, B // M, *x.shape[1:])

    state = jnp.zeros((P, B // M, *x.shape[1:]), x.dtype)
    outs = jnp.zeros_like(x_mb)

    def step(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t (clamped: drain steps feed garbage
        # that is never collected)
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False
        )
        state = lax.dynamic_update_index_in_dim(state, inject, 0, 0)
        # dim 1 is the per-microbatch batch dim — keep it on the data axes
        state = pin(state, state_axes)
        out = jax.vmap(stage_fn)(state, stage_ws)
        # last stage emits microbatch t-(P-1). Warm-up steps write garbage
        # into slot 0, overwritten by the real write at t = P-1 (scan order).
        idx = jnp.maximum(t - (P - 1), 0)
        outs = lax.dynamic_update_index_in_dim(outs, out[-1], idx, 0)
        # stage s -> stage s+1 (collective permute on the sharded dim);
        # the wrap-around into stage 0 is overwritten by the next inject.
        state = jnp.roll(out, 1, axis=0)
        return (state, outs), None

    (_, outs), _ = lax.scan(step, (state, outs), jnp.arange(M + P - 1))
    return outs.reshape(B, *x.shape[1:])


def _interleaved(layer_fn: LayerFn, layer_params: Any, x: jax.Array, *,
                 P: int, M: int, v: int, n_layers: int, pin,
                 state_axes: tuple) -> jax.Array:
    """Interleaved (circular) schedule: v chunks per stage, vM + P - 1
    ring steps, each step running L/(P*v) layers per stage.

    Chunk assignment follows Megatron's interleaving: chunk c on stage s
    holds layers [(c*P + s) * lc, +lc) — a microbatch that leaves stage
    P-1 wraps around to stage 0 with the next chunk. At time t, stage s
    runs chunk (t - s) // M (clamped): microbatch m reaches stage s for
    chunk c at exactly t = c*M + m + s, and with M == P the wrap-around
    slot into stage 0 is always free (proof in pipeline_apply's error
    message). Warm-up/drain steps compute garbage that is never
    collected, so its cotangent is zero and AD yields the mirrored
    backward schedule.
    """
    lc = n_layers // (P * v)
    B = x.shape[0]

    # [L, ...] -> [v, P, lc, ...] -> [P, v, lc, ...]: leaf[s][c] is the
    # chunk-c layer block of stage s
    stage_ws = jax.tree.map(
        lambda w: jnp.moveaxis(
            w.reshape(v, P, lc, *w.shape[1:]), 0, 1
        ),
        layer_params,
    )

    def stage_fn(h: jax.Array, ws_chunks: Any, chunk: jax.Array
                 ) -> jax.Array:
        ws = jax.tree.map(
            lambda w: lax.dynamic_index_in_dim(w, chunk, 0,
                                               keepdims=False),
            ws_chunks,
        )
        out, _ = lax.scan(lambda c, w: (layer_fn(c, w), None), h, ws)
        return out

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    state = jnp.zeros((P, B // M, *x.shape[1:]), x.dtype)
    outs = jnp.zeros_like(x_mb)
    stage_idx = jnp.arange(P)

    def step(carry, t):
        state, outs = carry
        # stage 0: fresh microbatch while injecting (t < M), afterwards
        # the wrapped chunk-handoff from stage P-1 (already in slot 0
        # from the previous roll) stays
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False
        )
        slot0 = jnp.where(t < M, inject, state[0])
        state = lax.dynamic_update_index_in_dim(state, slot0, 0, 0)
        state = pin(state, state_axes)
        chunk = jnp.clip((t - stage_idx) // M, 0, v - 1)
        out = jax.vmap(stage_fn)(state, stage_ws, chunk)
        # the final chunk's exit: microbatch m leaves stage P-1 with
        # chunk v-1 at t = (v-1)*M + m + P - 1. Earlier chunks' exits
        # (and warm-up garbage) clamp to slot 0 and are overwritten by
        # the real slot-0 write, which is the LAST clamped one.
        idx = jnp.clip(t - (P - 1) - (v - 1) * M, 0, M - 1)
        outs = lax.dynamic_update_index_in_dim(outs, out[-1], idx, 0)
        state = jnp.roll(out, 1, axis=0)
        return (state, outs), None

    (_, outs), _ = lax.scan(step, (state, outs),
                            jnp.arange(v * M + P - 1))
    return outs.reshape(B, *x.shape[1:])
