"""Pipeline parallelism, pure-SPMD: GPipe over a "pipeline" mesh axis.

Reference analog: ATorch's PiPPy-based pipeline stage split
(atorch/atorch/auto/opt_lib/pipeline_parallel_optimization.py:56) and the
DeepSpeed 3D combination (ds_3d_parallel_optimization.py:55). Those carve the
module graph into per-rank subgraphs driven by an RPC scheduler; on TPU the
idiomatic form keeps ONE jitted SPMD program: the stacked layer dim is
sharded over the "pipeline" mesh axis, each stage's compute is a ``vmap``
over the stage dim, and the stage-to-stage handoff is a ``jnp.roll`` on the
sharded dim which XLA lowers to a collective-permute over ICI. Microbatches
flow through the classic GPipe schedule (M + P - 1 steps, bubble fraction
(P-1)/(M+P-1)); reverse-mode AD of the rolled scan yields the backward
pipeline automatically.

No RPC, no per-stage processes, no schedule code — the schedule is data.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

# layer_fn: (x, w) -> x  — one transformer layer given one layer's weights.
LayerFn = Callable[[jax.Array, Any], jax.Array]


def pipeline_apply(
    layer_fn: LayerFn,
    layer_params: Any,
    x: jax.Array,
    *,
    num_stages: int,
    num_microbatches: int = 0,
    constrain: Callable[[jax.Array, tuple], jax.Array] | None = None,
    logical_axes: tuple = ("batch", "sequence", "embed"),
) -> jax.Array:
    """Run a stacked layer block as a GPipe pipeline.

    ``layer_params`` leaves are stacked ``[L, ...]`` (the model's scan
    layout); the leading dim must be divisible by ``num_stages`` and should
    be sharded over the "pipeline" mesh axis (rule ``("layers",
    "pipeline")``) so each stage's slice lives on its own devices.
    ``x`` is the activation ``[B, ...]`` whose trailing dims carry
    ``logical_axes`` names for the sharding constraint; B must be divisible
    by ``num_microbatches`` (default: ``num_stages``).
    """
    leaves = jax.tree_util.tree_leaves(layer_params)
    n_layers = leaves[0].shape[0]
    P = num_stages
    M = num_microbatches or P
    if n_layers % P:
        raise ValueError(
            f"n_layers={n_layers} not divisible by pipeline_stages={P}"
        )
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch={B} not divisible by microbatches={M}")
    pin = constrain or (lambda a, names: a)
    state_axes = ("stages", *logical_axes)

    # [L, ...] -> [P, L/P, ...]: stage s holds layers [s*L/P, (s+1)*L/P).
    stage_ws = jax.tree.map(
        lambda w: w.reshape(P, n_layers // P, *w.shape[1:]), layer_params
    )

    def stage_fn(h: jax.Array, ws: Any) -> jax.Array:
        out, _ = lax.scan(lambda c, w: (layer_fn(c, w), None), h, ws)
        return out

    # [B, ...] -> [M, B/M, ...]
    x_mb = x.reshape(M, B // M, *x.shape[1:])

    state = jnp.zeros((P, B // M, *x.shape[1:]), x.dtype)
    outs = jnp.zeros_like(x_mb)

    def step(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t (clamped: drain steps feed garbage
        # that is never collected)
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False
        )
        state = lax.dynamic_update_index_in_dim(state, inject, 0, 0)
        # dim 1 is the per-microbatch batch dim — keep it on the data axes
        state = pin(state, state_axes)
        out = jax.vmap(stage_fn)(state, stage_ws)
        # last stage emits microbatch t-(P-1). Warm-up steps write garbage
        # into slot 0, overwritten by the real write at t = P-1 (scan order).
        idx = jnp.maximum(t - (P - 1), 0)
        outs = lax.dynamic_update_index_in_dim(outs, out[-1], idx, 0)
        # stage s -> stage s+1 (collective permute on the sharded dim);
        # the wrap-around into stage 0 is overwritten by the next inject.
        state = jnp.roll(out, 1, axis=0)
        return (state, outs), None

    (_, outs), _ = lax.scan(step, (state, outs), jnp.arange(M + P - 1))
    return outs.reshape(B, *x.shape[1:])
