"""Pipeline parallelism, pure-SPMD: GPipe + interleaved schedules over a
"pipeline" mesh axis.

Reference analog: ATorch's PiPPy-based pipeline stage split
(atorch/atorch/auto/opt_lib/pipeline_parallel_optimization.py:56) and the
DeepSpeed 3D combination (ds_3d_parallel_optimization.py:55). Those carve the
module graph into per-rank subgraphs driven by an RPC scheduler; on TPU the
idiomatic form keeps ONE jitted SPMD program: the stacked layer dim is
sharded over the "pipeline" mesh axis, each stage's compute is a ``vmap``
over the stage dim, and the stage-to-stage handoff is a ``jnp.roll`` on the
sharded dim which XLA lowers to a collective-permute over ICI. Microbatches
flow through the classic GPipe schedule (M + P - 1 steps, bubble fraction
(P-1)/(M+P-1)); reverse-mode AD of the rolled scan yields the backward
pipeline automatically.

``interleave=v > 1`` runs the Megatron-style interleaved (circular)
schedule instead — the 1F1B-class bubble reduction of the reference's
PiPPy schedules (pipeline_parallel_optimization.py:56), in SPMD-roll
form: each stage holds ``v`` non-contiguous layer chunks and every
microbatch circulates through the stage ring ``v`` times, so per-step
stage work shrinks v-fold while the (P-1)-step fill/drain cost is paid
once. Bubble fraction per direction drops from (P-1)/(M+P-1) to
(P-1)/(vM+P-1); reverse-mode AD mirrors the same schedule for the
backward, halving the total bubble exactly as 1F1B-interleaved does —
without an RPC scheduler, because the schedule is still just data.

No RPC, no per-stage processes, no schedule code — the schedule is data.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

# layer_fn: (x, w) -> x  — one transformer layer given one layer's weights.
LayerFn = Callable[[jax.Array, Any], jax.Array]


def bubble_fraction(num_stages: int, num_microbatches: int = 0,
                    interleave: int = 1) -> float:
    """Idle fraction of stage-time slots for one direction (AD mirrors
    it, so fwd+bwd share the same fraction). GPipe: (P-1)/(M+P-1).
    Interleaved: the ring runs vM+P-1 steps of 1/v-sized stage work, so
    (P-1)/(vM+P-1) — the 1F1B-interleaved bubble, e.g. P=M=4: 0.43 ->
    v=2: 0.27, v=4: 0.16."""
    P = num_stages
    M = num_microbatches or P
    v = max(1, interleave)
    total = v * M + P - 1
    return (P - 1) / total


def pipeline_apply(
    layer_fn: LayerFn,
    layer_params: Any,
    x: jax.Array,
    *,
    num_stages: int,
    num_microbatches: int = 0,
    interleave: int = 1,
    constrain: Callable[[jax.Array, tuple], jax.Array] | None = None,
    logical_axes: tuple = ("batch", "sequence", "embed"),
) -> jax.Array:
    """Run a stacked layer block as a pipeline.

    ``layer_params`` leaves are stacked ``[L, ...]`` (the model's scan
    layout); the leading dim must be divisible by ``num_stages`` and should
    be sharded over the "pipeline" mesh axis (rule ``("layers",
    "pipeline")``) so each stage's slice lives on its own devices.
    ``x`` is the activation ``[B, ...]`` whose trailing dims carry
    ``logical_axes`` names for the sharding constraint; B must be divisible
    by ``num_microbatches`` (default: ``num_stages``).

    ``interleave=v > 1`` selects the interleaved (circular) schedule:
    each stage holds ``v`` layer chunks and microbatches traverse the
    ring ``v`` times (module docstring). Requires ``L % (P*v) == 0`` and
    ``M % P == 0``: microbatches flow in groups of P, and group g+1's
    injection into stage 0 starts exactly one step after group g's last
    stage-0 visit, so the ring never double-books a slot and no
    1F1B-style reordering is needed (proof in ``_interleaved``).
    """
    leaves = jax.tree_util.tree_leaves(layer_params)
    n_layers = leaves[0].shape[0]
    P = num_stages
    M = num_microbatches or P
    v = max(1, interleave)
    if n_layers % (P * v):
        raise ValueError(
            f"n_layers={n_layers} not divisible by pipeline_stages={P} "
            f"* interleave={v}"
        )
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch={B} not divisible by microbatches={M}")
    if v > 1 and M % P:
        raise ValueError(
            f"interleaved schedule needs microbatches divisible by "
            f"stages (got M={M}, P={P}): injection runs in groups of P "
            f"so every wrap-around lands on a slot stage 0 just vacated"
        )
    pin = constrain or (lambda a, names: a)
    state_axes = ("stages", *logical_axes)
    if v > 1:
        return _interleaved(layer_fn, layer_params, x, P=P, M=M, v=v,
                            n_layers=n_layers, pin=pin,
                            state_axes=state_axes)

    # [L, ...] -> [P, L/P, ...]: stage s holds layers [s*L/P, (s+1)*L/P).
    stage_ws = jax.tree.map(
        lambda w: w.reshape(P, n_layers // P, *w.shape[1:]), layer_params
    )

    def stage_fn(h: jax.Array, ws: Any) -> jax.Array:
        out, _ = lax.scan(lambda c, w: (layer_fn(c, w), None), h, ws)
        return out

    # [B, ...] -> [M, B/M, ...]
    x_mb = x.reshape(M, B // M, *x.shape[1:])

    state = jnp.zeros((P, B // M, *x.shape[1:]), x.dtype)
    outs = jnp.zeros_like(x_mb)

    def step(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t (clamped: drain steps feed garbage
        # that is never collected)
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, M - 1), 0, keepdims=False
        )
        state = lax.dynamic_update_index_in_dim(state, inject, 0, 0)
        # dim 1 is the per-microbatch batch dim — keep it on the data axes
        state = pin(state, state_axes)
        out = jax.vmap(stage_fn)(state, stage_ws)
        # last stage emits microbatch t-(P-1). Warm-up steps write garbage
        # into slot 0, overwritten by the real write at t = P-1 (scan order).
        idx = jnp.maximum(t - (P - 1), 0)
        outs = lax.dynamic_update_index_in_dim(outs, out[-1], idx, 0)
        # stage s -> stage s+1 (collective permute on the sharded dim);
        # the wrap-around into stage 0 is overwritten by the next inject.
        state = jnp.roll(out, 1, axis=0)
        return (state, outs), None

    (_, outs), _ = lax.scan(step, (state, outs), jnp.arange(M + P - 1))
    return outs.reshape(B, *x.shape[1:])


def _interleaved(layer_fn: LayerFn, layer_params: Any, x: jax.Array, *,
                 P: int, M: int, v: int, n_layers: int, pin,
                 state_axes: tuple) -> jax.Array:
    """Interleaved (circular) schedule: v chunks per stage, vM + P - 1
    ring steps, each step running L/(P*v) layers per stage.

    Chunk assignment follows Megatron's interleaving: chunk c on stage s
    holds layers [(c*P + s) * lc, +lc) — a microbatch that leaves stage
    P-1 wraps around to stage 0 with the next chunk. Microbatches flow
    in k = M/P groups of P injected back-to-back: microbatch m of group
    g sits at stage s running chunk c at exactly

        t = g*v*P + c*P + m + s.

    Conflict-freedom: stage s's visit times decompose uniquely as
    (g, c, m) in base (v, P), so no slot is ever double-booked; group
    g's last stage-0 visit is t = g*v*P + (v-1)*P + (P-1) = (g+1)*v*P-1,
    one step before group g+1's first injection. A microbatch finishing
    chunk v-1 wraps into slot 0 at a chunk-0 boundary, where it is
    either overwritten by the next group's injection or (after the last
    group) left as garbage whose emission check fails. Warm-up/drain
    steps compute garbage that is never collected, so its cotangent is
    zero and AD yields the mirrored backward schedule.
    """
    lc = n_layers // (P * v)
    B = x.shape[0]
    k = M // P

    # [L, ...] -> [v, P, lc, ...] -> [P, v, lc, ...]: leaf[s][c] is the
    # chunk-c layer block of stage s
    stage_ws = jax.tree.map(
        lambda w: jnp.moveaxis(
            w.reshape(v, P, lc, *w.shape[1:]), 0, 1
        ),
        layer_params,
    )

    def stage_fn(h: jax.Array, ws_chunks: Any, chunk: jax.Array
                 ) -> jax.Array:
        ws = jax.tree.map(
            lambda w: lax.dynamic_index_in_dim(w, chunk, 0,
                                               keepdims=False),
            ws_chunks,
        )
        out, _ = lax.scan(lambda c, w: (layer_fn(c, w), None), h, ws)
        return out

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    state = jnp.zeros((P, B // M, *x.shape[1:]), x.dtype)
    outs = jnp.zeros_like(x_mb)
    stage_idx = jnp.arange(P)

    def step(carry, t):
        state, outs = carry
        # stage 0 injects microbatch g*P + (t % P) at every chunk-0
        # boundary (t // P ≡ 0 mod v) while groups remain; other steps
        # keep the wrapped chunk-handoff from stage P-1 (already in
        # slot 0 from the previous roll)
        g_in = t // (v * P)
        injecting = ((t // P) % v == 0) & (g_in < k)
        mb_in = jnp.clip(g_in * P + t % P, 0, M - 1)
        inject = lax.dynamic_index_in_dim(x_mb, mb_in, 0, keepdims=False)
        slot0 = jnp.where(injecting, inject, state[0])
        state = lax.dynamic_update_index_in_dim(state, slot0, 0, 0)
        state = pin(state, state_axes)
        # stage s at time t runs chunk ((t - s) // P) mod v; warm-up
        # (t < s) floor-divides negative but mod keeps it in range —
        # garbage, never collected
        chunk = ((t - stage_idx) // P) % v
        out = jax.vmap(stage_fn)(state, stage_ws, chunk)
        # stage P-1 emits microbatch (g, m) exactly when its chunk was
        # v-1: w = t - (P-1) decomposes as g*v*P + c*P + m
        w = t - (P - 1)
        c_em = (w // P) % v
        g_em = w // (v * P)
        valid = (w >= 0) & (c_em == v - 1) & (g_em < k)
        idx = jnp.clip(g_em * P + w % P, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
        emit = jnp.where(valid, out[-1], cur)
        outs = lax.dynamic_update_index_in_dim(outs, emit, idx, 0)
        state = jnp.roll(out, 1, axis=0)
        return (state, outs), None

    (_, outs), _ = lax.scan(step, (state, outs),
                            jnp.arange(v * M + P - 1))
    return outs.reshape(B, *x.shape[1:])
