"""Automatic strategy selection: the ``auto_accelerate`` front door.

Reference analog: atorch's strategy search (auto/accelerate.py:406 with
the engine/planner loop generating candidates and the dry-runner scoring
them). TPU-native: candidates are Strategy presets in preference order
(cheapest collectives first); each is AOT-compiled (parallel/dry_run.py)
and the first one whose peak per-device memory fits HBM wins — seconds of
compile time instead of minutes of trial training.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.dry_run import pick_strategy
from dlrover_tpu.parallel.mesh import data_parallel_size
from dlrover_tpu.parallel.strategy import (
    Strategy,
    dp,
    fsdp,
    fsdp_tp,
    zero1,
    zero2,
)

logger = get_logger(__name__)


def device_hbm_bytes(device=None) -> int:
    """Per-device memory budget; a conservative default when the runtime
    doesn't report one (CPU/tunneled backends).

    ``DLROVER_TPU_DEVICE_HBM_BYTES`` (DESIGN.md §24) wins outright: a
    CPU or tunneled backend whose runtime reports nothing can state the
    REAL target envelope, so the autopilot planner's feasibility filter
    rejects OOM plans instead of silently skipping the check (0 = no
    check)."""
    import jax as _jax

    from dlrover_tpu.common import envspec
    from dlrover_tpu.common.constants import EnvKey

    stated = envspec.get_int(EnvKey.DEVICE_HBM_BYTES)
    if stated is not None and stated > 0:
        return stated
    device = device or _jax.devices()[0]
    try:
        stats = device.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001
        pass
    return 16 * (1 << 30) if device.platform == "tpu" else 0


def default_candidates(num_devices: int) -> list[Strategy]:
    """Preference order: replicated DP (no param collectives), ZeRO-1
    (dp + sharded optimizer state — fits when params do but params+Adam
    don't), then FSDP (param gathers), then FSDP x TP (per-layer
    collectives)."""
    candidates = [dp()]
    if num_devices > 1:
        candidates.append(zero1())
        candidates.append(zero2())
        candidates.append(fsdp())
    if num_devices >= 4:
        candidates.append(fsdp_tp(tensor_size=2))
    return candidates


def auto_strategy(
    *,
    loss_fn_for,           # (strategy, mesh) -> loss_fn(params, batch)
    init_params_fn,
    logical_params,
    optimizer,
    example_batch,          # pytree of np arrays [accum, batch, ...]
    devices: Sequence | None = None,
    candidates: Sequence[Strategy] | None = None,
    hbm_capacity_bytes: int | None = None,
    objective: str = "fastest",
    hw=None,
) -> tuple[Strategy, list]:
    """Pick the best candidate that compiles and fits memory.

    ``objective="fastest"`` (default) ranks fitting candidates by the
    roofline step-time estimate (parallel/cost_model.py); "first_fit"
    keeps the preference-order behavior. Returns (strategy, dry-run
    reports). ``loss_fn_for`` lets the caller bind attention/constraint
    choices per strategy (make_loss_fn).
    """
    from dlrover_tpu.trainer.train_step import compile_train

    devices = list(devices if devices is not None else jax.devices())
    if candidates is None:
        candidates = default_candidates(len(devices))
    if hbm_capacity_bytes is None:
        hbm_capacity_bytes = device_hbm_bytes(devices[0])

    def build_step(strategy: Strategy):
        mesh = strategy.build_mesh(devices)
        compiled = compile_train(
            strategy=strategy,
            mesh=mesh,
            loss_fn=loss_fn_for(strategy, mesh),
            init_params_fn=init_params_fn,
            logical_params=logical_params,
            optimizer=optimizer,
        )
        state_abstract = jax.eval_shape(
            compiled.init, jax.random.PRNGKey(0)
        )
        state_abstract = jax.tree.map(
            lambda leaf, s: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=s
            ),
            state_abstract, compiled.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
        batch_abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                np.shape(a), np.asarray(a).dtype,
                sharding=compiled.batch_sharding,
            ),
            example_batch,
        )
        return compiled.step, (state_abstract, batch_abstract)

    best, reports = pick_strategy(
        build_step, list(candidates),
        hbm_capacity_bytes=hbm_capacity_bytes,
        objective=objective, hw=hw,
    )
    logger.info("auto strategy selected: %s", best.name)
    return best, reports


# bump when the search algorithm or the preset definitions change in a
# way that should invalidate persisted strategy caches (it is folded
# into the workload fingerprint alongside the candidate names)
_SEARCH_VERSION = 2


def _workload_fingerprint(kwargs: dict, n_devices: int) -> str:
    """Hash of everything that determines auto_strategy's answer: the
    abstract parameter tree, batch shapes, objective, HBM budget,
    device count, AND the candidate set + search version — a cache hit
    for a DIFFERENT model/batch would hand back a strategy that never
    passed this workload's fit check, and a cache written before a
    preset was added (e.g. the round-3 zero1/zero2 candidates) must not
    pin the old pick across upgrades."""
    import hashlib

    def sig(tree):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return sorted(
            (jax.tree_util.keystr(p), v) for p, v in flat
        )

    shapes = jax.tree_util.tree_map(
        lambda l: (tuple(l.shape), str(l.dtype)),
        jax.eval_shape(kwargs["init_params_fn"], jax.random.PRNGKey(0)),
    )
    batch_shapes = jax.tree_util.tree_map(
        lambda a: (tuple(np.shape(a)), str(np.asarray(a).dtype)),
        kwargs["example_batch"],
    )
    cands = kwargs.get("candidates")
    cand_names = [
        c.name for c in (cands if cands is not None
                         else default_candidates(n_devices))
    ]
    blob = repr((
        sig(shapes),
        sig(batch_shapes),
        kwargs.get("objective", "fastest"),
        kwargs.get("hbm_capacity_bytes"),
        n_devices,
        cand_names,
        _SEARCH_VERSION,
    ))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def cached_auto_strategy(cache_path: str, **kwargs) -> tuple[Strategy, list]:
    """auto_strategy with a persisted result: the load_strategy analog.

    Reference: auto_accelerate's ``load_strategy`` (accelerate.py:467)
    — tune once, then every later run (and every elastic RESTART, where
    re-searching would burn the recovery window with N candidate
    compiles) reloads the picked strategy. The cache is keyed by a
    workload fingerprint (param/batch shapes, objective, HBM budget,
    device count): any change re-runs the search.
    """
    import dataclasses as _dc
    import json as _json
    import os as _os

    devices = kwargs.get("devices")
    n = len(devices) if devices is not None else len(jax.devices())
    fp = _workload_fingerprint(kwargs, n)
    try:
        with open(cache_path) as f:
            data = _json.load(f)
        if data.get("fingerprint") == fp:
            strategy = Strategy(**data["strategy"])
            logger.info(
                "reusing tuned strategy %r from %s (%d devices)",
                strategy.name, cache_path, n,
            )
            return strategy, []
    except (OSError, ValueError, KeyError, TypeError):
        pass
    strategy, reports = auto_strategy(**kwargs)
    try:
        _os.makedirs(_os.path.dirname(cache_path) or ".", exist_ok=True)
        # pid-suffixed temp + atomic replace: concurrent cold-starting
        # processes on a shared output_dir each write their own file
        # (identical content) — last writer wins, never interleaved
        tmp = f"{cache_path}.{_os.getpid()}.tmp"
        with open(tmp, "w") as f:
            _json.dump({
                "fingerprint": fp,
                "devices": n,
                "strategy": _dc.asdict(strategy),
            }, f, indent=2)
        _os.replace(tmp, cache_path)
    except OSError as e:  # cache is best-effort
        logger.warning("could not persist strategy cache: %s", e)
    return strategy, reports
