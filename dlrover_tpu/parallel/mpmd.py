"""MPMD pipeline runtime: per-stage programs + a host-side 1F1B
scheduler (Scaling Deep Learning Training with MPMD Pipeline
Parallelism, 2412.14374).

The SPMD roll in ``parallel/pipeline.py`` keeps ONE jitted program and
expresses the schedule as data — elegant, but it has two structural
costs this module removes:

1. **Lockstep pacing.** Every ring step of the SPMD roll runs all
   stages in lockstep, so a heterogeneous stage set (the embed-heavy
   first stage, the lm-head-heavy last stage) paces EVERY slot at the
   slowest stage's cost. Here each stage is its own compiled program on
   its own disjoint device submesh; the host threads microbatches
   through the stage executables in 1F1B order with explicit
   activation/cotangent handoff (``jax.device_put`` between submeshes —
   ICI p2p on real hardware) and overlapped dispatch (JAX's async
   dispatch runs the P in-flight programs concurrently), so steady
   state is paced only by the slowest stage and the fill/drain ramp
   pays each stage's own cost once. The measured schedule bubble
   matches ``parallel.pipeline.bubble_fraction``'s 1F1B bound
   ``(P-1)/(M+P-1)`` instead of GPipe's slowest-stage-paced slots.

2. **Monolithic recompile.** One program means a membership change
   recompiles everything. Per-stage programs ride the elastic compile
   cache (DESIGN.md §17) under per-stage fingerprints
   (``compile_cache.stage_key``: stage index + chunk config + phase in
   the key), so recovery after a single-stage failure recompiles only
   that stage's programs — the other P−1 load warm (~0.1s each). Every
   stage-program build journals ``pipeline_stage_compile`` evidence.

Each stage owns three program kinds:

- ``fwd``:   ``(stage_params, x_in) -> y`` — stage 0 embeds tokens
  first; activations stay in the model's compute dtype.
- ``bwd``:   ``(stage_params, x_in, dy, gacc) -> (dx, gacc')`` —
  recomputes the stage forward under ``jax.vjp`` (1F1B-with-remat
  semantics: the only saved tensor between fwd and bwd is the stage's
  INPUT activation), accumulating parameter grads into ``gacc``. The
  last stage fuses loss + backward into one ``(params, x_in, targets,
  gacc) -> (loss, dx, gacc')`` program; stage 0's drops the useless
  token cotangent.
- ``update``: the ZeRO-sharded weight update (Xu et al., 2004.13336):
  optimizer state shards over the stage submesh's data axis
  (``train_step.zero_shard_specs``), params stay replicated, the
  all-gather comes from the out shardings — optimizer bytes per device
  drop by the data-axis size with bit-identical math.

Numerics: the stage programs are built from the SAME module-level model
pieces the monolithic path scans (``models.transformer.make_layer_fn``
/ ``embed_tokens`` / ``final_norm`` / ``lm_logits`` / ``token_ce``),
and a mean over equal-size microbatches composes to the full-batch
mean, so the MPMD loss matches the SPMD pipeline within the
reduction-order bound ``RTOL_CROSS_LAYOUT`` (pinned in
tests/test_mpmd.py). MoE, prefix-LM and interleaved chunking are
rejected up front (the SPMD roll keeps those).

None of the stage programs donate inputs: a deserialized ``Compiled``
skips pjit's input re-staging, and donation over host-adopted CPU
buffers compounds in-place updates (the §17 hazard —
``compile_cache.launder``); restored states must still be laundered
before their first dispatch, which the example's restore path does.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.parallel import compile_cache as cc
from dlrover_tpu.parallel.cost_model import (
    HardwareSpec,
    PipelineSchedule,
    rank_schedules,
)
from dlrover_tpu.parallel.pipeline import bubble_fraction
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry
from dlrover_tpu.trainer.train_step import zero_shard_specs

logger = get_logger(__name__)

_stage_seconds = registry().histogram(
    "dlrover_tpu_pipeline_stage_seconds",
    "per-stage program dispatch wall time by phase (fwd/bwd/update); "
    "dispatch is async, so this is queue+dispatch cost unless the host "
    "is paced — the per-stage SLOW evidence for the §21 runbook",
    label_names=("stage", "phase"),
)
_bubble_gauge = registry().gauge(
    "dlrover_tpu_pipeline_bubble_frac",
    "measured 1F1B schedule bubble of the last MPMD step (idle stage-"
    "ticks / total stage-ticks); steady state matches (P-1)/(M+P-1)",
)
_stage_compile_seconds = registry().histogram(
    "dlrover_tpu_pipeline_stage_compile_seconds",
    "per-stage program load-or-compile time by phase (warm cache hits "
    "are ~0.1s; a cold entry here after recovery names the stage that "
    "actually recompiled)",
    label_names=("stage", "phase"),
)
_p2p_bytes = registry().counter(
    "dlrover_tpu_pipeline_handoff_bytes_total",
    "explicit inter-stage activation/cotangent handoff bytes moved by "
    "the host scheduler (device-to-device on real hardware)",
)
_opt_bytes_gauge = registry().gauge(
    "dlrover_tpu_pipeline_opt_state_bytes",
    "per-device optimizer-state bytes of one stage, by layout "
    "(ZeRO-sharded actual vs replicated counterfactual)",
    label_names=("stage", "layout"),
)

STAGE_PHASES = ("fwd", "bwd", "update")


def stage_op_schedule(num_stages: int, num_microbatches: int
                      ) -> list[list[tuple[str, int]]]:
    """Canonical per-stage 1F1B op lists ``[("F"|"B", microbatch)]``.

    Stage s warms up with ``min(M, P-1-s)`` forwards, then alternates
    F/B until the forwards run dry and the backwards drain — the
    memory-bounded 1F1B order (at most ``P-s`` activations stashed per
    stage). The last stage's F dispatches the fused loss+grad program;
    its B tick publishes the already-computed cotangent upstream (the
    program is two slots of work, dispatched at the first)."""
    P, M = num_stages, num_microbatches
    out = []
    for s in range(P):
        warm = min(M, P - 1 - s)
        ops: list[tuple[str, int]] = [("F", m) for m in range(warm)]
        f, b = warm, 0
        while b < M:
            if f < M:
                ops.append(("F", f))
                f += 1
            ops.append(("B", b))
            b += 1
        out.append(ops)
    return out


# ----------------------------------------------------------- stage split


def split_params(params: Any, num_stages: int) -> list[dict]:
    """Split the full stacked-param tree into per-stage trees: stage
    ``s`` owns layer rows ``[s*L/P, (s+1)*L/P)``; stage 0 additionally
    owns the embedding front end, the last stage the final norm + LM
    head. Leaf arrays are views/slices of the originals (callers
    device_put them onto the stage submeshes)."""
    P = num_stages
    leaves = jax.tree_util.tree_leaves(params["layers"])
    n_layers = leaves[0].shape[0]
    if P < 2:
        raise ValueError(f"MPMD needs >= 2 stages, got {P}")
    if n_layers % P:
        raise ValueError(
            f"n_layers={n_layers} not divisible by stages={P}"
        )
    chunk = n_layers // P
    out: list[dict] = []
    for s in range(P):
        tree: dict = {
            "layers": jax.tree.map(
                lambda a: a[s * chunk:(s + 1) * chunk], params["layers"]
            )
        }
        if s == 0:
            tree["embed"] = params["embed"]
            if "pos_embed" in params:
                tree["pos_embed"] = params["pos_embed"]
        if s == P - 1:
            tree["ln_f"] = params["ln_f"]
            if "ln_f_b" in params:
                tree["ln_f_b"] = params["ln_f_b"]
            tree["lm_head"] = params["lm_head"]
        out.append(tree)
    return out


def _check_supported(cfg: tfm.TransformerConfig, interleave: int) -> None:
    if cfg.moe_experts:
        raise NotImplementedError(
            "MPMD pipeline + MoE: aux-loss accounting across stage "
            "programs is not wired; use the moe/expert strategies"
        )
    if cfg.prefix_lm:
        raise NotImplementedError(
            "MPMD pipeline + prefix_lm: the per-row prefix mask is a "
            "full-batch closure, stages see microbatches"
        )
    if interleave > 1:
        raise NotImplementedError(
            "MPMD scheduler runs plain 1F1B (one chunk per stage); the "
            "SPMD roll (parallel/pipeline.py) keeps the interleaved "
            "schedule"
        )


# ----------------------------------------------------------- stage math


def _stage_hidden(stage_params: dict, x: jax.Array, layer_fn) -> jax.Array:
    out, _ = jax.lax.scan(
        lambda c, w: (layer_fn(c, w)[0], None), x, stage_params["layers"]
    )
    return out


def _make_stage_fns(cfg: tfm.TransformerConfig, num_stages: int
                    ) -> list[Callable]:
    """Per-stage forward callables over the shared model pieces.

    Stage 0: ``(params, tokens) -> act``; middle: ``(params, act) ->
    act``; last: ``(params, act, targets) -> loss`` (scalar mean CE of
    the microbatch)."""
    layer_fn = tfm.make_layer_fn(cfg)
    fns: list[Callable] = []
    for s in range(num_stages):
        if s == 0:
            def f0(params, tokens, _layer=layer_fn):
                x = tfm.embed_tokens(params, tokens, cfg)
                return _stage_hidden(params, x, _layer)

            fns.append(f0)
        elif s < num_stages - 1:
            def fm(params, x, _layer=layer_fn):
                return _stage_hidden(params, x, _layer)

            fns.append(fm)
        else:
            def fl(params, x, targets, _layer=layer_fn):
                h = _stage_hidden(params, x, _layer)
                h = tfm.final_norm(params, h, cfg)
                return tfm.token_ce(tfm.lm_logits(params, h, cfg),
                                    targets)

            fns.append(fl)
    return fns


# --------------------------------------------------------------- runtime


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MpmdState:
    """Train state of an MPMD job: one ``{"params", "opt_state"}`` dict
    per stage, living on that stage's submesh."""

    step: jax.Array
    stages: tuple


@dataclasses.dataclass
class _StagePrograms:
    """Compiled programs + shardings of one stage."""

    index: int
    mesh: Mesh
    fwd: Any = None          # AotStep.fn (None for the last stage)
    bwd: Any = None          # AotStep.fn (loss_grad for the last stage)
    update: Any = None       # AotStep.fn
    zero_grads: Any = None   # plain jit: () -> zeroed gacc tree
    in_sharding: Any = None  # sharding of this stage's input (tokens/act)
    act_sharding: Any = None  # sharding of this stage's OUTPUT activation
    param_shardings: Any = None
    opt_shardings: Any = None
    compile_seconds: float = 0.0   # sum over this stage's programs
    cache_hits: int = 0
    cache_misses: int = 0
    flops: float = 0.0             # fwd+bwd per microbatch + update once


def _replicated(mesh: Mesh, tree: Any) -> Any:
    sh = NamedSharding(mesh, PartitionSpec())
    return jax.tree.map(lambda _: sh, tree)


def _abstract(tree: Any, shardings: Any) -> Any:
    return jax.tree.map(
        lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                              sharding=sh),
        tree, shardings,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )


class MpmdTrain:
    """Duck-types ``trainer.train_step.CompiledTrain`` for
    ``ElasticTrainer``: ``mesh`` (stage 0's — its data axis is the
    batch-sharding world), ``batch_sharding``, ``init``, ``step``,
    ``state_shardings``, ``cache_hit``, ``flops_per_step``.

    ``step(state, batch)`` is the host-side 1F1B scheduler: ``batch``
    leaves are ``[accum, step_batch, ...]``; each accum round threads
    ``microbatches`` chunks of the step batch through the stage
    executables, grads accumulate per stage, and one ZeRO-sharded
    update per stage closes the step.
    """

    def __init__(self, cfg, strategy, optimizer, *, num_stages: int,
                 microbatches: int, seq: int, step_batch: int,
                 accum: int = 1, devices: Sequence[jax.Device] | None = None,
                 cache: cc.CompileCacheClient | None = None,
                 num_nodes: int = 1, extra_fingerprint: dict | None = None):
        _check_supported(cfg, int(getattr(strategy, "extra", {}).get(
            "pipeline_interleave", 1) or 1))
        self.cfg = cfg
        self.strategy = strategy
        self.optimizer = optimizer
        devices = list(devices if devices is not None else jax.devices())
        P = int(num_stages)
        if P < 2:
            raise ValueError(f"MPMD needs >= 2 stages, got {P}")
        if len(devices) % P:
            raise ValueError(
                f"{len(devices)} devices not divisible by {P} stages"
            )
        self.num_stages = P
        self.microbatches = M = int(microbatches) or P
        self.seq = int(seq)
        self.step_batch = int(step_batch)
        self.accum = max(1, int(accum))
        if self.step_batch % M:
            raise ValueError(
                f"step batch {self.step_batch} not divisible by "
                f"microbatches={M}"
            )
        self.mb_rows = self.step_batch // M
        per = len(devices) // P
        self.data_size = per
        if self.mb_rows % per:
            raise ValueError(
                f"microbatch rows {self.mb_rows} not divisible by the "
                f"stage data axis ({per} devices)"
            )
        self._meshes = [
            Mesh(np.asarray(devices[s * per:(s + 1) * per]), ("data",))
            for s in range(P)
        ]
        self.mesh = self._meshes[0]
        self.batch_sharding = NamedSharding(
            self.mesh, PartitionSpec(None, "data")
        )
        self._cache = cache or cc.CompileCacheClient()
        self._num_nodes = int(num_nodes)
        self._fp_extra = dict(extra_fingerprint or {})
        self._stage_fns = _make_stage_fns(cfg, P)
        self.stages: list[_StagePrograms] = []
        self.cache_hit: bool | None = None
        self.flops_per_step: float = 0.0
        self.last_bubble_frac: float = 0.0
        self.bubble_bound = bubble_fraction(P, M, 1)
        self._abs: list[dict] = []     # per-stage abstract arg trees
        self._build_all()

    # ------------------------------------------------------------ build

    def _stage_abstracts(self) -> list[dict]:
        """Per-stage abstract trees: params (replicated on the stage
        submesh), opt_state (ZeRO-sharded), grads, input/output
        activations — everything ``.lower`` needs, no arrays built."""
        P, M = self.num_stages, self.microbatches
        stages_abs = jax.eval_shape(
            lambda k: split_params(tfm.init_params(self.cfg, k), P),
            jax.random.PRNGKey(0),
        )
        dt = jnp.dtype(self.cfg.dtype)
        out = []
        for s in range(P):
            mesh = self._meshes[s]
            param_shardings = _replicated(mesh, stages_abs[s])
            params_abs = _abstract(stages_abs[s], param_shardings)
            opt_shape = jax.eval_shape(self.optimizer.init, params_abs)
            opt_specs = zero_shard_specs(
                jax.tree.map(lambda _: PartitionSpec(), opt_shape),
                opt_shape, mesh,
            )
            opt_shardings = jax.tree.map(
                lambda sp: NamedSharding(mesh, sp), opt_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            )
            opt_abs = _abstract(opt_shape, opt_shardings)
            grads_abs = _abstract(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, jnp.float32),
                    params_abs,
                    is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
                ),
                param_shardings,
            )
            data_sh = NamedSharding(mesh, PartitionSpec("data"))
            act = jax.ShapeDtypeStruct(
                (self.mb_rows, self.seq, self.cfg.d_model), dt,
                sharding=data_sh,
            )
            if s == 0:
                x_in = jax.ShapeDtypeStruct(
                    (self.mb_rows, self.seq), jnp.int32, sharding=data_sh
                )
            else:
                x_in = act
            targets = jax.ShapeDtypeStruct(
                (self.mb_rows, self.seq), jnp.int32, sharding=data_sh
            )
            out.append({
                "params": params_abs, "opt": opt_abs, "grads": grads_abs,
                "x_in": x_in, "act": act, "targets": targets,
                "param_shardings": param_shardings,
                "opt_shardings": opt_shardings,
                "data_sharding": data_sh,
            })
        return out

    def _fingerprint(self, s: int, phase: str, abstracts: tuple
                     ) -> tuple[str, dict]:
        mesh = self._meshes[s]
        base, inputs = cc.compile_fingerprint(
            num_nodes=self._num_nodes,
            total_devices=int(mesh.devices.size),
            mesh_axes=dict(mesh.shape),
            model=self.cfg,
            strategy=self.strategy,
            args_signature=cc.abstract_signature(abstracts),
            extra={
                **self._fp_extra,
                "schedule": "mpmd_1f1b",
                "pipeline_stage": s,
                "num_stages": self.num_stages,
                "microbatches": self.microbatches,
                "interleave": 1,
                "phase": phase,
            },
        )
        key = cc.stage_key(base, stage=s, num_stages=self.num_stages,
                           phase=phase)
        return key, inputs

    def _load_program(self, prog: _StagePrograms, phase: str,
                      jitted, abstracts: tuple) -> cc.AotStep:
        s = prog.index
        key, inputs = self._fingerprint(s, phase, abstracts)
        aot = cc.load_or_compile(
            key, inputs,
            compile_fn=lambda: jitted.lower(*abstracts).compile(),
            cache=self._cache,
        )
        _stage_compile_seconds.labels(str(s), phase).observe(aot.seconds)
        get_journal().emit(
            "pipeline_stage_compile", stage=s, phase=phase,
            hit=aot.cache_hit, source=aot.source, dur=aot.seconds,
            key=key,
        )
        prog.compile_seconds += aot.seconds
        if aot.source in ("local", "master"):
            prog.cache_hits += 1
        else:
            prog.cache_misses += 1
        return aot

    def _build_stage(self, s: int) -> _StagePrograms:
        """Compile-or-load one stage's programs (the per-stage recovery
        unit: ``rebuild_stage`` calls this for just the failed
        stage)."""
        P = self.num_stages
        ab = self._abs[s]
        mesh = self._meshes[s]
        prog = _StagePrograms(index=s, mesh=mesh)
        prog.in_sharding = (ab["x_in"].sharding if s == 0
                            else ab["data_sharding"])
        prog.act_sharding = ab["data_sharding"]
        prog.param_shardings = ab["param_shardings"]
        prog.opt_shardings = ab["opt_shardings"]
        fn = self._stage_fns[s]
        repl = NamedSharding(mesh, PartitionSpec())
        flops = 0.0
        if s < P - 1:
            fwd_jit = jax.jit(
                fn,
                in_shardings=(ab["param_shardings"], prog.in_sharding),
                out_shardings=ab["data_sharding"],
            )
            aot = self._load_program(
                prog, "fwd", fwd_jit, (ab["params"], ab["x_in"])
            )
            prog.fwd = aot.fn
            flops += aot.flops

            if s == 0:
                def bwd_fn(params, x_in, dy, gacc):
                    _, vjp = jax.vjp(lambda p: fn(p, x_in), params)
                    (dp,) = vjp(dy)
                    return jax.tree.map(jnp.add, gacc, dp)

                out_sh = ab["param_shardings"]
            else:
                def bwd_fn(params, x_in, dy, gacc):
                    _, vjp = jax.vjp(fn, params, x_in)
                    dp, dx = vjp(dy)
                    return dx, jax.tree.map(jnp.add, gacc, dp)

                out_sh = (prog.in_sharding, ab["param_shardings"])
            bwd_jit = jax.jit(
                bwd_fn,
                in_shardings=(ab["param_shardings"], prog.in_sharding,
                              ab["data_sharding"], ab["param_shardings"]),
                out_shardings=out_sh,
            )
            aot = self._load_program(
                prog, "bwd", bwd_jit,
                (ab["params"], ab["x_in"], ab["act"], ab["grads"]),
            )
            prog.bwd = aot.fn
            flops += aot.flops
        else:
            def loss_grad_fn(params, x_in, targets, gacc):
                loss, (dp, dx) = jax.value_and_grad(
                    fn, argnums=(0, 1)
                )(params, x_in, targets)
                return loss, dx, jax.tree.map(jnp.add, gacc, dp)

            lg_jit = jax.jit(
                loss_grad_fn,
                in_shardings=(ab["param_shardings"], prog.in_sharding,
                              ab["data_sharding"], ab["param_shardings"]),
                out_shardings=(repl, prog.in_sharding,
                               ab["param_shardings"]),
            )
            aot = self._load_program(
                prog, "bwd", lg_jit,
                (ab["params"], ab["x_in"], ab["targets"], ab["grads"]),
            )
            prog.bwd = aot.fn
            flops += aot.flops

        total_mb = self.microbatches * self.accum
        scale = 1.0 / float(total_mb)
        optimizer = self.optimizer

        def update_fn(params, opt_state, gacc):
            import optax

            grads = jax.tree.map(lambda g: g * scale, gacc)
            updates, opt2 = optimizer.update(grads, opt_state, params)
            params2 = optax.apply_updates(params, updates)
            # squared partial norm: the host sums stages then sqrts, so
            # the reported grad_norm equals the monolithic global_norm
            gn2 = sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree_util.tree_leaves(grads)
            )
            return params2, opt2, gn2

        upd_jit = jax.jit(
            update_fn,
            in_shardings=(ab["param_shardings"], ab["opt_shardings"],
                          ab["param_shardings"]),
            out_shardings=(ab["param_shardings"], ab["opt_shardings"],
                           repl),
        )
        aot = self._load_program(
            prog, "update", upd_jit,
            (ab["params"], ab["opt"], ab["grads"]),
        )
        prog.update = aot.fn

        grads_shape = ab["grads"]
        prog.zero_grads = jax.jit(
            lambda: jax.tree.map(
                lambda a: jnp.zeros(a.shape, a.dtype), grads_shape,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
            ),
            out_shardings=ab["param_shardings"],
        )
        # fwd+bwd run once per microbatch, the update once per step
        prog.flops = (flops * self.microbatches * self.accum
                      + float(aot.flops))
        self._publish_opt_bytes(s, ab)
        return prog

    def _publish_opt_bytes(self, s: int, ab: dict) -> None:
        """ZeRO evidence: per-device optimizer bytes, sharded vs the
        replicated counterfactual."""
        sharded = replicated = 0
        for leaf, sh in zip(
            jax.tree_util.tree_leaves(ab["opt"]),
            jax.tree_util.tree_leaves(
                ab["opt_shardings"],
                is_leaf=lambda x: isinstance(x, NamedSharding)),
        ):
            nbytes = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
            replicated += nbytes
            frac = self.data_size if sh.spec != PartitionSpec() else 1
            sharded += nbytes // frac
        _opt_bytes_gauge.labels(str(s), "sharded").set(float(sharded))
        _opt_bytes_gauge.labels(str(s), "replicated").set(float(replicated))
        self.opt_bytes = getattr(self, "opt_bytes", {})
        self.opt_bytes[s] = {"sharded": sharded, "replicated": replicated}

    def _build_all(self) -> None:
        t0 = time.monotonic()
        self._abs = self._stage_abstracts()
        self.stages = [self._build_stage(s)
                       for s in range(self.num_stages)]
        self.flops_per_step = sum(p.flops for p in self.stages)
        misses = sum(p.cache_misses for p in self.stages)
        self.cache_hit = misses == 0
        logger.info(
            "MPMD runtime ready: %d stages x %d microbatches over %d "
            "devices in %.2fs (%d program cache hits, %d compiles)",
            self.num_stages, self.microbatches,
            self.num_stages * self.data_size, time.monotonic() - t0,
            sum(p.cache_hits for p in self.stages), misses,
        )

    def rebuild_stage(self, s: int) -> _StagePrograms:
        """Per-stage elastic recovery: recompile/reload ONLY stage
        ``s``'s programs (the failed stage's replacement finds the
        other P−1 untouched; its own come warm from the master cache or
        cold-compile — either way the journal's
        ``pipeline_stage_compile`` entries name exactly this stage)."""
        self.stages[s] = self._build_stage(s)
        self.flops_per_step = sum(p.flops for p in self.stages)
        return self.stages[s]

    # ------------------------------------------------------------- state

    @property
    def state_shardings(self) -> MpmdState:
        return MpmdState(
            step=NamedSharding(self.mesh, PartitionSpec()),
            stages=tuple(
                {"params": p.param_shardings, "opt_state": p.opt_shardings}
                for p in self.stages
            ),
        )

    def abstract_state(self) -> MpmdState:
        return MpmdState(
            step=jax.ShapeDtypeStruct((), jnp.int32),
            stages=tuple(
                {"params": ab["params"], "opt_state": ab["opt"]}
                for ab in self._abs
            ),
        )

    def init(self, rng: jax.Array) -> MpmdState:
        full = tfm.init_params(self.cfg, rng)
        trees = split_params(full, self.num_stages)
        states = []
        for s, prog in enumerate(self.stages):
            params = jax.device_put(trees[s], prog.param_shardings)
            opt_init = jax.jit(
                self.optimizer.init,
                out_shardings=prog.opt_shardings,
            )
            states.append({"params": params,
                           "opt_state": opt_init(params)})
        return MpmdState(
            step=jax.device_put(
                jnp.zeros((), jnp.int32),
                NamedSharding(self.mesh, PartitionSpec()),
            ),
            stages=tuple(states),
        )

    # --------------------------------------------------- 1F1B scheduler

    def _stage_ops(self) -> list[deque]:
        return [deque(ops) for ops in
                stage_op_schedule(self.num_stages, self.microbatches)]

    def _handoff(self, arr: jax.Array, sharding) -> jax.Array:
        """Explicit inter-stage transfer (p2p over ICI on real
        hardware; host-mediated on the CPU test backend)."""
        _p2p_bytes.inc(int(arr.size) * arr.dtype.itemsize)
        return jax.device_put(arr, sharding)

    def _run_round(self, stage_states, tokens_round, gaccs, losses
                   ) -> tuple[int, int]:
        """One 1F1B pass of M microbatches; returns (ticks, busy)."""
        P, M = self.num_stages, self.microbatches
        mb = self.mb_rows
        queues = self._stage_ops()
        acts: dict[tuple[int, int], Any] = {}
        cots: dict[tuple[int, int], Any] = {}
        stash: list[dict[int, Any]] = [dict() for _ in range(P)]
        dx_pending: dict[int, Any] = {}
        first = self.stages[0]
        last = self.stages[P - 1]

        def stage0_input(m: int):
            rows = tokens_round[m * mb:(m + 1) * mb]
            return jax.device_put(rows[:, :-1], first.in_sharding)

        def targets_for(m: int):
            rows = tokens_round[m * mb:(m + 1) * mb]
            return self._handoff(rows[:, 1:], last.act_sharding)

        ticks = busy = 0
        while any(queues):
            publishes: list[tuple[dict, tuple[int, int], Any]] = []
            progressed = 0
            for s in range(P):
                if not queues[s]:
                    continue
                op, m = queues[s][0]
                prog = self.stages[s]
                params = stage_states[s]["params"]
                t0 = time.monotonic()
                if op == "F" and s < P - 1:
                    if s > 0 and (s, m) not in acts:
                        continue
                    x_in = stage0_input(m) if s == 0 else acts.pop((s, m))
                    y = prog.fwd(params, x_in)
                    stash[s][m] = x_in
                    publishes.append((acts, (s + 1, m),
                                      self._handoff(y, self.stages[s + 1]
                                                    .in_sharding)))
                    _stage_seconds.labels(str(s), "fwd").observe(
                        time.monotonic() - t0)
                elif op == "F":  # last stage: fused loss+grad
                    if (s, m) not in acts:
                        continue
                    x_in = acts.pop((s, m))
                    loss, dx, gaccs[s] = prog.bwd(
                        params, x_in, targets_for(m), gaccs[s]
                    )
                    losses.append(loss)
                    dx_pending[m] = dx
                    _stage_seconds.labels(str(s), "fwd").observe(
                        time.monotonic() - t0)
                elif s == P - 1:  # last stage B: publish the cotangent
                    if m not in dx_pending:
                        continue
                    publishes.append((cots, (s - 1, m),
                                      self._handoff(dx_pending.pop(m),
                                                    self.stages[s - 1]
                                                    .act_sharding)))
                    _stage_seconds.labels(str(s), "bwd").observe(
                        time.monotonic() - t0)
                else:  # B at stage s < P-1
                    if (s, m) not in cots:
                        continue
                    dy = cots.pop((s, m))
                    x_in = stash[s].pop(m)
                    if s == 0:
                        gaccs[s] = prog.bwd(params, x_in, dy, gaccs[s])
                    else:
                        dx, gaccs[s] = prog.bwd(params, x_in, dy,
                                                gaccs[s])
                        publishes.append((cots, (s - 1, m),
                                          self._handoff(dx,
                                                        self.stages[s - 1]
                                                        .act_sharding)))
                    _stage_seconds.labels(str(s), "bwd").observe(
                        time.monotonic() - t0)
                queues[s].popleft()
                progressed += 1
            # handoffs land at the NEXT tick: stages are separate
            # programs — nothing propagates the whole ring in one slot
            for store, key, value in publishes:
                store[key] = value
            if not progressed:
                raise RuntimeError(
                    "1F1B deadlock: no stage could make progress "
                    f"(queues={[len(q) for q in queues]})"
                )
            busy += progressed
            ticks += 1
        return ticks, busy

    def step(self, state: MpmdState, batch: dict
             ) -> tuple[MpmdState, dict]:
        tokens = batch["tokens"]  # [accum, step_batch, seq+1]
        A = int(tokens.shape[0])
        losses: list[jax.Array] = []
        gaccs = [p.zero_grads() for p in self.stages]
        stage_states = list(state.stages)
        ticks = busy = 0
        for r in range(A):
            t, b = self._run_round(stage_states, tokens[r], gaccs,
                                   losses)
            ticks += t
            busy += b
        P = self.num_stages
        bubble = 1.0 - busy / float(P * ticks) if ticks else 0.0
        self.last_bubble_frac = bubble
        _bubble_gauge.set(bubble)

        new_stages = []
        gn2s = []
        for s, prog in enumerate(self.stages):
            t0 = time.monotonic()
            params, opt_state, gn2 = prog.update(
                stage_states[s]["params"], stage_states[s]["opt_state"],
                gaccs[s],
            )
            _stage_seconds.labels(str(s), "update").observe(
                time.monotonic() - t0)
            new_stages.append({"params": params, "opt_state": opt_state})
            gn2s.append(gn2)
        last_mesh_repl = NamedSharding(self._meshes[-1], PartitionSpec())
        loss = jnp.stack(losses).mean()
        gn = jnp.sqrt(jnp.stack([
            jax.device_put(g, last_mesh_repl) for g in gn2s
        ]).sum())
        metrics = {"loss": loss.astype(jnp.float32),
                   "grad_norm": gn.astype(jnp.float32)}
        return MpmdState(step=state.step + 1,
                         stages=tuple(new_stages)), metrics


# -------------------------------------------------------- schedule gate


def estimate_stage_times(
    cfg: tfm.TransformerConfig, *, num_stages: int, step_batch: int,
    seq: int, microbatches: int = 0, hw: HardwareSpec | None = None,
) -> list[float]:
    """Analytic per-stage per-microbatch fwd+bwd seconds (PaLM 6N
    accounting + attention term, 3x for fwd:bwd 1:2): the heterogeneity
    input of the schedule gate — stage 0 carries the embedding gather,
    the last stage the LM-head matmul, so real configs are NOT
    uniform."""
    hw = hw or HardwareSpec.for_device()
    P = num_stages
    M = int(microbatches) or P
    mb_tokens = (step_batch // M) * seq
    layer_params = (cfg.param_count
                    - 2 * cfg.vocab_size * cfg.d_model) / cfg.n_layers
    per_layer = 6 * layer_params + 12 * seq * cfg.d_model
    chunk = cfg.n_layers // P
    times = []
    for s in range(P):
        flops_tok = chunk * per_layer
        if s == 0:
            flops_tok += 6 * cfg.d_model  # embedding gather + add
        if s == P - 1:
            flops_tok += 6 * cfg.vocab_size * cfg.d_model  # lm head
        times.append(flops_tok * mb_tokens
                     / (hw.peak_flops * hw.mxu_efficiency))
    return times


def choose_schedule(
    cfg: tfm.TransformerConfig, *, num_stages: int, step_batch: int,
    seq: int, microbatches: int = 0, interleave: int = 1,
    hw: HardwareSpec | None = None,
) -> tuple[str, dict]:
    """The MPMD-vs-SPMD gate (cost-model ranked): returns
    ``("mpmd"|"spmd", {name: est_step_s})``. MPMD wins whenever its
    independent-stage schedule beats the lockstep roll at the
    strategy's interleave depth — with the embed/LM-head stages making
    real configs heterogeneous, that is the common case; a deep
    interleave on near-uniform stages keeps SPMD."""
    hw = hw or HardwareSpec.for_device()
    times = estimate_stage_times(
        cfg, num_stages=num_stages, step_batch=step_batch, seq=seq,
        microbatches=microbatches, hw=hw,
    )
    dt_bytes = jnp.dtype(cfg.dtype).itemsize
    M = int(microbatches) or num_stages
    act = (step_batch // M) * seq * cfg.d_model * dt_bytes
    common = dict(num_stages=num_stages, num_microbatches=M,
                  activation_bytes=act, stage_time_s=tuple(times))
    ranked = rank_schedules(
        {
            "spmd": PipelineSchedule(
                kind=("spmd_interleaved" if interleave > 1
                      else "spmd_gpipe"),
                interleave=max(1, interleave), **common),
            "mpmd": PipelineSchedule(kind="mpmd_1f1b", **common),
        },
        flops=0.0, bytes_accessed=0.0, hw=hw,
    )
    ests = {name: est.est_step_s for name, est in ranked}
    return ranked[0][0], ests
