"""Elastic compile cache: serialized AOT executables keyed on
topology × model-shape × strategy fingerprint (DESIGN.md §17).

The residual per-failure cost after the warm-recovery path (PR 5) is
XLA recompilation: respawn/rendezvous/restore are ~0, but every
incarnation re-traces and re-compiles the same program — ~7s on CPU,
20-30s per real XLA:TPU compile (BENCH_r04 ``compile_s``). ElasWave
(PAPERS.md 2510.00606) closes this gap by making a membership change a
resharding event instead of a restart; the enabling piece is that the
program for the post-change topology must already exist.

This module is the trainer half of that cache:

- ``compile_fingerprint``: canonical key over everything that changes
  the executable — device topology, mesh axes, model config, strategy,
  abstract arg shapes/shardings, jax version + backend.
- ``serialize_executable_blob`` / ``load_executable_blob``: the
  ``jax.experimental.serialize_executable`` round-trip, wrapped in a
  CRC-checked envelope (a torn cache file must read as a miss, never a
  misloaded program).
- ``CompileCacheClient``: two layers — a node-local directory (shared
  by every incarnation and the parked standby on the host, the
  ``DLROVER_TPU_COMPILE_CACHE_DIR`` satellite) in front of the
  master-served store (``master/kv_store.py::CompileCacheService``)
  that survives node relaunches and feeds freshly joined hosts.
- ``load_or_compile``: the one call sites use — returns the loaded
  executable on a key hit (~0.1s) or compiles, publishes, and returns.
- ``FallbackPrecompiler``: the AOT-fallback-topology daemon — after a
  successful rendezvous it lowers and compiles the N−1/N+1 meshes in
  the background (reusing the offline AOT machinery of
  ``parallel/dry_run.py``: compile is host-side and needs no exclusive
  chip access) and publishes them, so the fallback executable is
  already resident when a node dies.

Module top level is jax-free on purpose: the metrics live in
``master/kv_store.py`` (one registration site serves both the master
and this client), and jax is imported lazily so control-plane processes
can import the fingerprint helpers without initializing a backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import threading
import time
import zlib
from typing import Any, Callable, Sequence

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.kv_store import (
    cache_hits_total,
    cache_misses_total,
    cache_puts_total,
    topology_tag,
)
from dlrover_tpu.telemetry.journal import get_journal, spawn_ctx

logger = get_logger(__name__)

_ENVELOPE_MAGIC = b"DLRTPU-AOT1"


def aot_cache_enabled() -> bool:
    """The executable cache rides ``serialize_executable`` (a pickled
    XLA executable + arg tree) — unlike the XLA persistent-cache-dir
    path it round-trips correctly on this CPU backend, so it defaults
    on everywhere. ``DLROVER_TPU_AOT_CACHE=0`` turns it off."""
    return envspec.get_bool(EnvKey.AOT_CACHE)


# ----------------------------------------------------------- fingerprinting


def _canonical(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return _canonical(dataclasses.asdict(value))
    if isinstance(value, dict):
        return {str(k): _canonical(v) for k, v in sorted(value.items())}
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)


def abstract_signature(tree: Any) -> list:
    """Shape/dtype/sharding-spec triples of a pytree of abstract args —
    the part of the fingerprint that pins the program's calling
    convention (a resharded batch dim or a changed accumulation factor
    must map to a different executable)."""
    import jax

    sig = []
    for leaf in jax.tree_util.tree_leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        sig.append([
            list(getattr(leaf, "shape", ())),
            str(getattr(leaf, "dtype", "?")),
            repr(spec) if spec is not None else "",
        ])
    return sig


def compile_fingerprint(
    *,
    num_nodes: int,
    total_devices: int,
    mesh_axes: dict,
    model: Any,
    strategy: Any,
    args_signature: Any = None,
    extra: dict | None = None,
) -> tuple[str, dict]:
    """(key, inputs): the cache key is ``<topology_tag>/<digest>`` and
    ``inputs`` is the raw material (stored beside the artifact so a
    reader verifies the match instead of trusting the digest)."""
    import jax

    strategy_json = (
        strategy.to_json() if hasattr(strategy, "to_json")
        else json.dumps(_canonical(strategy))
    )
    inputs = {
        "num_nodes": int(num_nodes),
        "total_devices": int(total_devices),
        "mesh_axes": _canonical(dict(mesh_axes)),
        "model": _canonical(model),
        "strategy": json.loads(strategy_json),
        "args": _canonical(args_signature) if args_signature else [],
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "extra": _canonical(extra or {}),
    }
    digest = hashlib.sha256(
        json.dumps(inputs, sort_keys=True).encode()
    ).hexdigest()[:32]
    tag = topology_tag(total_devices, num_nodes)
    return f"{tag}/{digest}", inputs


def stage_key(base_key: str, *, stage: int, num_stages: int, phase: str,
              interleave: int = 1) -> str:
    """Per-stage compile-cache key for an MPMD pipeline program
    (``parallel/mpmd.py``): the stage index, stage count, chunk config
    and program phase (``fwd``/``bwd``/``update``) ride IN the key —
    readable in cache listings and scannable by prefix. The ``pp``
    marker directly after the topology tag lets the agent's reshard
    decision count per-stage executables with one coverage scan
    (``<tag>/pp``); ``base_key`` must come from
    :func:`compile_fingerprint` with the same stage facts in ``extra``
    (the digest is what actually pins the program)."""
    tag, digest = base_key.split("/", 1)
    return (f"{tag}/pp{int(stage)}of{int(num_stages)}"
            f"v{max(1, int(interleave))}{phase}_{digest}")


def verify_key(base_key: str, *, depth: int) -> str:
    """Per-depth compile-cache key for a speculative-decode verify
    program (DESIGN.md §31): the draft depth rides IN the key — one
    entry per member of the pow2 depth ladder, scannable by prefix
    (``<tag>/sv``) just like the pipeline-stage keys. ``base_key``
    must come from :func:`compile_fingerprint` with the serving slot
    geometry in the strategy facts."""
    tag, digest = base_key.split("/", 1)
    return f"{tag}/sv{int(depth)}_{digest}"


# ------------------------------------------------------- artifact envelope


def executable_stats(compiled) -> dict:
    """Cheap post-compile facts worth caching beside the executable —
    today the program's FLOPs (XLA cost analysis), the number the live
    MFU gauge needs. Computed ONCE at compile time and stored in the
    envelope, so a warm cache load never re-lowers just to count."""
    from dlrover_tpu.utils.profiler import executable_flops

    flops = executable_flops(compiled)
    return {"flops": flops} if flops > 0 else {}


def serialize_executable_blob(compiled, inputs: dict,
                              stats: dict | None = None) -> bytes:
    """Envelope a compiled (AOT) executable: magic + crc32 + pickle of
    the serialize_executable triple, the fingerprint inputs, and
    post-compile ``stats`` (``executable_stats``; None = compute)."""
    from jax.experimental.serialize_executable import serialize

    payload, in_tree, out_tree = serialize(compiled)
    body = pickle.dumps({
        "exe": payload,
        "in_tree": in_tree,
        "out_tree": out_tree,
        "inputs": inputs,
        "stats": executable_stats(compiled) if stats is None else stats,
        "created": time.time(),
    })
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return _ENVELOPE_MAGIC + crc.to_bytes(4, "big") + body


def _parse_blob(blob: bytes) -> dict | None:
    """CRC-checked envelope record, or None on any damage."""
    try:
        if not blob.startswith(_ENVELOPE_MAGIC):
            return None
        off = len(_ENVELOPE_MAGIC)
        crc = int.from_bytes(blob[off:off + 4], "big")
        body = blob[off + 4:]
        if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            logger.warning("compile-cache artifact failed CRC; ignoring")
            return None
        record = pickle.loads(body)
        return record if isinstance(record, dict) else None
    except Exception as e:  # noqa: BLE001 - any damage is a miss
        logger.warning("compile-cache artifact unusable: %s", e)
        return None


def blob_stats(blob: bytes) -> dict:
    """The cached post-compile stats of an envelope ({} on damage or
    pre-stats blobs) — read WITHOUT deserializing the executable."""
    record = _parse_blob(blob)
    stats = (record or {}).get("stats")
    return dict(stats) if isinstance(stats, dict) else {}


def load_executable_blob(blob: bytes, expect_inputs: dict | None = None):
    """Deserialize an envelope back into a callable executable; returns
    None (a miss) on any damage or fingerprint-input mismatch."""
    from jax.experimental.serialize_executable import deserialize_and_load

    try:
        record = _parse_blob(blob)
        if record is None:
            return None
        if expect_inputs is not None and record.get("inputs") != \
                expect_inputs:
            # digest collision or stale writer: same key, different
            # program inputs — must read as a miss, never a wrong load
            logger.warning("compile-cache fingerprint mismatch; ignoring")
            return None
        return deserialize_and_load(
            record["exe"], record["in_tree"], record["out_tree"]
        )
    except Exception as e:  # noqa: BLE001 - any damage is a miss
        logger.warning("compile-cache artifact unusable: %s", e)
        return None


# ----------------------------------------------------------------- client


def default_local_dir() -> str:
    """Node-local artifact dir, shared by every incarnation and the
    parked standby of one job on the host. ``DLROVER_TPU_COMPILE_CACHE_DIR``
    overrides (the shared-dir escape hatch); the default is keyed by
    job name so co-hosted jobs cannot cross-hit."""
    explicit = os.environ.get(EnvKey.COMPILE_CACHE_SHARED_DIR)
    if explicit:
        return os.path.join(explicit, "aot")
    job = os.environ.get(EnvKey.JOB_NAME, "local") or "local"
    return os.path.join("/tmp", f"dlrover_tpu_aot_{job}")


class CompileCacheClient:
    """Two-layer artifact cache: node-local directory in front of the
    master store. ``master_client=None`` (standalone notebooks, tests)
    degrades to the local layer only."""

    def __init__(self, local_dir: str | None = None, master_client=None,
                 max_local_files: int = 32):
        self.local_dir = local_dir or default_local_dir()
        self.max_local_files = max_local_files
        self._master = master_client
        if self._master is None and os.environ.get(EnvKey.MASTER_ADDR):
            from dlrover_tpu.agent.master_client import MasterClient

            try:
                self._master = MasterClient.singleton()
            except RuntimeError:
                self._master = None

    def _path(self, key: str) -> str:
        return os.path.join(self.local_dir, key.replace("/", "_") + ".aot")

    def get(self, key: str) -> tuple[bytes, str] | None:
        """(blob, layer) or None. A local hit also refreshes mtime so
        LRU pruning keeps live topologies resident."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            os.utime(path, None)
            cache_hits_total.labels("local").inc()
            return blob, "local"
        except OSError:
            cache_misses_total.labels("local").inc()
        if self._master is not None:
            try:
                got = self._master.compile_cache_get(key)
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("master compile-cache get failed: %s", e)
                got = None
            if got is not None:
                blob = got[0]
                self._write_local(key, blob)
                return blob, "master"
        return None

    def put(self, key: str, blob: bytes, meta: dict | None = None) -> None:
        self._write_local(key, blob)
        cache_puts_total.labels("local").inc()
        if self._master is not None:
            try:
                self._master.compile_cache_put(key, blob, meta or {})
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("master compile-cache put failed: %s", e)

    def _write_local(self, key: str, blob: bytes) -> None:
        try:
            os.makedirs(self.local_dir, exist_ok=True)
            path = self._path(key)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # atomic: readers never see torn files
            self._prune()
        except OSError as e:
            logger.warning("compile-cache local write failed: %s", e)

    def _prune(self) -> None:
        try:
            files = [
                os.path.join(self.local_dir, f)
                for f in os.listdir(self.local_dir) if f.endswith(".aot")
            ]
            if len(files) <= self.max_local_files:
                return
            files.sort(key=lambda p: os.path.getmtime(p))
            for p in files[:len(files) - self.max_local_files]:
                os.unlink(p)
        except OSError:
            pass


def launder(tree: Any):
    """Rebuild a pytree of arrays through a jitted copy so every leaf
    owns proper per-device buffers.

    Required before handing a RESTORED state to a cached (deserialized)
    executable that donates its inputs: ``jax.device_put`` on the CPU
    backend may ADOPT an aligned host buffer (and ``device_get`` hands
    out views), so the per-device "copies" of a restored leaf can share
    one host allocation. A deserialized ``Compiled`` skips pjit's input
    re-staging and, with donation, performs its updates in place — each
    device's ``step + 1`` then lands on the SAME buffer and compounds
    (observed: +8 per call on an 8-device mesh, weight corruption when
    the buffers alias the shm arena). A jitted copy is exactly pjit's
    re-staging, paid once per restore instead of silently never.

    States produced by jit programs (``compiled.init``, a previous step
    call) are already properly staged; only host-built trees (snapshot
    restore, ``reshard_state`` output) need this.

    Leaves are grouped by device set before the jitted copy: an MPMD
    state's stages live on disjoint submeshes (``parallel/mpmd.py``)
    and one jitted program cannot span device sets — each group gets
    its own copy program, same re-staging guarantee.
    """
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: dict[tuple, list[int]] = {}
    for i, leaf in enumerate(leaves):
        devs = ()
        if isinstance(leaf, jax.Array):
            devs = tuple(sorted(
                d.id for d in getattr(leaf.sharding, "device_set", ())
            ))
        groups.setdefault(devs, []).append(i)
    copy = jax.jit(lambda xs: [jnp.copy(x) for x in xs])
    out = list(leaves)
    for idxs in groups.values():
        for i, copied in zip(idxs, copy([leaves[i] for i in idxs])):
            out[i] = copied
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------- load-or-compile


@dataclasses.dataclass
class AotStep:
    fn: Callable            # the executable, original pytree signature
    cache_hit: bool
    source: str             # "local" | "master" | "compiled" | "disabled"
    seconds: float          # load (hit) or compile+publish (miss) time
    key: str = ""
    # compiled-program FLOPs per call (XLA cost analysis) — computed at
    # compile time and cached in the envelope, so warm loads feed the
    # live MFU gauge without re-lowering; 0.0 when unknown
    flops: float = 0.0


def load_or_compile(
    key: str,
    inputs: dict,
    compile_fn: Callable[[], Any],
    cache: CompileCacheClient | None = None,
) -> AotStep:
    """The elastic-recovery compile path: serve the executable from the
    cache when this (topology, model, strategy, shapes) was compiled by
    ANY earlier incarnation — promoted standby, pre-failure fallback
    precompile, another node — else compile via ``compile_fn`` (which
    must return an AOT-compiled executable, i.e. ``jit(...).lower(
    *abstract).compile()``) and publish the result."""
    start = time.monotonic()
    if not aot_cache_enabled():
        compiled = compile_fn()
        return AotStep(fn=compiled, cache_hit=False, source="disabled",
                       seconds=time.monotonic() - start, key=key,
                       flops=executable_stats(compiled).get("flops", 0.0))
    cache = cache or CompileCacheClient()
    got = cache.get(key)
    if got is not None:
        loaded = load_executable_blob(got[0], expect_inputs=inputs)
        if loaded is not None:
            dur = time.monotonic() - start
            stats = blob_stats(got[0])
            get_journal().emit("compile_cache", dur=dur, hit=True,
                               layer=got[1], key=key,
                               remote_parent=spawn_ctx())
            logger.info("compile cache HIT (%s) for %s in %.2fs",
                        got[1], key, dur)
            return AotStep(fn=loaded, cache_hit=True, source=got[1],
                           seconds=dur, key=key,
                           flops=float(stats.get("flops", 0.0) or 0.0))
    compiled = compile_fn()
    stats = executable_stats(compiled)
    try:
        blob = serialize_executable_blob(compiled, inputs, stats=stats)
        cache.put(key, blob, meta={"inputs": inputs, "bytes": len(blob),
                                   "stats": stats})
    except Exception as e:  # noqa: BLE001 - publishing is best-effort
        logger.warning("compile-cache publish failed: %s", e)
    dur = time.monotonic() - start
    get_journal().emit("compile_cache", dur=dur, hit=False,
                       layer="none", key=key, remote_parent=spawn_ctx())
    logger.info("compile cache MISS for %s; compiled+published in %.2fs",
                key, dur)
    return AotStep(fn=compiled, cache_hit=False, source="compiled",
                   seconds=dur, key=key,
                   flops=float(stats.get("flops", 0.0) or 0.0))


# --------------------------------------------------- fallback pre-compiler


class FallbackPrecompiler:
    """Ahead-of-time compilation of the N−1/N+1 fallback topologies.

    After each successful rendezvous the trainer starts this daemon; it
    walks the candidate world sizes, asks ``build_fn(n_nodes)`` for
    ``(key, inputs, compile_fn)`` (None = that world is infeasible —
    indivisible mesh, no spare devices), compiles off the training path
    (XLA compilation is host-side work; like ``parallel/dry_run.py`` it
    needs no exclusive accelerator access), and publishes the artifact.
    When a node later dies, the surviving incarnation's
    ``load_or_compile`` for the N−1 world is a cache hit and recovery
    skips the cold compile entirely.

    ``budget_s`` bounds total background compile time; already-cached
    topologies are skipped so re-arming after every rendezvous is
    cheap.
    """

    def __init__(
        self,
        build_fn: Callable[[int], tuple[str, dict, Callable] | None],
        world_sizes: Sequence[int],
        cache: CompileCacheClient | None = None,
        budget_s: float = 600.0,
        delay_s: float = 1.0,
    ):
        self.build_fn = build_fn
        self.world_sizes = [n for n in world_sizes if n >= 1]
        self.cache = cache or CompileCacheClient()
        self.budget_s = budget_s
        self.delay_s = delay_s
        self.results: dict[int, str] = {}  # n_nodes -> outcome
        self._done = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "FallbackPrecompiler":
        self._thread = threading.Thread(
            target=self._run, name="aot-fallback", daemon=True
        )
        self._thread.start()
        return self

    def wait(self, timeout: float = 600.0) -> bool:
        return self._done.wait(timeout)

    def _run(self) -> None:
        # let the live incarnation's own first compile win the host's
        # compile threads before background work starts
        time.sleep(self.delay_s)
        deadline = time.monotonic() + self.budget_s
        try:
            for n in self.world_sizes:
                if time.monotonic() > deadline:
                    self.results[n] = "budget_exhausted"
                    continue
                t0 = time.monotonic()
                try:
                    built = self.build_fn(n)
                    if built is None:
                        self.results[n] = "infeasible"
                        continue
                    key, inputs, compile_fn = built
                    if self.cache.get(key) is not None:
                        self.results[n] = "already_cached"
                        continue
                    compiled = compile_fn()
                    blob = serialize_executable_blob(compiled, inputs)
                    self.cache.put(key, blob,
                                   meta={"inputs": inputs,
                                         "bytes": len(blob)})
                    self.results[n] = "published"
                    get_journal().emit(
                        "aot_fallback", dur=time.monotonic() - t0,
                        nodes=n, key=key, ok=True,
                    )
                    logger.info(
                        "fallback topology %d nodes pre-compiled and "
                        "published in %.1fs (%s)", n,
                        time.monotonic() - t0, key,
                    )
                except Exception as e:  # noqa: BLE001 - a failed fallback
                    # compile must never touch the live incarnation
                    self.results[n] = f"error: {e}"
                    get_journal().emit(
                        "aot_fallback", dur=time.monotonic() - t0,
                        nodes=n, ok=False,
                    )
                    logger.warning(
                        "fallback precompile for %d nodes failed: %s", n, e
                    )
        finally:
            self._done.set()
