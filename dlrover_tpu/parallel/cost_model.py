"""Analytic step-time estimation for strategy ranking.

Reference analog: ATorch scores candidate parallelization strategies by
throughput — BO over dry-run timings (atorch/auto/engine/
acceleration_engine.py:13) and an MIP tensor-planner
(atorch/auto/opt_lib/shard_planners/). The TPU-native equivalent needs no
trial training: XLA's AOT compile already yields the per-device FLOP
count, the bytes touched, and — in the HLO itself — every collective the
partitioner inserted. A roofline over those three numbers ranks
strategies in milliseconds.

    est_step_s = max(compute_t, hbm_t) + ici_t + dcn_t

where compute_t = flops / (peak x efficiency), hbm_t = bytes_accessed /
HBM bandwidth, and the collective terms come from summing the wire
volume of every all-reduce / all-gather / reduce-scatter / all-to-all /
collective-permute in the compiled module (each is per-device in an SPMD
program). max() models XLA's elementwise/matmul overlap; collectives are
charged unoverlapped — conservative, but uniform across candidates, and
ranking is all selection needs.
"""

from __future__ import annotations

import dataclasses
import re

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# v5e-class defaults (per chip). Absolute accuracy is not the goal —
# candidates are ranked against each other under the SAME constants.
_V5E = dict(peak_flops=1.97e14, hbm_bps=8.1e11, ici_bps=9.0e10,
            dcn_bps=6.25e9, mxu_efficiency=0.5)


@dataclasses.dataclass
class HardwareSpec:
    peak_flops: float = _V5E["peak_flops"]
    hbm_bps: float = _V5E["hbm_bps"]
    ici_bps: float = _V5E["ici_bps"]
    dcn_bps: float = _V5E["dcn_bps"]
    mxu_efficiency: float = _V5E["mxu_efficiency"]

    @classmethod
    def for_device(cls, device=None) -> "HardwareSpec":
        """Best-effort spec for the live backend; exact constants only
        matter for absolute estimates, never for ranking."""
        try:
            import jax

            device = device or jax.devices()[0]
        except Exception:  # noqa: BLE001
            return cls()
        if device.platform == "tpu":
            from dlrover_tpu.utils.profiler import PEAK_FLOPS

            peak = PEAK_FLOPS.get(device.device_kind)
            return cls(**({**_V5E, "peak_flops": peak} if peak else _V5E))
        # CPU / virtual test meshes: small constants so comm terms are
        # visible relative to compute in unit tests
        return cls(peak_flops=2e11, hbm_bps=5e10, ici_bps=2e10,
                   dcn_bps=2e9, mxu_efficiency=1.0)


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

# `%x = f32[128,64]{1,0} all-gather(...)` and the async `-start` forms.
# `-done` ops carry no new volume (same buffer) and don't match because
# the regex requires the opname to be followed directly by `(` or `-start(`.
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<type>\(?[a-z0-9]+\[[^=]*?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|ragged-all-to-all)(?:-start)?\(",
)
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_bytes(type_text: str) -> int:
    """Total bytes of every array shape in an HLO type expression
    (handles tuple types from async -start ops by taking the LARGEST
    member: start tuples alias (operand, result) of the same transfer)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(type_text):
        unit = _DTYPE_BYTES.get(dtype)
        if unit is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * unit)
    return max(sizes, default=0)


# Ring-algorithm wire multiplier per result byte: an all-reduce moves
# ~2x its tensor over the wire (reduce-scatter + all-gather phases);
# gather/scatter/a2a/permute move ~1x their larger side.
_WIRE_FACTOR = {
    "all-reduce": 2.0, "all-gather": 1.0, "reduce-scatter": 1.0,
    "all-to-all": 1.0, "collective-permute": 1.0,
    "ragged-all-to-all": 1.0,
}


def collective_bytes(hlo_text: str) -> dict[str, float]:
    """Per-device wire bytes by collective kind in a compiled module."""
    out: dict[str, float] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        op = m.group("op")
        nbytes = _shape_bytes(m.group("type")) * _WIRE_FACTOR[op]
        out[op] = out.get(op, 0.0) + nbytes
    return out


@dataclasses.dataclass
class PipelineSchedule:
    """Schedule shape of a pipelined candidate, for the bubble + p2p
    terms of :func:`estimate_step_time`.

    ``kind``:

    - ``"spmd_gpipe"`` / ``"spmd_interleaved"``: the single-program
      SPMD-roll schedules of ``parallel/pipeline.py`` — every ring step
      runs in lockstep, so each of the ``vM + P - 1`` slots is paced by
      the SLOWEST stage (at 1/v of its per-microbatch work when
      interleaved).
    - ``"mpmd_1f1b"``: the per-stage-program runtime
      (``parallel/mpmd.py``) — stages advance independently, so the
      fill/drain ramp pays each stage's own cost once and steady state
      is paced only by the slowest stage:
      ``T = (M - 1) * max_s(t_s) + sum_s(t_s)``.

    ``stage_time_s``: optional per-stage per-microbatch fwd+bwd times
    for heterogeneous stages; when absent, stages are assumed uniform
    and derived from the roofline work term. ``activation_bytes``: size
    of one microbatch's boundary activation — every stage handoff moves
    it once forward and once backward (the inter-stage p2p wire term
    the SPMD roll pays as collective-permutes inside the HLO and MPMD
    pays as explicit device-to-device transfers).
    """

    kind: str = "spmd_gpipe"
    num_stages: int = 1
    num_microbatches: int = 0
    interleave: int = 1
    activation_bytes: float = 0.0
    stage_time_s: tuple = ()

    def shape(self) -> tuple[int, int, int]:
        P = max(1, int(self.num_stages))
        M = int(self.num_microbatches) or P
        v = max(1, int(self.interleave))
        return P, M, v


def pipeline_schedule_time(schedule: PipelineSchedule,
                           work_s: float) -> tuple[float, float]:
    """(scheduled_s, bubble_s) for one step whose ideal (bubble-free)
    per-device work is ``work_s``.

    Uniform stages: every schedule degrades to
    ``work_s * (1 + (P-1)/(vM))`` — the classic bubble fraction
    ``(P-1)/(vM+P-1)`` of the total. Heterogeneous stages are where the
    kinds separate: the lockstep SPMD roll charges every slot at the
    slowest stage's pace, MPMD 1F1B pays other stages' cost only during
    fill/drain (the ISSUE's "stages with heterogeneous cost no longer
    pay the slowest stage's bubble").
    """
    P, M, v = schedule.shape()
    if P <= 1:
        return work_s, 0.0
    times = [float(t) for t in (schedule.stage_time_s or ())]
    if len(times) != P:
        # ``work_s`` is PER-DEVICE (one stage's work over all M
        # microbatches under pipeline sharding), so the uniform
        # per-microbatch stage time is work_s / M
        times = [work_s / M] * P
    t_max = max(times)
    # bubble-free floor: all stages perfectly overlapped, wall time set
    # by the busiest device
    ideal = M * t_max
    if schedule.kind == "mpmd_1f1b":
        sched = (M - 1) * t_max + sum(times)
    else:
        # lockstep SPMD roll: vM + P - 1 ring steps of 1/v-sized work,
        # each paced by the slowest stage
        sched = (v * M + P - 1) * t_max / v
    sched = max(sched, ideal)
    return sched, sched - ideal


@dataclasses.dataclass
class StepTimeEstimate:
    est_step_s: float = 0.0
    compute_s: float = 0.0
    hbm_s: float = 0.0
    ici_s: float = 0.0
    dcn_s: float = 0.0
    comm_bytes: float = 0.0
    by_collective: dict = dataclasses.field(default_factory=dict)
    # schedule-aware terms (0 / "" without a pipeline schedule)
    bubble_s: float = 0.0
    bubble_frac: float = 0.0
    p2p_s: float = 0.0
    schedule_kind: str = ""


def estimate_step_time(
    *,
    flops: float,
    bytes_accessed: float,
    hlo_text: str = "",
    hw: HardwareSpec | None = None,
    dcn_fraction: float = 0.0,
    schedule: PipelineSchedule | None = None,
) -> StepTimeEstimate:
    """Roofline step time from AOT compile artifacts (all per-device).

    ``dcn_fraction``: share of collective wire volume that crosses DCN
    instead of ICI. The HLO alone cannot tell which replica groups span
    hosts, so single-slice estimation (the default) charges everything
    at ICI bandwidth; callers ranking multi-slice candidates over a
    hybrid mesh pass the fraction their mesh layout implies (e.g. the
    dp-over-DCN share from parallel/mesh.py's hybrid builder).

    ``schedule``: pipeline schedule shape. Without it the estimate is
    schedule-blind (the pre-MPMD behavior, unchanged); with it the work
    term is stretched by the schedule's fill/drain bubble — lockstep
    for the SPMD roll, per-stage-independent for MPMD 1F1B — and an
    explicit inter-stage p2p wire term is charged for the boundary
    activations (2 crossings per microbatch per boundary: fwd
    activation + bwd cotangent).
    """
    hw = hw or HardwareSpec.for_device()
    by = collective_bytes(hlo_text) if hlo_text else {}
    comm = sum(by.values())
    compute_s = flops / (hw.peak_flops * hw.mxu_efficiency) if flops else 0.0
    hbm_s = bytes_accessed / hw.hbm_bps if bytes_accessed else 0.0
    ici_s = comm * (1.0 - dcn_fraction) / hw.ici_bps
    dcn_s = comm * dcn_fraction / hw.dcn_bps
    work_s = max(compute_s, hbm_s)
    bubble_s = 0.0
    bubble_frac = 0.0
    p2p_s = 0.0
    kind = ""
    if schedule is not None and schedule.num_stages > 1:
        P, M, _v = schedule.shape()
        kind = schedule.kind
        work_s, bubble_s = pipeline_schedule_time(schedule, work_s)
        bubble_frac = bubble_s / work_s if work_s else 0.0
        # a stage's device sends + receives one boundary activation per
        # microbatch in each direction (fwd activation, bwd cotangent)
        p2p_s = 2.0 * M * schedule.activation_bytes / hw.ici_bps
    return StepTimeEstimate(
        est_step_s=work_s + ici_s + dcn_s + p2p_s,
        compute_s=compute_s,
        hbm_s=hbm_s,
        ici_s=ici_s,
        dcn_s=dcn_s,
        comm_bytes=comm,
        by_collective=by,
        bubble_s=bubble_s,
        bubble_frac=bubble_frac,
        p2p_s=p2p_s,
        schedule_kind=kind,
    )


def rank_schedules(
    candidates: dict[str, PipelineSchedule],
    *,
    flops: float,
    bytes_accessed: float,
    hw: HardwareSpec | None = None,
) -> list[tuple[str, StepTimeEstimate]]:
    """Rank pipeline schedule candidates for ONE model geometry,
    fastest first — the MPMD-vs-SPMD gate (``parallel/mpmd.py``'s
    ``choose_schedule`` and the example's ``--schedule auto`` consume
    the head). Same constants across candidates, so only the schedule
    terms separate them."""
    hw = hw or HardwareSpec.for_device()
    ranked = [
        (name,
         estimate_step_time(flops=flops, bytes_accessed=bytes_accessed,
                            hw=hw, schedule=sched))
        for name, sched in candidates.items()
    ]
    ranked.sort(key=lambda pair: pair[1].est_step_s)
    return ranked
