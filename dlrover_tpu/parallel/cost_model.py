"""Analytic step-time estimation for strategy ranking.

Reference analog: ATorch scores candidate parallelization strategies by
throughput — BO over dry-run timings (atorch/auto/engine/
acceleration_engine.py:13) and an MIP tensor-planner
(atorch/auto/opt_lib/shard_planners/). The TPU-native equivalent needs no
trial training: XLA's AOT compile already yields the per-device FLOP
count, the bytes touched, and — in the HLO itself — every collective the
partitioner inserted. A roofline over those three numbers ranks
strategies in milliseconds.

    est_step_s = max(compute_t, hbm_t) + ici_t + dcn_t

where compute_t = flops / (peak x efficiency), hbm_t = bytes_accessed /
HBM bandwidth, and the collective terms come from summing the wire
volume of every all-reduce / all-gather / reduce-scatter / all-to-all /
collective-permute in the compiled module (each is per-device in an SPMD
program). max() models XLA's elementwise/matmul overlap; collectives are
charged unoverlapped — conservative, but uniform across candidates, and
ranking is all selection needs.
"""

from __future__ import annotations

import dataclasses
import re

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# v5e-class defaults (per chip). Absolute accuracy is not the goal —
# candidates are ranked against each other under the SAME constants.
_V5E = dict(peak_flops=1.97e14, hbm_bps=8.1e11, ici_bps=9.0e10,
            dcn_bps=6.25e9, mxu_efficiency=0.5)


@dataclasses.dataclass
class HardwareSpec:
    peak_flops: float = _V5E["peak_flops"]
    hbm_bps: float = _V5E["hbm_bps"]
    ici_bps: float = _V5E["ici_bps"]
    dcn_bps: float = _V5E["dcn_bps"]
    mxu_efficiency: float = _V5E["mxu_efficiency"]

    @classmethod
    def for_device(cls, device=None) -> "HardwareSpec":
        """Best-effort spec for the live backend; exact constants only
        matter for absolute estimates, never for ranking."""
        try:
            import jax

            device = device or jax.devices()[0]
        except Exception:  # noqa: BLE001
            return cls()
        if device.platform == "tpu":
            from dlrover_tpu.utils.profiler import PEAK_FLOPS

            peak = PEAK_FLOPS.get(device.device_kind)
            return cls(**({**_V5E, "peak_flops": peak} if peak else _V5E))
        # CPU / virtual test meshes: small constants so comm terms are
        # visible relative to compute in unit tests
        return cls(peak_flops=2e11, hbm_bps=5e10, ici_bps=2e10,
                   dcn_bps=2e9, mxu_efficiency=1.0)


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

# `%x = f32[128,64]{1,0} all-gather(...)` and the async `-start` forms.
# `-done` ops carry no new volume (same buffer) and don't match because
# the regex requires the opname to be followed directly by `(` or `-start(`.
_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<type>\(?[a-z0-9]+\[[^=]*?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|ragged-all-to-all)(?:-start)?\(",
)
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_bytes(type_text: str) -> int:
    """Total bytes of every array shape in an HLO type expression
    (handles tuple types from async -start ops by taking the LARGEST
    member: start tuples alias (operand, result) of the same transfer)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(type_text):
        unit = _DTYPE_BYTES.get(dtype)
        if unit is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * unit)
    return max(sizes, default=0)


# Ring-algorithm wire multiplier per result byte: an all-reduce moves
# ~2x its tensor over the wire (reduce-scatter + all-gather phases);
# gather/scatter/a2a/permute move ~1x their larger side.
_WIRE_FACTOR = {
    "all-reduce": 2.0, "all-gather": 1.0, "reduce-scatter": 1.0,
    "all-to-all": 1.0, "collective-permute": 1.0,
    "ragged-all-to-all": 1.0,
}


def collective_bytes(hlo_text: str) -> dict[str, float]:
    """Per-device wire bytes by collective kind in a compiled module."""
    out: dict[str, float] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        op = m.group("op")
        nbytes = _shape_bytes(m.group("type")) * _WIRE_FACTOR[op]
        out[op] = out.get(op, 0.0) + nbytes
    return out


@dataclasses.dataclass
class StepTimeEstimate:
    est_step_s: float = 0.0
    compute_s: float = 0.0
    hbm_s: float = 0.0
    ici_s: float = 0.0
    dcn_s: float = 0.0
    comm_bytes: float = 0.0
    by_collective: dict = dataclasses.field(default_factory=dict)


def estimate_step_time(
    *,
    flops: float,
    bytes_accessed: float,
    hlo_text: str = "",
    hw: HardwareSpec | None = None,
    dcn_fraction: float = 0.0,
) -> StepTimeEstimate:
    """Roofline step time from AOT compile artifacts (all per-device).

    ``dcn_fraction``: share of collective wire volume that crosses DCN
    instead of ICI. The HLO alone cannot tell which replica groups span
    hosts, so single-slice estimation (the default) charges everything
    at ICI bandwidth; callers ranking multi-slice candidates over a
    hybrid mesh pass the fraction their mesh layout implies (e.g. the
    dp-over-DCN share from parallel/mesh.py's hybrid builder).
    """
    hw = hw or HardwareSpec.for_device()
    by = collective_bytes(hlo_text) if hlo_text else {}
    comm = sum(by.values())
    compute_s = flops / (hw.peak_flops * hw.mxu_efficiency) if flops else 0.0
    hbm_s = bytes_accessed / hw.hbm_bps if bytes_accessed else 0.0
    ici_s = comm * (1.0 - dcn_fraction) / hw.ici_bps
    dcn_s = comm * dcn_fraction / hw.dcn_bps
    return StepTimeEstimate(
        est_step_s=max(compute_s, hbm_s) + ici_s + dcn_s,
        compute_s=compute_s,
        hbm_s=hbm_s,
        ici_s=ici_s,
        dcn_s=dcn_s,
        comm_bytes=comm,
        by_collective=by,
    )
