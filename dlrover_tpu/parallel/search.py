"""Measured-feedback strategy search: successive halving over the knob
cross-product, seeded by the roofline.

Reference analog: atorch's acceleration engine does not stop at analytic
estimates — it tunes with Bayesian optimization and combination search
over optimization-method combinations
(atorch/atorch/auto/engine/sg_algo/bayes_opt_sg.py:1,
sg_algo/combination_sg.py, sg_algo/hebo/). TPU-native shape: the
roofline (parallel/dry_run.py AOT compile + parallel/cost_model.py) is
the cheap seeding pass — it filters OOM candidates and orders the field
without touching the chips — then *successive halving* spends real timed
steps only on survivors, doubling measurement depth per rung while
halving the field, so the total chip time is ~2x a single candidate's
budget regardless of how many combinations the cross-product opened.

The search runs on the TARGET mesh (measured time on a virtual CPU mesh
says nothing about TPU); the winner and its measured step time feed the
strategy-engine service's measured history
(parallel/engine_service.py), which is how the tuning is shared across
jobs.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.dry_run import dry_run
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger(__name__)


def expand_candidates(
    base: Sequence[Strategy],
    *,
    remat: Sequence[str] = ("none", "dots_no_batch"),
    int8: Sequence[bool] = (False, True),
    grad_accum: Sequence[int] = (1, 2),
    model_remat: Sequence[tuple] | None = None,
) -> list[Strategy]:
    """Cross the base presets with the tunable knobs.

    ``model_remat`` entries are ``(remat_scan, remat_policy,
    remat_interval)`` tuples carried in ``extra`` (consumed by
    models/transformer.py resolve_config); ``None`` leaves the model's
    own remat configuration untouched.
    """
    out: list[Strategy] = []
    for s in base:
        for r in remat:
            for q in int8:
                for a in grad_accum:
                    for mr in (model_remat or (None,)):
                        extra = dict(s.extra)
                        if q:
                            extra["int8_matmuls"] = True
                        tag = f"r={r},int8={int(q)},acc={a}"
                        if mr is not None:
                            scan, policy, interval = mr
                            extra.update(
                                remat_scan=bool(scan),
                                remat_policy=policy,
                                remat_interval=int(interval),
                            )
                            tag += f",mr={policy}/{interval}"
                        out.append(dataclasses.replace(
                            s, name=f"{s.name}[{tag}]", remat=r,
                            grad_accum=a, extra=extra,
                        ))
    return out


def _reshape_accum(batch: Any, accum: int) -> Any | None:
    """[A0, B, ...] example batch -> [accum, A0*B/accum, ...] or None
    when the global batch doesn't divide."""
    def one(a):
        a = np.asarray(a)
        total = a.shape[0] * a.shape[1]
        if total % accum:
            return None
        return a.reshape(accum, total // accum, *a.shape[2:])

    leaves = [one(a) for a in jax.tree_util.tree_leaves(batch)]
    if any(v is None for v in leaves):
        return None
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(batch), leaves
    )


def measured_search(
    *,
    loss_fn_for: Callable,     # (strategy, mesh) -> loss_fn
    init_params_fn,
    logical_params,
    optimizer,
    example_batch,             # pytree of np arrays [accum, batch, ...]
    devices: Sequence | None = None,
    candidates: Sequence[Strategy] | None = None,
    expand: bool = True,
    top_k: int = 6,
    rungs: Sequence[int] = (3, 8),
    keep: float = 0.5,
    hbm_capacity_bytes: int | None = None,
    hw=None,
    engine_client=None,
    engine_key: dict | None = None,
    surrogate_rounds: int = 1,
    surrogate_proposals: int = 2,
) -> tuple[Strategy, dict]:
    """Roofline-seeded successive halving + GP-surrogate acquisition;
    returns (winner, report).

    After the halving rungs, a Gaussian-process surrogate
    (parallel/surrogate.py — the bayes_opt_sg.py analog) is fitted on
    EVERY timed measurement and proposes up to ``surrogate_proposals``
    configs per round from the candidates the roofline seeding ranked
    OUTSIDE the measured top-k; each proposal is measured at the final
    rung depth and can take the win. ``surrogate_rounds=0`` disables.

    Report: ``{"roofline": [(name, est_s, fits)], "rungs":
    [{name: measured_s}], "roofline_pick": name, "surrogate":
    [{name: measured_s}], "winner": name, "winner_step_s": s}``. When
    ``engine_client`` is given, every measurement is reported to the
    engine service — the service's observation store is the persisted
    posterior a later search warm-starts from — and the winner feeds
    the measured history that serves ``propose(objective="fastest")``.
    """
    from dlrover_tpu.parallel.auto import (
        default_candidates,
        device_hbm_bytes,
    )
    from dlrover_tpu.trainer.train_step import compile_train

    devices = list(devices if devices is not None else jax.devices())
    if candidates is None:
        candidates = default_candidates(len(devices))
    if expand:
        candidates = expand_candidates(candidates)
    if hbm_capacity_bytes is None:
        hbm_capacity_bytes = device_hbm_bytes(devices[0])

    def build(strategy: Strategy):
        mesh = strategy.build_mesh(devices)
        compiled = compile_train(
            strategy=strategy,
            mesh=mesh,
            loss_fn=loss_fn_for(strategy, mesh),
            init_params_fn=init_params_fn,
            logical_params=logical_params,
            optimizer=optimizer,
        )
        return compiled

    def abstract_args(strategy: Strategy, compiled, batch):
        state = jax.eval_shape(compiled.init, jax.random.PRNGKey(0))
        state = jax.tree.map(
            lambda leaf, s: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=s
            ),
            state, compiled.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
        b = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                np.shape(a), np.asarray(a).dtype,
                sharding=compiled.batch_sharding,
            ),
            batch,
        )
        return state, b

    # ---- seeding pass: AOT roofline, filters OOM / non-dividing accum
    seeded: list[tuple[Strategy, Any, Any, float]] = []
    roofline_rows = []
    for s in candidates:
        batch = _reshape_accum(example_batch, max(1, s.grad_accum))
        if batch is None:
            roofline_rows.append((s.name, math.inf, False))
            continue
        try:
            compiled = build(s)
        except Exception as e:  # noqa: BLE001 - candidate, not crash
            logger.info("candidate %s failed to build: %s", s.name, e)
            roofline_rows.append((s.name, math.inf, False))
            continue
        r = dry_run(
            lambda _s, c=compiled, b=batch: (
                c.step, abstract_args(_s, c, b)
            ),
            s, hw=hw,
        )
        fits = r.fits(hbm_capacity_bytes) if hbm_capacity_bytes else r.ok
        roofline_rows.append((s.name, r.est_step_s or math.inf, fits))
        if fits:
            seeded.append((s, compiled, batch, r.est_step_s or math.inf))
    if not seeded:
        raise RuntimeError(
            "measured_search: no candidate compiled and fit memory"
        )
    seeded.sort(key=lambda t: t[3])
    roofline_pick = seeded[0][0].name
    field = seeded[:top_k]

    # ---- successive halving with real timed steps
    rung_rows: list[dict] = []
    measured: dict[str, float] = {}
    for depth in rungs:
        row: dict[str, float] = {}
        for s, compiled, batch, _ in field:
            try:
                t = _time_steps(compiled, batch, depth)
            except Exception as e:  # noqa: BLE001 - drop the candidate
                logger.info("candidate %s failed measuring: %s",
                            s.name, e)
                t = math.inf
            row[s.name] = t
            measured[s.name] = t
        rung_rows.append(row)
        field.sort(key=lambda item: row[item[0].name])
        field = [f for f in field
                 if math.isfinite(row[f[0].name])] or field[:1]
        survivors = max(1, int(math.ceil(len(field) * keep)))
        field = field[:survivors]
        if len(field) == 1:
            break
    winner = field[0][0]
    winner_s = measured[winner.name]

    # ---- surrogate acquisition: fit a GP on every timed result and
    # measure the configs it says are promising among the seeded
    # candidates halving never touched (top_k cut them before any
    # measurement). by_name carries their already-compiled programs.
    surrogate_rows: list[dict] = []
    if surrogate_rounds > 0:
        from dlrover_tpu.parallel.surrogate import surrogate_propose

        by_name = {s.name: (s, compiled, batch)
                   for s, compiled, batch, _ in seeded}
        pool = [s for s, _, _, _ in seeded]
        for _ in range(surrogate_rounds):
            observations = [
                (by_name[n][0], t) for n, t in measured.items()
                if n in by_name
            ]
            try:
                proposals = surrogate_propose(
                    observations, pool, n=surrogate_proposals
                )
            except Exception as e:  # noqa: BLE001 - optional layer
                logger.warning("surrogate propose failed: %s", e)
                break
            if not proposals:
                break
            row: dict[str, float] = {}
            for s, ei in proposals:
                _, compiled, batch = by_name[s.name]
                try:
                    t = _time_steps(compiled, batch, rungs[-1])
                except Exception as e:  # noqa: BLE001 - drop it
                    logger.info("surrogate pick %s failed: %s",
                                s.name, e)
                    t = math.inf
                row[s.name] = t
                measured[s.name] = t
                logger.info("surrogate pick %s (EI %.3g): %.4fs",
                            s.name, ei, t)
                if t < winner_s:
                    winner, winner_s = s, t
            surrogate_rows.append(row)

    report = {
        "roofline": roofline_rows,
        "roofline_pick": roofline_pick,
        "rungs": rung_rows,
        "surrogate": surrogate_rows,
        "winner": winner.name,
        "winner_step_s": winner_s,
    }
    logger.info(
        "measured search: winner %s at %.4fs/step (roofline pick was "
        "%s)", winner.name, winner_s, roofline_pick,
    )
    if engine_client is not None:
        # every finite measurement feeds the service's observation
        # store (the persisted surrogate posterior); the service keeps
        # the fastest as the measured-history winner. Client + service
        # normalize through autopilot/history.py's ONE fingerprint
        # vocabulary (shape_key + canonical strategy JSON), so the
        # winner written here is exactly what a later autopilot
        # planner's history lookup reads back (pinned by
        # tests/test_autopilot.py).
        name_to_strategy = {s.name: s for s, _, _, _ in seeded}
        try:
            for cand_name, t in measured.items():
                if not math.isfinite(t):
                    continue
                cand = name_to_strategy.get(cand_name)
                if cand is None:
                    continue
                engine_client.report_measurement(
                    strategy=cand, step_time_s=t, **(engine_key or {}),
                )
        except Exception as e:  # noqa: BLE001 - telemetry, not critical
            logger.warning("engine measurement report failed: %s", e)
    return winner, report


def _time_steps(compiled, batch, steps: int) -> float:
    """Median-of-run wall time per global step (loss device_get is the
    sync point — block_until_ready does not block on remote platforms)."""
    state = compiled.init(jax.random.PRNGKey(0))
    step_batch = jax.device_put(batch, compiled.batch_sharding)
    state, m = compiled.step(state, step_batch)  # compile + warmup
    float(jax.device_get(m["loss"]))
    t0 = time.monotonic()
    for _ in range(steps):
        state, m = compiled.step(state, step_batch)
    float(jax.device_get(m["loss"]))
    return (time.monotonic() - t0) / steps
