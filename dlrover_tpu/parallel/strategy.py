"""Acceleration strategies: the ``auto_accelerate`` analog.

Reference analog: atorch/atorch/auto/accelerate.py:406 (auto_accelerate),
auto/strategy.py (strategy serialization), auto/opt_lib/** (the optimization
library: FSDP/TP/AMP/checkpoint wrappers). In torch each optimization is an
imperative model transform; on TPU the whole bundle reduces to declarative
inputs of one ``jax.jit``:

- parallel "groups"      -> mesh axis sizes (MeshSpec)
- FSDP/TP/SP wrappers    -> logical->mesh sharding rules
- AMP                    -> compute dtype (bf16 matmuls, f32 reductions)
- activation checkpoint  -> jax.checkpoint policy applied to the step fn
- ZeRO optimizer states  -> optimizer-state sharding rules (same table)

A Strategy is a plain serializable record, so it can be saved next to a
checkpoint and reloaded (reference: load_strategy, accelerate.py:467).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import jax

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.mesh import MeshSpec, build_mesh
from dlrover_tpu.parallel.partition import (
    Rules,
    tree_shardings,
    tree_specs,
)

logger = get_logger(__name__)

# jax.checkpoint policies by name (serialization-friendly).
REMAT_POLICIES = {
    "none": None,
    "full": jax.checkpoint_policies.nothing_saveable,
    "dots": jax.checkpoint_policies.dots_saveable,
    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "everything": jax.checkpoint_policies.everything_saveable,
}


@dataclasses.dataclass
class Strategy:
    """One complete acceleration plan for a model."""

    name: str = "dp"
    mesh_axes: dict[str, int] = dataclasses.field(
        default_factory=lambda: {"data": -1}
    )
    dcn_axes: dict[str, int] = dataclasses.field(default_factory=dict)
    # logical axis name -> mesh axis (str), tuple of axes, or None
    rules: list[list] = dataclasses.field(default_factory=list)
    compute_dtype: str = "bfloat16"
    # master weights: params (and optimizer states) stay f32; the bf16
    # casts happen at use sites inside the model (mixed precision with
    # master weights — the AMP shape that is safe by default on TPU)
    param_dtype: str = "float32"
    remat: str = "none"  # key into REMAT_POLICIES
    grad_accum: int = 1
    extra: dict = dataclasses.field(default_factory=dict)

    # ------------------------------------------------------------- building

    def mesh_spec(self) -> MeshSpec:
        return MeshSpec(axes=dict(self.mesh_axes), dcn_axes=dict(self.dcn_axes))

    def build_mesh(self, devices=None) -> jax.sharding.Mesh:
        return build_mesh(self.mesh_spec(), devices=devices)

    def rule_table(self) -> Rules:
        return [
            (name, tuple(ax) if isinstance(ax, list) else ax)
            for name, ax in self.rules
        ]

    def shardings(self, logical_tree: Any, mesh) -> Any:
        return tree_shardings(logical_tree, self.rule_table(), mesh)

    def specs(self, logical_tree: Any, mesh) -> Any:
        return tree_specs(logical_tree, self.rule_table(), mesh)

    def remat_policy(self):
        if self.remat not in REMAT_POLICIES:
            raise ValueError(
                f"unknown remat policy {self.remat!r}; "
                f"known: {sorted(REMAT_POLICIES)}"
            )
        return REMAT_POLICIES[self.remat]

    # --------------------------------------------------------- serialization

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "Strategy":
        return cls(**json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "Strategy":
        with open(path) as f:
            return cls.from_json(f.read())


# Rule fragments shared by the presets. Logical names are the vocabulary the
# bundled models use (models/transformer.py); user models may extend freely.
_FSDP_RULES = [
    ["embed", "fsdp"],          # shard the big embed dim of every weight
    ["vocab", "fsdp"],
    ["batch", ["data", "fsdp"]],
]
_TP_RULES = [
    ["heads", "tensor"],        # attention heads across tensor axis
    ["mlp", "tensor"],          # ffn hidden dim across tensor axis
    ["vocab", "tensor"],        # vocab-parallel embedding / lm head
    ["kv_heads", "tensor"],
]
_SP_RULES = [
    ["sequence", "sequence"],   # activation sequence dim across seq axis
]
_EP_RULES = [
    ["expert", "expert"],
]


def dp(num_devices: int = -1, grad_compression: bool = False) -> Strategy:
    """Pure data parallel: params replicated, batch split.

    ``grad_compression`` ships the gradient reduce as int8 (reference:
    ATorch's quant-reduce comm compression) — worthwhile when the data
    axis spans DCN, where that reduce is the slowest hop of the step.
    """
    return Strategy(
        name="dp",
        mesh_axes={"data": num_devices},
        rules=[["batch", ["data", "fsdp"]]],
        extra={"grad_compression": "int8"} if grad_compression else {},
    )


def zero1(data_size: int = -1) -> Strategy:
    """ZeRO-1: pure data parallelism with SHARDED optimizer state.

    Params and grads stay replicated (one psum, like dp); the Adam
    moments shard over the data axis, cutting optimizer memory by the
    axis size — the middle ground when params fit HBM but params+Adam
    don't, without fsdp's per-layer param gathers. XLA inserts the
    update all-gather from the output shardings; the math is bit-for-dp
    (it is a layout choice, not an algorithm change). Reference:
    atorch Zero1Optimization (auto/opt_lib/zero_optimization.py:115).
    """
    return Strategy(
        name="zero1",
        mesh_axes={"data": data_size},
        rules=[["batch", "data"]],
        extra={"zero1": True},
    )


def zero2(data_size: int = -1) -> Strategy:
    """ZeRO-2: ZeRO-1 plus reduce-scattered gradients.

    Gradients are constrained to the optimizer state's sharding before
    the update, so XLA lowers the cross-data-axis gradient sum to a
    reduce_scatter (half the wire bytes of an all-reduce) and each
    device holds only its gradient shard while updating its moment
    shard; the update all-gather restores replicated params. Same math
    as dp/zero1. Reference: atorch Zero2Optimization
    (auto/opt_lib/zero_optimization.py:158).
    """
    return Strategy(
        name="zero2",
        mesh_axes={"data": data_size},
        rules=[["batch", "data"]],
        extra={"zero1": True, "zero2": True},
    )


def fsdp(fsdp_size: int = -1, remat: str = "dots",
         int8: bool = False) -> Strategy:
    """ZeRO-3-style fully sharded data parallel (param gather per layer).

    ``int8`` routes the layer-stack projections through the MXU's int8
    path (ops/quantization.py) — the fp8/TransformerEngine-optimization
    analog. Measured on v5e: 1.2x forward / 1.6x grad step at
    d_model=4096; a LOSS at gpt2-small-class geometry where the step is
    HBM-bandwidth-bound, so it is opt-in on the large-model strategies
    rather than a default.
    """
    return Strategy(
        name="fsdp",
        mesh_axes={"fsdp": fsdp_size},
        rules=list(_FSDP_RULES),
        remat=remat,
        extra={"int8_matmuls": True} if int8 else {},
    )


def tp(tensor_size: int = 2, data_size: int = -1,
       remat: str = "none") -> Strategy:
    """Megatron-style tensor parallel × data parallel."""
    return Strategy(
        name="tp",
        mesh_axes={"data": data_size, "tensor": tensor_size},
        rules=[["batch", ["data", "fsdp"]]] + [list(r) for r in _TP_RULES],
        remat=remat,
    )


def fsdp_tp(tensor_size: int = 2, fsdp_size: int = -1,
            remat: str = "dots", int8: bool = False) -> Strategy:
    """2D: FSDP across hosts × TP inside the fast ICI neighborhood.

    ``int8``: see :func:`fsdp`.
    """
    return Strategy(
        name="fsdp_tp",
        mesh_axes={"fsdp": fsdp_size, "tensor": tensor_size},
        rules=list(_FSDP_RULES) + [list(r) for r in _TP_RULES],
        remat=remat,
        extra={"int8_matmuls": True} if int8 else {},
    )


def long_context(sequence_size: int = 2, data_size: int = -1,
                 remat: str = "dots") -> Strategy:
    """Sequence/context parallel for long sequences (ring attention)."""
    return Strategy(
        name="long_context",
        mesh_axes={"data": data_size, "sequence": sequence_size},
        rules=[["batch", ["data", "fsdp"]]] + [list(r) for r in _SP_RULES],
        remat=remat,
        extra={"attention": "ring"},
    )


def ulysses(sequence_size: int = 2, data_size: int = -1,
            remat: str = "dots") -> Strategy:
    """Sequence parallel via all-to-all head redistribution
    (ops/ulysses.py) — the alternative to ring attention when the head
    count comfortably divides by the sequence axis."""
    return Strategy(
        name="ulysses",
        mesh_axes={"data": data_size, "sequence": sequence_size},
        rules=[["batch", ["data", "fsdp"]]] + [list(r) for r in _SP_RULES],
        remat=remat,
        extra={"attention": "ulysses"},
    )


def sliding_window(window: int = 1024, data_size: int = -1,
                   remat: str = "dots") -> Strategy:
    """Local (sliding-window) attention via the splash kernel.

    Single-device long-context alternative to ring attention: each query
    sees the last ``window`` keys and the sparse kernel skips masked
    blocks, so step cost is O(S * window) instead of O(S^2).
    """
    return Strategy(
        name="sliding_window",
        mesh_axes={"data": data_size},
        rules=[["batch", ["data", "fsdp"]]],
        remat=remat,
        extra={"attention": "splash", "attention_window": int(window)},
    )


def pipeline(pipeline_size: int = 2, data_size: int = -1,
             microbatches: int = 0, remat: str = "none",
             interleave: int = 1) -> Strategy:
    """Pipeline over the "pipeline" axis × data parallel.

    The layer-stack dim shards over the pipeline axis so each stage's
    weights (and their optimizer states — ZeRO for free) live only on that
    stage's devices; parallel/pipeline.py supplies the schedule:
    ``interleave=1`` GPipe, ``>1`` the Megatron-interleaved circular
    schedule (1F1B-class bubble, reference
    atorch/auto/opt_lib/pipeline_parallel_optimization.py:56).
    """
    return Strategy(
        name="pipeline",
        mesh_axes={"data": data_size, "pipeline": pipeline_size},
        rules=[
            ["batch", ["data", "fsdp"]],
            ["layers", "pipeline"],
            ["stages", "pipeline"],
        ],
        remat=remat,
        extra={
            "pipeline_stages": pipeline_size,
            "pipeline_microbatches": microbatches,
            "pipeline_interleave": interleave,
        },
    )


def mpmd(pipeline_size: int = 2, microbatches: int = 0) -> Strategy:
    """MPMD pipeline (parallel/mpmd.py): per-stage compiled programs on
    disjoint device submeshes, host-side 1F1B schedule, ZeRO-sharded
    weight update per stage (2412.14374 + 2004.13336).

    Unlike the SPMD presets this strategy does NOT describe one mesh:
    each stage builds its own ``{"data": devices/P}`` submesh and the
    optimizer state shards over that data axis (``zero1`` semantics per
    stage). ``mesh_axes`` here is only the batch-sharding world of
    stage 0. Per-stage programs are what buy per-stage elastic
    recovery: a stage failure recompiles/reloads only that stage.
    """
    return Strategy(
        name="mpmd",
        mesh_axes={"data": -1},
        rules=[["batch", "data"]],
        extra={
            "mpmd": True,
            "zero1": True,
            "pipeline_stages": pipeline_size,
            "pipeline_microbatches": microbatches,
            "pipeline_interleave": 1,
        },
    )


def mixed(pipeline_size: int = 2, tensor_size: int = 2,
          data_size: int = -1, microbatches: int = 0,
          remat: str = "none", interleave: int = 1) -> Strategy:
    """3D: GPipe pipeline × Megatron-style tensor × data parallel.

    Reference analog: MixedParallelOptimization's TP+PP+DP combination
    (atorch/atorch/auto/opt_lib/mixed_parallel_optimization.py:32) — here
    it is just the union of the pipeline and tensor rule tables over one
    mesh; XLA derives the collectives for both axes from the shardings.
    """
    return Strategy(
        name="mixed",
        mesh_axes={
            "data": data_size,
            "pipeline": pipeline_size,
            "tensor": tensor_size,
        },
        rules=[
            ["batch", ["data", "fsdp"]],
            ["layers", "pipeline"],
            ["stages", "pipeline"],
        ] + [list(r) for r in _TP_RULES],
        remat=remat,
        extra={
            "pipeline_stages": pipeline_size,
            "pipeline_microbatches": microbatches,
            "pipeline_interleave": interleave,
        },
    )


def moe(expert_size: int = 2, data_size: int = -1) -> Strategy:
    """Expert parallel: experts split over the expert axis."""
    return Strategy(
        name="moe",
        mesh_axes={"data": data_size, "expert": expert_size},
        rules=[["batch", ["data", "fsdp"]]] + [list(r) for r in _EP_RULES],
    )


PRESETS = {
    "dp": dp,
    "zero1": zero1,
    "zero2": zero2,
    "fsdp": fsdp,
    "tp": tp,
    "fsdp_tp": fsdp_tp,
    "long_context": long_context,
    "ulysses": ulysses,
    "sliding_window": sliding_window,
    "pipeline": pipeline,
    "mpmd": mpmd,
    "mixed": mixed,
    "moe": moe,
}
