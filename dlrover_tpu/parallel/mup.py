"""muP: maximal-update parametrization for width-transferable HPs.

Reference analog: atorch/atorch/mup/ (infshape/init/optim/module — the
torch port of Yang & Hu's muP). What muP buys: tune learning rate etc. on
a small-width proxy model, transfer to the full width unchanged.

The standard muP-Adam recipe, expressed the JAX way (pure functions, no
module surgery):

- hidden "matrix-like" weights (both dims scale with width): LR scaled by
  ``base_width / width``
- "vector-like" params (embeddings, norms, biases — one or zero dims
  scale): LR unscaled
- readout (lm_head): forward output multiplied by ``base_width / width``
- attention logits scaled ``1/d_head`` instead of ``1/sqrt(d_head)``

Model integration: set ``TransformerConfig.mup_base_width``; the forward
pass applies the readout/attention scalings, and ``mup_optimizer`` wraps
any optax optimizer with the per-leaf LR table derived from the logical
axes (the same annotations the sharding rules use — "matrix-like" is
exactly "has an 'embed'/'mlp'/'heads' input dim").
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import optax

# logical axis names that scale with model width
_WIDTH_AXES = {"embed", "mlp", "heads", "kv_heads"}


def lr_scale_tree(logical_axes: Any, base_width: int, width: int) -> Any:
    """Per-leaf LR multipliers from the logical-axis annotations.

    A leaf is matrix-like (scaled ``base/width``) when at least TWO of its
    dims scale with width — e.g. wq [embed, heads, d], w_down [mlp,
    embed], lm_head [embed, vocab]... lm_head is handled by the forward
    readout multiplier instead, but its fan-in still scales, so muP-Adam
    scales its LR too (both-dims rule with vocab treated as non-width).
    """
    ratio = base_width / width

    def leaf_scale(axes: tuple) -> float:
        width_dims = sum(1 for a in axes if a in _WIDTH_AXES)
        return ratio if width_dims >= 2 else (
            ratio if width_dims == 1 and "vocab" in axes
            and axes[0] == "embed" else 1.0
        )

    return jax.tree.map(
        leaf_scale,
        logical_axes,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x),
    )


class _ScaleByTreeState(NamedTuple):
    pass


def scale_by_tree(scales: Any) -> optax.GradientTransformation:
    """Multiply each update leaf by its entry in ``scales``."""

    def init_fn(params):
        del params
        return _ScaleByTreeState()

    def update_fn(updates, state, params=None):
        del params
        return jax.tree.map(
            lambda u, s: u * s, updates, scales
        ), state

    return optax.GradientTransformation(init_fn, update_fn)


def mup_optimizer(
    base_optimizer: optax.GradientTransformation,
    logical_axes: Any,
    base_width: int,
    width: int,
) -> optax.GradientTransformation:
    """Wrap an optimizer with muP per-leaf LR scaling.

    ``logical_axes`` is the model's axis-annotation tree
    (models.transformer.logical_axes); widths are d_model values.
    """
    return optax.chain(
        base_optimizer,
        scale_by_tree(lr_scale_tree(logical_axes, base_width, width)),
    )
