"""Dry-run strategy evaluation via AOT compilation statistics.

Reference analog: ATorch's analyser + dry-runner
(atorch/atorch/auto/analyser/analyser.py:14, auto/dry_runner/dry_runner.py)
profile candidate strategies by actually running them. XLA gives this for
free ahead-of-time: ``jit(...).lower(...).compile()`` yields per-program
memory and FLOP analyses without executing a step, so strategy selection
costs seconds of compile instead of minutes of training.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class DryRunReport:
    strategy_name: str
    ok: bool
    error: str = ""
    flops: float = 0.0
    hbm_bytes: int = 0          # peak per-device memory if known
    argument_bytes: int = 0
    output_bytes: int = 0
    compile_seconds: float = 0.0
    bytes_accessed: float = 0.0  # cost-analysis HBM traffic (per device)
    comm_bytes: float = 0.0      # collective wire volume (per device)
    est_step_s: float = 0.0      # roofline estimate (parallel/cost_model)

    def fits(self, hbm_capacity_bytes: int) -> bool:
        return self.ok and (
            self.hbm_bytes == 0 or self.hbm_bytes <= hbm_capacity_bytes
        )


def dry_run(
    build_step: Callable[[Any], tuple[Callable, tuple]],
    strategy: Any,
    hw=None,
) -> DryRunReport:
    """Compile a strategy's train step and harvest cost/memory analyses.

    ``build_step(strategy) -> (jitted_fn, abstract_args)`` so the caller
    controls model/optimizer wiring; abstract args come from
    ``jax.eval_shape``-style ShapeDtypeStructs with shardings attached.
    ``hw`` (cost_model.HardwareSpec) parameterizes the roofline step-time
    estimate; default = live backend.
    """
    import time

    start = time.monotonic()
    try:
        fn, args = build_step(strategy)
        compiled = fn.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 - a failing candidate is a result
        return DryRunReport(
            strategy_name=getattr(strategy, "name", "?"),
            ok=False, error=f"{type(e).__name__}: {e}",
            compile_seconds=time.monotonic() - start,
        )
    report = DryRunReport(
        strategy_name=getattr(strategy, "name", "?"),
        ok=True,
        compile_seconds=time.monotonic() - start,
    )
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            # newer jax returns one dict per executable module
            cost = cost[0] if cost else None
        if cost:
            report.flops = float(cost.get("flops", 0.0))
            report.bytes_accessed = float(cost.get("bytes accessed", 0.0))
    except Exception:  # noqa: BLE001 - backends may not implement this
        pass
    try:
        # throughput ranking: roofline over FLOPs + HBM traffic + the
        # collectives the partitioner inserted (read from the HLO itself)
        from dlrover_tpu.parallel.cost_model import estimate_step_time

        est = estimate_step_time(
            flops=report.flops,
            bytes_accessed=report.bytes_accessed,
            hlo_text=compiled.as_text(),
            hw=hw,
        )
        report.est_step_s = est.est_step_s
        report.comm_bytes = est.comm_bytes
    except Exception:  # noqa: BLE001 - estimate is advisory
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            # donated args alias outputs (the train state is donated), so
            # argument + output double-counts it; peak live set is temps
            # plus the larger of the two plus any non-aliased remainder
            arg = int(getattr(mem, "argument_size_in_bytes", 0))
            out = int(getattr(mem, "output_size_in_bytes", 0))
            alias = getattr(mem, "alias_size_in_bytes", None)
            if alias is None:
                # backend doesn't report aliasing: assume donation (the
                # train-step convention here) aliases the smaller side
                alias = min(arg, out)
            report.hbm_bytes = int(
                getattr(mem, "temp_size_in_bytes", 0) + arg + out - int(alias)
            )
            report.argument_bytes = arg
            report.output_bytes = out
    except Exception:  # noqa: BLE001
        pass
    return report


def pick_strategy(
    build_step: Callable[[Any], tuple[Callable, tuple]],
    candidates: Sequence[Any],
    hbm_capacity_bytes: int = 0,
    objective: str = "fastest",
    hw=None,
) -> tuple[Any, list[DryRunReport]]:
    """Evaluate candidates; return (best, all reports).

    ``objective="fastest"``: among candidates that compile and fit
    memory, pick the lowest roofline step-time estimate (ties and
    missing estimates fall back to the caller's preference order).
    ``objective="first_fit"``: the r02 behavior — first candidate that
    compiles and fits. Reference analog: atorch's acceleration engine
    scores strategies by throughput, not just feasibility
    (atorch/auto/engine/acceleration_engine.py:13).
    """
    if objective not in ("fastest", "first_fit"):
        raise ValueError(f"unknown objective {objective!r}")
    reports = []
    fitting: list[tuple[Any, DryRunReport]] = []
    for s in candidates:
        r = dry_run(build_step, s, hw=hw)
        reports.append(r)
        logger.info(
            "dry-run %s: ok=%s hbm=%.2fGB flops=%.2e comm=%.2fMB "
            "est=%.2fms (%.1fs)",
            r.strategy_name, r.ok, r.hbm_bytes / 2**30, r.flops,
            r.comm_bytes / 2**20, r.est_step_s * 1e3, r.compile_seconds,
        )
        # every candidate is dry-run (reports must cover them all for
        # comparison logging) — only the pick rule differs by objective
        if r.fits(hbm_capacity_bytes) if hbm_capacity_bytes else r.ok:
            fitting.append((s, r))
    if not fitting:
        raise RuntimeError(
            "no candidate strategy compiled and fit memory: "
            + "; ".join(f"{r.strategy_name}: {r.error or 'OOM'}"
                        for r in reports)
        )
    if objective == "fastest" and all(r.est_step_s > 0 for _, r in fitting):
        # stable min: preference order wins ties
        best = min(fitting, key=lambda sr: sr[1].est_step_s)[0]
    else:
        best = fitting[0][0]
    return best, reports
