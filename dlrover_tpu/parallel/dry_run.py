"""Dry-run strategy evaluation via AOT compilation statistics.

Reference analog: ATorch's analyser + dry-runner
(atorch/atorch/auto/analyser/analyser.py:14, auto/dry_runner/dry_runner.py)
profile candidate strategies by actually running them. XLA gives this for
free ahead-of-time: ``jit(...).lower(...).compile()`` yields per-program
memory and FLOP analyses without executing a step, so strategy selection
costs seconds of compile instead of minutes of training.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class DryRunReport:
    strategy_name: str
    ok: bool
    error: str = ""
    flops: float = 0.0
    hbm_bytes: int = 0          # peak per-device memory if known
    argument_bytes: int = 0
    output_bytes: int = 0
    compile_seconds: float = 0.0

    def fits(self, hbm_capacity_bytes: int) -> bool:
        return self.ok and (
            self.hbm_bytes == 0 or self.hbm_bytes <= hbm_capacity_bytes
        )


def dry_run(
    build_step: Callable[[Any], tuple[Callable, tuple]],
    strategy: Any,
) -> DryRunReport:
    """Compile a strategy's train step and harvest cost/memory analyses.

    ``build_step(strategy) -> (jitted_fn, abstract_args)`` so the caller
    controls model/optimizer wiring; abstract args come from
    ``jax.eval_shape``-style ShapeDtypeStructs with shardings attached.
    """
    import time

    start = time.monotonic()
    try:
        fn, args = build_step(strategy)
        compiled = fn.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 - a failing candidate is a result
        return DryRunReport(
            strategy_name=getattr(strategy, "name", "?"),
            ok=False, error=f"{type(e).__name__}: {e}",
            compile_seconds=time.monotonic() - start,
        )
    report = DryRunReport(
        strategy_name=getattr(strategy, "name", "?"),
        ok=True,
        compile_seconds=time.monotonic() - start,
    )
    try:
        cost = compiled.cost_analysis()
        if cost:
            report.flops = float(cost.get("flops", 0.0))
    except Exception:  # noqa: BLE001 - backends may not implement this
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            # donated args alias outputs (the train state is donated), so
            # argument + output double-counts it; peak live set is temps
            # plus the larger of the two plus any non-aliased remainder
            arg = int(getattr(mem, "argument_size_in_bytes", 0))
            out = int(getattr(mem, "output_size_in_bytes", 0))
            alias = getattr(mem, "alias_size_in_bytes", None)
            if alias is None:
                # backend doesn't report aliasing: assume donation (the
                # train-step convention here) aliases the smaller side
                alias = min(arg, out)
            report.hbm_bytes = int(
                getattr(mem, "temp_size_in_bytes", 0) + arg + out - int(alias)
            )
            report.argument_bytes = arg
            report.output_bytes = out
    except Exception:  # noqa: BLE001
        pass
    return report


def pick_strategy(
    build_step: Callable[[Any], tuple[Callable, tuple]],
    candidates: Sequence[Any],
    hbm_capacity_bytes: int = 0,
) -> tuple[Any, list[DryRunReport]]:
    """Evaluate candidates; return (best, all reports).

    Best = the first candidate (caller's preference order) that compiles and
    fits memory; reports let callers log the full comparison.
    """
    reports = []
    best = None
    for s in candidates:
        r = dry_run(build_step, s)
        reports.append(r)
        logger.info(
            "dry-run %s: ok=%s hbm=%.2fGB flops=%.2e (%.1fs)",
            r.strategy_name, r.ok, r.hbm_bytes / 2**30, r.flops,
            r.compile_seconds,
        )
        if best is None and (
            r.fits(hbm_capacity_bytes) if hbm_capacity_bytes else r.ok
        ):
            best = s
    if best is None and candidates:
        raise RuntimeError(
            "no candidate strategy compiled and fit memory: "
            + "; ".join(f"{r.strategy_name}: {r.error or 'OOM'}" for r in reports)
        )
    return best, reports
