"""Named-axis device mesh construction over ICI and DCN.

Reference analog: ATorch's named-dim process-group fabric
(atorch/atorch/distributed/distributed.py:321 create_parallel_group) builds
one torch process group per parallel dim. The TPU-native equivalent is a
single ``jax.sharding.Mesh`` whose named axes play the role of those groups:
collectives are inserted by XLA from sharding annotations instead of being
issued imperatively, and axis order is chosen so the fastest-varying axes
(tensor/sequence) ride ICI while the slowest (data across slices) rides DCN.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_reshard_seconds = registry().histogram(
    "dlrover_tpu_reshard_seconds",
    "live state reshard duration (old mesh -> new mesh remap of every "
    "DP/TP/PP shard)",
)

# Canonical axis order: slow (DCN-friendly) -> fast (ICI-friendly). Data
# parallelism tolerates the highest latency (one gradient reduce per step),
# tensor/sequence need the tightest coupling (collectives inside every layer).
AXIS_ORDER = ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")


@dataclasses.dataclass
class MeshSpec:
    """Sizes for each named axis; at most one axis may be -1 (fill).

    ``dcn_axes`` names the axes that span slices (multi-host groups connected
    by data-center network rather than ICI); used to build a hybrid mesh.
    """

    axes: dict[str, int] = dataclasses.field(default_factory=dict)
    dcn_axes: dict[str, int] = dataclasses.field(default_factory=dict)

    def resolved(self, num_devices: int) -> dict[str, int]:
        sizes = {a: int(s) for a, s in self.axes.items() if int(s) != 1}
        for a in sizes:
            if a not in AXIS_ORDER:
                raise ValueError(
                    f"unknown mesh axis {a!r}; known: {AXIS_ORDER}"
                )
        fill = [a for a, s in sizes.items() if s == -1]
        if len(fill) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if fill:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {fixed}"
                )
            sizes[fill[0]] = num_devices // fixed
        total = math.prod(sizes.values())
        if total != num_devices:
            raise ValueError(
                f"mesh axes {sizes} use {total} devices, have {num_devices}"
            )
        # keep canonical order, drop size-1 axes that were explicit
        return {a: sizes[a] for a in AXIS_ORDER if a in sizes and sizes[a] > 1} or {
            "data": num_devices
        }


def build_mesh(
    spec: MeshSpec | dict[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh whose axis layout maps well onto the TPU topology.

    Uses ``mesh_utils.create_device_mesh`` so physical ICI neighbors land in
    the same tensor/sequence axis rows; falls back to a reshape for device
    sets the util can't map (CPU test meshes).
    """
    if isinstance(spec, dict):
        spec = MeshSpec(axes=spec)
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolved(len(devices))
    names = tuple(sizes)
    shape = tuple(sizes.values())
    if spec.dcn_axes:
        dcn = {a: int(s) for a, s in spec.dcn_axes.items()}
        for a, s in dcn.items():
            if a not in sizes:
                raise ValueError(
                    f"dcn axis {a!r} not among resolved mesh axes "
                    f"{list(sizes)} (size-1 axes are dropped)"
                )
            if sizes[a] % s:
                raise ValueError(
                    f"dcn size {s} does not divide axis {a!r}={sizes[a]}"
                )
        ici_shape = tuple(
            sizes[a] // dcn.get(a, 1) for a in names
        )
        dcn_shape = tuple(dcn.get(a, 1) for a in names)
        if all(getattr(d, "slice_index", None) is not None
               for d in devices):
            # real multi-slice topology: build it properly, and let a
            # genuine misconfiguration (dcn product != slice count) raise
            arr = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                allow_split_physical_axes=True,
            )
        else:
            # no slice attributes (CPU test meshes, single-slice TPUs):
            # emulate by reshape so dcn-spanning specs stay testable
            logger.info(
                "no slice topology on these devices; emulating the "
                "hybrid mesh %s x %s by reshape", dcn_shape, ici_shape
            )
            arr = np.asarray(devices).reshape(shape)
    else:
        try:
            arr = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True
            )
        except (ValueError, NotImplementedError, AssertionError):
            arr = np.asarray(devices).reshape(shape)
    mesh = Mesh(arr, names)
    logger.info("built mesh %s over %d devices", dict(sizes), len(devices))
    return mesh


def data_parallel_size(mesh: Mesh) -> int:
    """Number of independent data-parallel replicas (data × fsdp axes)."""
    size = 1
    for a in ("data", "fsdp"):
        if a in mesh.axis_names:
            size *= mesh.shape[a]
    return size


def batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes the global batch dimension is sharded over."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


# -------------------------------------------------------- elastic reshard


def remap_spec(spec: PartitionSpec, new_mesh: Mesh) -> PartitionSpec:
    """Carry a PartitionSpec onto a reshaped mesh: axis names the new
    mesh kept stay sharded (at the new axis size), names it dropped
    (e.g. ``tensor`` collapsed to 1 and pruned by ``MeshSpec.resolved``)
    replicate that dimension. This is the layout half of an elastic
    N -> N±1 reshape — the math is unchanged, only shard ownership
    moves."""
    if spec is None:
        return PartitionSpec()
    dims = []
    for entry in spec:
        if entry is None:
            dims.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in new_mesh.axis_names)
            dims.append(kept if len(kept) > 1
                        else (kept[0] if kept else None))
        else:
            dims.append(entry if entry in new_mesh.axis_names else None)
    while dims and dims[-1] is None:
        dims.pop()
    return PartitionSpec(*dims)


def _leaf_spec(leaf: Any) -> PartitionSpec:
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return spec if spec is not None else PartitionSpec()


def reshard_state(old_mesh: Mesh, new_mesh: Mesh, state: Any,
                  put: Any | None = None) -> Any:
    """Remap a live train state across a mesh reshape (ElasWave's
    resharding event): each leaf keeps its logical PartitionSpec, host-
    gathers its shards off the old mesh, and scatters onto the new one.

    The surviving incarnation resumes on the pre-compiled N−1 program
    with this state — no restart, no cold ``pjit`` compile. Host-side
    gather/scatter is deliberate: a device-to-device resharding program
    would itself need compiling, which is the cost this path exists to
    avoid. ``put(leaf_host_array, new_sharding)`` overrides the scatter
    (the checkpoint engine passes a shm-snapshot-backed reader).

    NB: leaves come back as ``device_put``-built arrays. Before handing
    the result to a cached AOT executable that DONATES its inputs,
    re-stage it with ``parallel.compile_cache.launder`` (the engine's
    ``reshard_state`` does this for you) — see launder's docstring for
    the CPU buffer-adoption hazard.
    """
    del old_mesh  # the old layout is read off each leaf's sharding
    start = time.monotonic()
    n_leaves = 0

    def _move(leaf):
        nonlocal n_leaves
        n_leaves += 1
        new_sharding = NamedSharding(
            new_mesh, remap_spec(_leaf_spec(leaf), new_mesh)
        )
        if put is not None:
            return put(leaf, new_sharding)
        return jax.device_put(np.asarray(jax.device_get(leaf)),
                              new_sharding)

    out = jax.tree.map(_move, state)
    dur = time.monotonic() - start
    _reshard_seconds.observe(dur)
    get_journal().emit(
        "reshard", dur=dur, leaves=n_leaves,
        new_devices=new_mesh.devices.size,
        new_axes=dict(new_mesh.shape),
    )
    logger.info(
        "resharded %d leaves onto mesh %s (%d devices) in %.3fs",
        n_leaves, dict(new_mesh.shape), new_mesh.devices.size, dur,
    )
    return out
