"""GP surrogate over measured strategy configurations: the
Bayesian-optimization layer above successive halving.

Reference analog: atorch's strategy engine carries model-based search —
Bayesian optimization over optimization-method combinations
(atorch/atorch/auto/engine/sg_algo/bayes_opt_sg.py:1, sg_algo/hebo/,
combination_sg.py). Halving burns chip time proportional to the
candidate count; a surrogate model REUSES every timed step: fit a
Gaussian process on (config features -> log step time) and spend the
next measurements on the configs the posterior says are promising —
including configs the roofline seeding ranked OUTSIDE the top-k, which
pure halving would never touch.

Pure-numpy GP on purpose: the feature space is tiny (one-hot presets +
a handful of knobs, tens of candidates), where an exact GP with a
Cholesky solve is both optimal and dependency-free. Features: base
preset one-hot, strategy-remat one-hot, int8 flag, log2(grad accum),
model-remat (scan flag, policy one-hot, log2 interval) — the exact
knob set expand_candidates() crosses.

The "posterior" persisted in the engine service is the observation set
itself (parallel/engine_service.py keeps every reported measurement per
shape key): given the fixed kernel, observations ARE the posterior, and
a later search warm-starts by fitting on them.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger(__name__)


def _base_name(s: Strategy) -> str:
    return s.name.split("[", 1)[0]


class StrategyFeaturizer:
    """Fixed-vocabulary encoding of the expand_candidates() knob space.

    The vocabularies come from the candidate POOL (not the observed
    subset) so an unobserved preset still gets its own one-hot column —
    the GP's prior then treats it as unexplored rather than aliasing it
    onto a seen preset."""

    def __init__(self, pool: Sequence[Strategy]):
        self.presets = sorted({_base_name(s) for s in pool})
        self.remats = sorted({s.remat for s in pool})
        self.policies = sorted({
            str(s.extra.get("remat_policy", "")) for s in pool
        })

    def encode(self, s: Strategy) -> np.ndarray:
        f: list[float] = []
        base = _base_name(s)
        f.extend(1.0 if base == p else 0.0 for p in self.presets)
        f.extend(1.0 if s.remat == r else 0.0 for r in self.remats)
        f.append(1.0 if s.extra.get("int8_matmuls") else 0.0)
        f.append(math.log2(max(1, s.grad_accum)))
        f.append(1.0 if s.extra.get("remat_scan") else 0.0)
        pol = str(s.extra.get("remat_policy", ""))
        f.extend(1.0 if pol == p else 0.0 for p in self.policies)
        f.append(math.log2(max(1, int(s.extra.get("remat_interval", 1)))))
        return np.asarray(f, np.float64)

    def encode_all(self, ss: Sequence[Strategy]) -> np.ndarray:
        return np.stack([self.encode(s) for s in ss])


@dataclasses.dataclass
class GPSurrogate:
    """Exact GP regression, RBF kernel, median-distance lengthscale."""

    noise: float = 1e-3
    lengthscale: float = 0.0     # 0 = median pairwise distance heuristic

    def fit(self, X: np.ndarray, y: np.ndarray) -> "GPSurrogate":
        self.X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        self.y_mean = float(y.mean())
        self.y_std = float(y.std()) or 1.0
        self.y = (y - self.y_mean) / self.y_std
        if not self.lengthscale:
            d = np.sqrt(
                ((self.X[:, None] - self.X[None, :]) ** 2).sum(-1)
            )
            pos = d[d > 0]
            self.lengthscale = float(np.median(pos)) if pos.size else 1.0
        K = self._kernel(self.X, self.X)
        K[np.diag_indices_from(K)] += self.noise
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, self.y)
        )
        return self

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None] - B[None, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.lengthscale ** 2))

    def predict(self, Xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior (mean, std) in the ORIGINAL y units."""
        Ks = self._kernel(np.asarray(Xs, np.float64), self.X)
        mean = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 + self.noise - (v ** 2).sum(0), 1e-12, None)
        return (mean * self.y_std + self.y_mean,
                np.sqrt(var) * self.y_std)

    def expected_improvement(self, Xs: np.ndarray,
                             best_y: float) -> np.ndarray:
        """EI for MINIMIZATION of y."""
        mean, std = self.predict(Xs)
        z = (best_y - mean) / std
        # standard normal pdf/cdf without scipy
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        return (best_y - mean) * cdf + std * pdf


def surrogate_propose(
    observations: Sequence[tuple[Strategy, float]],
    pool: Sequence[Strategy],
    n: int = 2,
    featurizer: StrategyFeaturizer | None = None,
) -> list[tuple[Strategy, float]]:
    """Rank UNTRIED pool configs by expected improvement over the best
    observed step time. Returns [(strategy, ei)], best first.

    ``observations`` are (strategy, measured_step_s); non-finite times
    (OOM/crash candidates) are kept as censored high observations so
    the GP learns to avoid that region instead of re-proposing it."""
    obs = [(s, t) for s, t in observations if t > 0]
    if len(obs) < 2:
        return []
    feat = featurizer or StrategyFeaturizer(
        list(pool) + [s for s, _ in obs]
    )
    finite = [t for _, t in obs if math.isfinite(t)]
    if not finite:
        return []
    worst = max(finite)
    y = np.asarray([
        math.log(t if math.isfinite(t) else worst * 4.0)
        for _, t in obs
    ])
    X = feat.encode_all([s for s, _ in obs])
    gp = GPSurrogate().fit(X, y)
    tried = {s.name for s, _ in obs}
    untried = [s for s in pool if s.name not in tried]
    if not untried:
        return []
    ei = gp.expected_improvement(
        feat.encode_all(untried), best_y=float(min(
            math.log(t) for t in finite
        ))
    )
    order = np.argsort(-ei)
    return [(untried[i], float(ei[i])) for i in order[:n]]
