"""Logical-axis sharding rules: param trees annotated with *logical* axis
names, mapped to mesh axes by a rule table.

Reference analog: ATorch decides placement imperatively per module (TP layer
classes in atorch/atorch/modules/distributed_modules/layers.py:239,392,549;
FSDP auto-wrap policies in auto/opt_lib/zero_optimization.py:240). The
TPU-native design is declarative: models label every weight dim with a
logical name ("embed", "heads", "mlp", "vocab"), a Strategy supplies
logical->mesh rules, and XLA derives the collectives. Changing DP->FSDP->TP
is a rule-table edit, not a model rewrite.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

Rules = Sequence[tuple[str, Any]]  # logical name -> mesh axis | tuple | None


def spec_for(
    logical: Sequence[str | None], rules: Rules, mesh: Mesh
) -> PartitionSpec:
    """Map one array's logical axes to a PartitionSpec on ``mesh``.

    A rule whose mesh axis is absent from the mesh (or size 1) resolves to
    replication for that dim, so the same rule table works on any mesh shape
    — the elasticity property: shrink the mesh and specs degrade gracefully.
    Mesh axes already used by an earlier dim of the same array are skipped
    (an axis can shard at most one dim).
    """
    table = dict(rules)
    used: set[str] = set()
    parts: list[Any] = []
    for name in logical:
        axis = table.get(name) if name is not None else None
        if axis is None:
            parts.append(None)
            continue
        axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
        ok = tuple(
            a for a in axes
            if a in mesh.axis_names and mesh.shape[a] > 1 and a not in used
        )
        used.update(ok)
        parts.append(ok if len(ok) > 1 else (ok[0] if ok else None))
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def tree_specs(logical_tree: Any, rules: Rules, mesh: Mesh) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda ax: spec_for(ax, rules, mesh),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x),
    )


def tree_shardings(logical_tree: Any, rules: Rules, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        tree_specs(logical_tree, rules, mesh),
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def constrain(
    x: jax.Array, logical: Sequence[str | None], rules: Rules, mesh: Mesh
) -> jax.Array:
    """``with_sharding_constraint`` through the logical-axis table.

    Used inside model code to pin activation layouts (e.g. keep the batch
    dim on data axes, the sequence dim on the sequence axis).
    """
    if mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec_for(logical, rules, mesh))
    )
