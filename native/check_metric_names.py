#!/usr/bin/env python3
"""Lint: metric names AND journal span names are well-formed + documented.

Walks the package source for ``registry().counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` registrations and asserts

- every name matches ``dlrover_tpu_[a-z_]+`` (no digits, no dots — the
  Prometheus-safe subset the exposition endpoint promises),
- every name is registered in exactly one call site, so the endpoint can
  never emit colliding series with divergent help/type/labels, and
- every ``dlrover_tpu_gateway_*``, ``dlrover_tpu_standby_*`` and
  interval-tuner (``dlrover_tpu_snapshot_interval_*``) name appears
  verbatim in DESIGN.md: those scrape surfaces are operator contracts
  (deploy/README.md points dashboards and the "recovery is slow"
  runbook at them), so registry and docs must not drift.

It also walks journal emissions (``.emit("...")`` / ``.begin("...")`` /
``.span("...")``) and asserts every span name matches ``[a-z_]+``, is
passed as a literal, and appears verbatim in DESIGN.md — span names are
the contract ``telemetry/report.py`` attributes lost time by and
``telemetry/timeline.py`` renders, so a span shipped undocumented is a
span the operator can't read.

Chaos fault points (``chaos.fire("...")`` injection sites) are linted
the same way: literal ``[a-z_]+`` names, each documented in DESIGN.md —
a fault point a chaos plan can't be written against (because nobody
can discover its name) is dead weight in a hot path.

Invoked from the tier-1 suite (tests/test_telemetry.py +
tests/test_flight_recorder.py) and runnable standalone:
``python native/check_metric_names.py``.
"""

from __future__ import annotations

import os
import re
import sys

NAME_RE = re.compile(r"^dlrover_tpu_[a-z_]+$")
REG_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)
SPAN_NAME_RE = re.compile(r"^[a-z_]+$")
SPAN_RE = re.compile(
    r"\.\s*(emit|begin|span)\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)
# the journal implementation itself forwards caller-supplied names
# (EventJournal.span -> self.begin(name, ...)): not an emission site
SPAN_SCAN_EXCLUDE = (os.path.join("telemetry", "journal.py"),)

POINT_NAME_RE = re.compile(r"^[a-z_]+$")
POINT_RE = re.compile(
    r"chaos\s*\.\s*fire\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)
# the chaos package itself forwards caller-supplied point names and its
# docstrings discuss the call form: not injection sites
POINT_SCAN_EXCLUDE = (os.path.join("dlrover_tpu", "chaos") + os.sep,)

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "dlrover_tpu")
DESIGN_MD = os.path.join(os.path.dirname(PKG), "DESIGN.md")
# metric families whose names are an operator contract: every
# registered name under these prefixes must appear verbatim in DESIGN.md
DOCUMENTED_PREFIXES = (
    "dlrover_tpu_gateway_",
    "dlrover_tpu_standby_",
    "dlrover_tpu_snapshot_interval_",
    # elastic resharding + compile cache (DESIGN.md §17): the runbook
    # "failover is recompiling" keys on these names
    "dlrover_tpu_compile_cache_",
    "dlrover_tpu_reshard_",
    # efficiency observatory (DESIGN.md §18): the "MFU dropped" runbook
    # keys on the live MFU gauge, the step-phase histogram, and the
    # profiler-capture counters
    "dlrover_tpu_mfu",
    "dlrover_tpu_step_phase_",
    "dlrover_tpu_profile_",
)

# label names that are themselves an operator contract (dashboards and
# runbooks filter on them): each must be used by a registration in the
# package AND appear verbatim in DESIGN.md
CONTRACT_LABELS = ("straggler_phase",)


def check_contract_labels(pkg_dir: str = PKG,
                          design_path: str = DESIGN_MD) -> list[str]:
    """Contract labels must exist in code and be documented."""
    problems: list[str] = []
    source = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if fname.endswith(".py"):
                with open(os.path.join(root, fname),
                          encoding="utf-8") as f:
                    source.append(f.read())
    source_text = "\n".join(source)
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        return [f"cannot read {design_path}: {e}"]
    for label in CONTRACT_LABELS:
        if f'"{label}"' not in source_text \
                and f"'{label}'" not in source_text:
            problems.append(
                f"contract label {label!r} is not used by any metric "
                "registration in the package"
            )
        if label not in design:
            problems.append(
                f"contract label {label!r} is not documented in "
                "DESIGN.md; add it to its metrics table"
            )
    return problems


def check_documented(names: dict[str, list[str]],
                     design_path: str = DESIGN_MD) -> list[str]:
    """Every contract-family metric registered in code must appear in
    DESIGN.md (gateway, warm-standby, interval tuner)."""
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        return [f"cannot read {design_path}: {e}"]
    return [
        f"metric {name!r} ({', '.join(sites)}) is not documented in "
        f"DESIGN.md; add it to its metrics table"
        for name, sites in sorted(names.items())
        if any(name.startswith(p) for p in DOCUMENTED_PREFIXES)
        and name not in design
    ]


def scan_spans(pkg_dir: str = PKG,
               design_path: str = DESIGN_MD) -> tuple[dict[str, list[str]],
                                                      list[str]]:
    """(span name -> [emission sites], problems) for journal spans."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            if rel.endswith(SPAN_SCAN_EXCLUDE):
                continue
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in SPAN_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    problems.append(
                        f"{site}: journal span emitted with a non-literal "
                        f"name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not SPAN_NAME_RE.match(name):
                    problems.append(
                        f"{site}: span name {name!r} does not match "
                        f"{SPAN_NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        problems.append(f"cannot read {design_path}: {e}")
        return names, problems
    for name, sites in sorted(names.items()):
        if name not in design:
            problems.append(
                f"journal span {name!r} ({', '.join(sites)}) is not "
                f"documented in DESIGN.md; add it to the span-name table"
            )
    return names, problems


def scan_fault_points(pkg_dir: str = PKG,
                      design_path: str = DESIGN_MD
                      ) -> tuple[dict[str, list[str]], list[str]]:
    """(fault point name -> [injection sites], problems) for the chaos
    harness's ``chaos.fire("...")`` call sites."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            if any(ex in rel for ex in POINT_SCAN_EXCLUDE):
                continue
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in POINT_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    problems.append(
                        f"{site}: chaos fault point fired with a "
                        f"non-literal name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not POINT_NAME_RE.match(name):
                    problems.append(
                        f"{site}: fault point name {name!r} does not "
                        f"match {POINT_NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        problems.append(f"cannot read {design_path}: {e}")
        return names, problems
    for name, sites in sorted(names.items()):
        if name not in design:
            problems.append(
                f"chaos fault point {name!r} ({', '.join(sites)}) is not "
                f"documented in DESIGN.md; add it to the fault-point table"
            )
    return names, problems


def scan(pkg_dir: str = PKG) -> tuple[dict[str, list[str]], list[str]]:
    """(name -> [call sites], problems)."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in REG_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    # non-literal first argument: the lint (and grep-
                    # ability) relies on literal names at the call site
                    problems.append(
                        f"{site}: metric registered with a non-literal "
                        f"name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not NAME_RE.match(name):
                    problems.append(
                        f"{site}: metric name {name!r} does not match "
                        f"{NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    for name, sites in sorted(names.items()):
        if len(sites) > 1:
            problems.append(
                f"metric {name!r} registered at {len(sites)} call sites "
                f"({', '.join(sites)}); names must be unique"
            )
    problems.extend(check_documented(names))
    return names, problems


def main() -> int:
    names, problems = scan()
    span_names, span_problems = scan_spans()
    point_names, point_problems = scan_fault_points()
    problems = (problems + span_problems + point_problems
                + check_contract_labels())
    if problems:
        for p in problems:
            print(f"check_metric_names: {p}", file=sys.stderr)
        return 1
    print(f"check_metric_names: {len(names)} metric names, "
          f"{len(span_names)} span names, "
          f"{len(point_names)} chaos fault points OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
