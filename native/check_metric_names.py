#!/usr/bin/env python3
"""Lint: every metric registered in dlrover_tpu/ is well-named and unique.

Walks the package source for ``registry().counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` registrations and asserts

- every name matches ``dlrover_tpu_[a-z_]+`` (no digits, no dots — the
  Prometheus-safe subset the exposition endpoint promises),
- every name is registered in exactly one call site, so the endpoint can
  never emit colliding series with divergent help/type/labels, and
- every ``dlrover_tpu_gateway_*`` name appears verbatim in DESIGN.md:
  the gateway's scrape surface is an operator contract (deploy/README.md
  points dashboards at it), so registry and docs must not drift.

Invoked from the tier-1 suite (tests/test_telemetry.py) and runnable
standalone: ``python native/check_metric_names.py``.
"""

from __future__ import annotations

import os
import re
import sys

NAME_RE = re.compile(r"^dlrover_tpu_[a-z_]+$")
REG_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "dlrover_tpu")
DESIGN_MD = os.path.join(os.path.dirname(PKG), "DESIGN.md")
DOCUMENTED_PREFIX = "dlrover_tpu_gateway_"


def check_documented(names: dict[str, list[str]],
                     design_path: str = DESIGN_MD) -> list[str]:
    """Every gateway metric registered in code must appear in DESIGN.md."""
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        return [f"cannot read {design_path}: {e}"]
    return [
        f"metric {name!r} ({', '.join(sites)}) is not documented in "
        f"DESIGN.md; add it to the gateway metrics table"
        for name, sites in sorted(names.items())
        if name.startswith(DOCUMENTED_PREFIX) and name not in design
    ]


def scan(pkg_dir: str = PKG) -> tuple[dict[str, list[str]], list[str]]:
    """(name -> [call sites], problems)."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in REG_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    # non-literal first argument: the lint (and grep-
                    # ability) relies on literal names at the call site
                    problems.append(
                        f"{site}: metric registered with a non-literal "
                        f"name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not NAME_RE.match(name):
                    problems.append(
                        f"{site}: metric name {name!r} does not match "
                        f"{NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    for name, sites in sorted(names.items()):
        if len(sites) > 1:
            problems.append(
                f"metric {name!r} registered at {len(sites)} call sites "
                f"({', '.join(sites)}); names must be unique"
            )
    problems.extend(check_documented(names))
    return names, problems


def main() -> int:
    names, problems = scan()
    if problems:
        for p in problems:
            print(f"check_metric_names: {p}", file=sys.stderr)
        return 1
    print(f"check_metric_names: {len(names)} metric names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
