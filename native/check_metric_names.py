#!/usr/bin/env python3
"""Lint: every metric registered in dlrover_tpu/ is well-named and unique.

Walks the package source for ``registry().counter("...")`` /
``.gauge("...")`` / ``.histogram("...")`` registrations and asserts

- every name matches ``dlrover_tpu_[a-z_]+`` (no digits, no dots — the
  Prometheus-safe subset the exposition endpoint promises), and
- every name is registered in exactly one call site, so the endpoint can
  never emit colliding series with divergent help/type/labels.

Invoked from the tier-1 suite (tests/test_telemetry.py) and runnable
standalone: ``python native/check_metric_names.py``.
"""

from __future__ import annotations

import os
import re
import sys

NAME_RE = re.compile(r"^dlrover_tpu_[a-z_]+$")
REG_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "dlrover_tpu")


def scan(pkg_dir: str = PKG) -> tuple[dict[str, list[str]], list[str]]:
    """(name -> [call sites], problems)."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in REG_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    # non-literal first argument: the lint (and grep-
                    # ability) relies on literal names at the call site
                    problems.append(
                        f"{site}: metric registered with a non-literal "
                        f"name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not NAME_RE.match(name):
                    problems.append(
                        f"{site}: metric name {name!r} does not match "
                        f"{NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    for name, sites in sorted(names.items()):
        if len(sites) > 1:
            problems.append(
                f"metric {name!r} registered at {len(sites)} call sites "
                f"({', '.join(sites)}); names must be unique"
            )
    return names, problems


def main() -> int:
    names, problems = scan()
    if problems:
        for p in problems:
            print(f"check_metric_names: {p}", file=sys.stderr)
        return 1
    print(f"check_metric_names: {len(names)} metric names OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
