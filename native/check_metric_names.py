#!/usr/bin/env python3
"""Shim: the metric/span/fault-point name lint moved into the invariant
analyzer (``native/analyze/checkers/metric_names.py`` — rule
``metric-name`` — and ``journal_span.py`` — rule ``journal-span``).

This entry point is kept so existing invocations and the tier-1 tests
that load it by file path keep working unchanged; it re-exports the
full legacy API and CLI. New code should run the framework instead::

    python -m native.analyze dlrover_tpu --rules metric-name,journal-span
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from native.analyze.checkers.metric_names import (  # noqa: E402,F401
    CONTRACT_LABELS,
    DESIGN_MD,
    DOCUMENTED_PREFIXES,
    NAME_RE,
    PKG,
    POINT_NAME_RE,
    POINT_RE,
    POINT_SCAN_EXCLUDE,
    REG_RE,
    SPAN_NAME_RE,
    SPAN_RE,
    SPAN_SCAN_EXCLUDE,
    check_contract_labels,
    check_documented,
    main,
    scan,
    scan_fault_points,
    scan_spans,
)

if __name__ == "__main__":
    sys.exit(main())
