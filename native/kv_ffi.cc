// XLA FFI custom-call bindings for the KvVariable embedding runtime:
// the IN-GRAPH sparse lookup/apply path.
//
// Reference analog: tfplus's KvVariable is a TF *graph op* — the gather
// and the sparse optimizer application execute inside the runtime
// (tfplus/kv_variable/ops/kv_variable_ops.cc:37, kernels/
// training_ops.cc), with no per-step host/Python round trip. The repo's
// default sparse path is host-side (XLA's static shapes can't hold an
// unbounded table), which costs a Python round trip per step. These FFI
// handlers put the HOT OPS back inside the compiled program on CPU
// backends (trainer/data hosts that own a table shard): `jax.ffi`
// lowers them to custom calls, so a jitted step gathers rows and
// applies the sparse optimizer with zero Python in the loop. On TPU the
// table stays host-side by design (device HBM cannot hold an unbounded
// hash table); the dense tower is the on-chip half.
//
// The table handle travels as an i64 attribute: it IS the kv_create
// pointer, registered/owned by the Python KvEmbeddingTable whose
// lifetime must cover every compiled program that captured it (the
// Python wrapper enforces this by keeping the table in the closure).
//
// Build: linked into libdlrover_tpu_native.so next to kv_variable.cc
// when the jax FFI headers are available (make FFI_INCLUDE=...); the
// base runtime builds without them, so environments without jax
// headers lose only the in-graph path.

#include <cstdint>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

extern "C" {
void kv_lookup(void* handle, const int64_t* keys, int64_t n, float* out,
               int init_missing);
void kv_apply_adam(void* handle, const int64_t* keys, const float* grads,
                   int64_t n, float lr, float beta1, float beta2,
                   float eps, int64_t step, float l2, float group_lasso);
int64_t kv_size(void* handle);
}

static ffi::Error KvGatherImpl(int64_t table, bool init_missing,
                               ffi::Buffer<ffi::S64> ids,
                               ffi::ResultBuffer<ffi::F32> out) {
  if (table == 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_gather: null table handle");
  }
  const int64_t n = ids.element_count();
  const int64_t out_elems = out->element_count();
  if (n == 0) return ffi::Error::Success();
  if (out_elems % n != 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_gather: output size not a multiple of ids");
  }
  kv_lookup(reinterpret_cast<void*>(table), ids.typed_data(), n,
            out->typed_data(), init_missing ? 1 : 0);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    KvGather, KvGatherImpl,
    ffi::Ffi::Bind()
        .Attr<int64_t>("table")
        .Attr<bool>("init_missing")
        .Arg<ffi::Buffer<ffi::S64>>()
        .Ret<ffi::Buffer<ffi::F32>>());

// 32-bit-id variant: jax without jax_enable_x64 lowers every integer
// array to i32, so this is the path most jitted callers actually take.
// Keys widen losslessly (i32 ⊂ the table's i64 key space).
static ffi::Error KvGather32Impl(int64_t table, bool init_missing,
                                 ffi::Buffer<ffi::S32> ids,
                                 ffi::ResultBuffer<ffi::F32> out) {
  if (table == 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_gather: null table handle");
  }
  const int64_t n = ids.element_count();
  if (n == 0) return ffi::Error::Success();
  if (out->element_count() % n != 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_gather: output size not a multiple of ids");
  }
  std::vector<int64_t> wide(ids.typed_data(), ids.typed_data() + n);
  kv_lookup(reinterpret_cast<void*>(table), wide.data(), n,
            out->typed_data(), init_missing ? 1 : 0);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    KvGather32, KvGather32Impl,
    ffi::Ffi::Bind()
        .Attr<int64_t>("table")
        .Attr<bool>("init_missing")
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

// Sparse Adam application as a graph op (the training_ops.cc analog).
// Returns the table's row count so the call has a data result (XLA
// custom calls need one); callers mark it side-effecting so DCE and
// CSE keep their hands off.
// `step` (Adam bias correction) is a TRACED scalar operand, not an
// attribute: attributes are compile-time constants and would force a
// recompile per training step.
static ffi::Error KvApplyAdamImpl(int64_t table, float lr, float beta1,
                                  float beta2, float eps, float l2,
                                  float group_lasso,
                                  ffi::Buffer<ffi::S64> ids,
                                  ffi::Buffer<ffi::F32> grads,
                                  ffi::Buffer<ffi::S64> step,
                                  ffi::ResultBuffer<ffi::S64> rows) {
  if (table == 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_apply_adam: null table handle");
  }
  if (step.element_count() != 1) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_apply_adam: step must be a scalar");
  }
  const int64_t n = ids.element_count();
  if (n > 0 && grads.element_count() % n != 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_apply_adam: grads size not a multiple of ids");
  }
  if (n > 0) {
    kv_apply_adam(reinterpret_cast<void*>(table), ids.typed_data(),
                  grads.typed_data(), n, lr, beta1, beta2, eps,
                  step.typed_data()[0], l2, group_lasso);
  }
  rows->typed_data()[0] = kv_size(reinterpret_cast<void*>(table));
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    KvApplyAdam, KvApplyAdamImpl,
    ffi::Ffi::Bind()
        .Attr<int64_t>("table")
        .Attr<float>("lr")
        .Attr<float>("beta1")
        .Attr<float>("beta2")
        .Attr<float>("eps")
        .Attr<float>("l2")
        .Attr<float>("group_lasso")
        .Arg<ffi::Buffer<ffi::S64>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::S64>>()
        .Ret<ffi::Buffer<ffi::S64>>());

static ffi::Error KvApplyAdam32Impl(int64_t table, float lr, float beta1,
                                    float beta2, float eps, float l2,
                                    float group_lasso,
                                    ffi::Buffer<ffi::S32> ids,
                                    ffi::Buffer<ffi::F32> grads,
                                    ffi::Buffer<ffi::S32> step,
                                    ffi::ResultBuffer<ffi::S32> rows) {
  if (table == 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_apply_adam: null table handle");
  }
  if (step.element_count() != 1) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_apply_adam: step must be a scalar");
  }
  const int64_t n = ids.element_count();
  if (n > 0 && grads.element_count() % n != 0) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "kv_apply_adam: grads size not a multiple of ids");
  }
  if (n > 0) {
    std::vector<int64_t> wide(ids.typed_data(), ids.typed_data() + n);
    kv_apply_adam(reinterpret_cast<void*>(table), wide.data(),
                  grads.typed_data(), n, lr, beta1, beta2, eps,
                  step.typed_data()[0], l2, group_lasso);
  }
  rows->typed_data()[0] =
      static_cast<int32_t>(kv_size(reinterpret_cast<void*>(table)));
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    KvApplyAdam32, KvApplyAdam32Impl,
    ffi::Ffi::Bind()
        .Attr<int64_t>("table")
        .Attr<float>("lr")
        .Attr<float>("beta1")
        .Attr<float>("beta2")
        .Attr<float>("eps")
        .Attr<float>("l2")
        .Attr<float>("group_lasso")
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>());
