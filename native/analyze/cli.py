"""`python -m native.analyze` — run the invariant checkers and gate on
the committed baseline.

Exit codes: 0 = clean (every finding grandfathered), 1 = new findings
or stale baseline entries, 2 = usage error. Tier-1 runs::

    python -m native.analyze dlrover_tpu \
        --format json --baseline native/analyze/baseline.json

``--fix-hints`` appends each rule's remediation snippet to text output;
``--env-table`` prints the DLROVER_TPU_* reference table DESIGN.md
embeds (generated from ``common/envspec.py`` so docs cannot drift).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from native.analyze import checkers as _checkers  # noqa: F401 - registers
from native.analyze.baseline import (
    Baseline,
    BaselineEntry,
    load_baseline,
    save_baseline,
)
from native.analyze.core import CHECKERS, Finding, Project

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "native", "analyze",
                                "baseline.json")


@dataclasses.dataclass
class AnalysisResult:
    findings: list[Finding]
    new_findings: list[Finding]
    grandfathered: list[Finding]
    stale_entries: list[BaselineEntry]
    rules: list[str]

    @property
    def ok(self) -> bool:
        return not self.new_findings and not self.stale_entries

    def to_dict(self) -> dict:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "ok": self.ok,
            "rules": self.rules,
            "counts": counts,
            "findings": [f.to_dict() for f in self.findings],
            "new": [f.key for f in self.new_findings],
            "grandfathered": [f.key for f in self.grandfathered],
            "stale_baseline": [e.key for e in self.stale_entries],
        }


def run_analysis(root: str = REPO_ROOT, package: str = "dlrover_tpu",
                 rules: list[str] | None = None,
                 baseline: Baseline | str | None = None,
                 design_path: str | None = None) -> AnalysisResult:
    """Parse the package once, run the selected checkers, split against
    the baseline. ``baseline`` may be a path, a loaded Baseline, or
    None (everything counts as new)."""
    selected = sorted(rules if rules is not None else CHECKERS)
    unknown = [r for r in selected if r not in CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; available: {sorted(CHECKERS)}"
        )
    project = Project(root, package=package, design_path=design_path)
    findings: list[Finding] = list(project.parse_failures)
    for rule in selected:
        findings.extend(CHECKERS[rule]().check(project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    if isinstance(baseline, str):
        baseline = load_baseline(baseline)
    if baseline is None:
        baseline = Baseline()
    new, old, stale = baseline.split(findings)
    return AnalysisResult(findings=findings, new_findings=new,
                          grandfathered=old, stale_entries=stale,
                          rules=selected)


def _print_text(result: AnalysisResult, fix_hints: bool) -> None:
    for f in result.new_findings:
        print(f.render(fix_hints=fix_hints))
    for e in result.stale_entries:
        print(f"stale baseline entry (fixed? remove it or run "
              f"--update-baseline): {e.key}")
    n_rules = len(result.rules)
    if result.ok:
        grandfathered = len(result.grandfathered)
        extra = f", {grandfathered} baselined" if grandfathered else ""
        print(f"analyze: OK — {n_rules} rules, 0 new findings{extra}")
    else:
        print(
            f"analyze: FAIL — {len(result.new_findings)} new finding(s), "
            f"{len(result.stale_entries)} stale baseline entr(ies) "
            f"across {n_rules} rules",
            file=sys.stderr,
        )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m native.analyze",
        description="invariant analyzer (DESIGN.md §19)",
    )
    parser.add_argument("package", nargs="?", default="dlrover_tpu",
                        help="package dir under --root to analyze")
    parser.add_argument("--root", default=REPO_ROOT)
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: none)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite --baseline from current findings, "
                             "keeping surviving justifications")
    parser.add_argument("--fix-hints", action="store_true",
                        help="print the remediation snippet per finding")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--env-table", action="store_true",
                        help="print the env-var reference table from "
                             "common/envspec.py and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(CHECKERS):
            print(f"{rule}: {CHECKERS[rule].description}")
        return 0
    if args.env_table:
        sys.path.insert(0, args.root)
        from dlrover_tpu.common import envspec

        print(envspec.markdown_table())
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    baseline_path = args.baseline
    try:
        result = run_analysis(
            root=args.root, package=args.package, rules=rules,
            baseline=baseline_path,
        )
    except ValueError as e:
        print(f"analyze: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        if not baseline_path:
            print("analyze: --update-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        previous = load_baseline(baseline_path)
        save_baseline(baseline_path, result.findings, previous=previous)
        print(f"analyze: baseline rewritten with "
              f"{len(result.findings)} entr(ies) at {baseline_path}")
        return 0

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2))
    else:
        _print_text(result, fix_hints=args.fix_hints)
    return 0 if result.ok else 1
