"""Invariant analyzer: AST/dataflow lints that machine-enforce the
codebase's hard-won runtime contracts (DESIGN.md §19).

Seven PRs of incident reports distilled three classes of invariant that
only existed as prose: trees restored from snapshots must be laundered
before their first deserialized-``Compiled`` call (the PR-6 CPU
donation/adoption weight-corruption hazard), files another process will
read must be published by atomic rename (``atomic_write_file``), and
shared state touched by daemon threads must be touched under a held
lock. This package turns them — plus the env-var, RPC-message and
journal-span contracts — into checkers that run in tier-1.

Usage::

    python -m native.analyze dlrover_tpu \
        --baseline native/analyze/baseline.json --format json

Programmatic::

    from native.analyze import run_analysis
    result = run_analysis()          # repo root + dlrover_tpu defaults
    assert not result.new_findings

Checkers live in ``native.analyze.checkers`` and register themselves on
import; grandfathered findings live in the committed
``native/analyze/baseline.json`` with a one-line justification each.
"""

from native.analyze.core import (  # noqa: F401
    CHECKERS,
    Checker,
    Finding,
    Module,
    Project,
    register,
)
from native.analyze.baseline import Baseline, load_baseline  # noqa: F401
from native.analyze.cli import AnalysisResult, main, run_analysis  # noqa: F401
