"""Committed baseline of grandfathered findings.

The baseline is the escape hatch that lets the analyzer run red-line in
tier-1 from day one: a finding that is deliberate (or not worth fixing
yet) is recorded by its stable key with a one-line justification, and
the suite fails only on NEW findings. Entries expire loudly — a
baseline row whose finding no longer exists fails the run too, so the
file can only shrink honestly (``--update-baseline`` rewrites it from
the current findings, preserving justifications for keys that remain).
"""

from __future__ import annotations

import dataclasses
import json
import os

from native.analyze.core import Finding


@dataclasses.dataclass
class BaselineEntry:
    key: str
    justification: str = ""
    rule: str = ""
    path: str = ""


@dataclasses.dataclass
class Baseline:
    entries: list[BaselineEntry] = dataclasses.field(default_factory=list)
    path: str = ""

    @property
    def keys(self) -> set[str]:
        return {e.key for e in self.entries}

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[BaselineEntry]]:
        """(new, grandfathered, stale_entries)."""
        known = self.keys
        new = [f for f in findings if f.key not in known]
        old = [f for f in findings if f.key in known]
        live = {f.key for f in findings}
        stale = [e for e in self.entries if e.key not in live]
        return new, old, stale


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline(path=path)
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = [
        BaselineEntry(
            key=e["key"],
            justification=e.get("justification", ""),
            rule=e.get("rule", ""),
            path=e.get("path", ""),
        )
        for e in data.get("entries", [])
    ]
    return Baseline(entries=entries, path=path)


def save_baseline(path: str, findings: list[Finding],
                  previous: Baseline | None = None) -> Baseline:
    """Rewrite the baseline from the current findings, carrying forward
    justifications for keys that survive; new keys get a TODO marker the
    reviewer must replace (the tier-1 test asserts none remain)."""
    carried = {e.key: e.justification for e in previous.entries} \
        if previous else {}
    entries = [
        BaselineEntry(
            key=f.key,
            justification=carried.get(f.key, "TODO: justify or fix"),
            rule=f.rule,
            path=f.path,
        )
        for f in sorted(findings, key=lambda f: f.key)
    ]
    data = {
        "version": 1,
        "entries": [dataclasses.asdict(e) for e in entries],
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return Baseline(entries=entries, path=path)
