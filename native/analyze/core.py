"""Analyzer core: finding objects, the checker registry, and the parsed
project model every checker walks.

Design constraints that shaped this module:

- **Stable baseline keys.** A grandfathered finding must keep matching
  its baseline entry while unrelated edits shift line numbers, so a
  ``Finding``'s identity is ``rule|path|symbol|message`` (the enclosing
  ``Class.method`` symbol, never the line). Messages therefore must not
  embed line numbers.
- **Cross-module symbol resolution without imports.** Checkers need to
  know that ``launder(...)``, ``compile_cache.launder(...)`` and
  ``from ...compile_cache import launder as L; L(...)`` are the same
  function. Each ``Module`` builds an alias→dotted-path import map and
  ``Module.qualname`` resolves any Name/Attribute chain through it —
  purely static, so the analyzer never executes package code.
- **Fixture-friendly.** A ``Project`` is rooted anywhere (tests point it
  at a tmp dir with seeded violations); nothing hardcodes the real
  package path.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator, Optional


@dataclasses.dataclass
class Finding:
    """One rule violation at a file:line, with a remediation hint."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str       # line-number free (baseline key stability)
    hint: str = ""
    symbol: str = ""   # enclosing "Class.method" / "function" / "<module>"

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.symbol}|{self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "hint": self.hint,
            "key": self.key,
        }

    def render(self, fix_hints: bool = False) -> str:
        text = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if fix_hints and self.hint:
            text += f"\n    fix: {self.hint}"
        return text


class Checker:
    """Base class: subclass, set ``rule``/``description``/``hint``,
    implement ``check``, and decorate with ``@register``."""

    rule: str = ""
    description: str = ""
    # generic remediation snippet shown by --fix-hints (per-finding
    # hints override it)
    hint: str = ""

    def check(self, project: "Project") -> list[Finding]:
        raise NotImplementedError

    def finding(self, module: "Module", node: ast.AST, message: str,
                hint: str = "") -> Finding:
        return Finding(
            rule=self.rule,
            path=module.relpath,
            line=getattr(node, "lineno", 1),
            message=message,
            hint=hint or self.hint,
            symbol=module.symbol_at(node),
        )


CHECKERS: dict[str, type[Checker]] = {}


def register(cls: type[Checker]) -> type[Checker]:
    if not cls.rule:
        raise ValueError(f"{cls.__name__} has no rule id")
    CHECKERS[cls.rule] = cls
    return cls


# ------------------------------------------------------------------ modules


def _import_map(tree: ast.AST) -> dict[str, str]:
    """alias -> fully qualified dotted path, from the module's imports."""
    mapping: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    mapping[alias.asname] = alias.name
                else:
                    # ``import a.b.c`` binds ``a``; resolving the head
                    # segment is enough for dotted-chain resolution
                    head = alias.name.split(".")[0]
                    mapping.setdefault(head, head)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for alias in node.names:
                mapping[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return mapping


def dotted(node: ast.AST) -> Optional[str]:
    """Textual dotted path of a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Module:
    """One parsed source file plus resolution helpers."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=relpath)
        self.imports = _import_map(self.tree)
        self._symbols: Optional[list[tuple[int, int, str]]] = None

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain through this module's imports
        to a fully qualified dotted path (best effort)."""
        text = dotted(node)
        if text is None:
            return None
        head, _, rest = text.partition(".")
        resolved = self.imports.get(head)
        if resolved is None:
            return text
        return f"{resolved}.{rest}" if rest else resolved

    def call_suffix(self, call: ast.Call) -> str:
        """Last dotted segment of a call's callee ('' when dynamic)."""
        text = dotted(call.func)
        return text.rsplit(".", 1)[-1] if text else ""

    def _build_symbols(self) -> list[tuple[int, int, str]]:
        spans: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno)
                    spans.append((child.lineno, end, name))
                    visit(child, name)
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        # innermost span wins: sort by size so later lookups can take
        # the narrowest enclosing one
        spans.sort(key=lambda s: (s[1] - s[0]), reverse=True)
        return spans

    def symbol_at(self, node: ast.AST) -> str:
        """Innermost enclosing Class.func symbol for a node."""
        line = getattr(node, "lineno", 0)
        if not line:
            return "<module>"
        if self._symbols is None:
            self._symbols = self._build_symbols()
        best = "<module>"
        for start, end, name in self._symbols:
            if start <= line <= end:
                best = name  # spans sorted widest-first: keep narrowing
        return best

    def functions(self) -> Iterator[tuple[str, ast.FunctionDef]]:
        """(symbol, node) for every function/method in the module."""

        def visit(node: ast.AST, prefix: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    yield name, child
                    yield from visit(child, name)
                elif isinstance(child, ast.ClassDef):
                    cname = f"{prefix}.{child.name}" if prefix \
                        else child.name
                    yield from visit(child, cname)
                else:
                    yield from visit(child, prefix)

        yield from visit(self.tree, "")

    def classes(self) -> Iterator[ast.ClassDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                yield node


# ------------------------------------------------------------------ project


class Project:
    """All parsed modules of one package tree plus shared context
    (DESIGN.md text) checkers assert contracts against."""

    def __init__(self, root: str, package: str = "dlrover_tpu",
                 design_path: str | None = None):
        self.root = os.path.abspath(root)
        self.package = package
        self.package_dir = os.path.join(self.root, package)
        self.modules: list[Module] = []
        self.parse_failures: list[Finding] = []
        for dirpath, dirnames, filenames in os.walk(self.package_dir):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, self.root)
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                try:
                    self.modules.append(Module(path, rel, source))
                except SyntaxError as e:
                    self.parse_failures.append(Finding(
                        rule="parse-error", path=rel.replace(os.sep, "/"),
                        line=e.lineno or 1,
                        message=f"cannot parse: {e.msg}",
                    ))
        design = design_path or os.path.join(self.root, "DESIGN.md")
        try:
            with open(design, encoding="utf-8") as f:
                self.design_text = f.read()
        except OSError:
            self.design_text = ""
        self.design_path = design

    def module_by_suffix(self, suffix: str) -> Optional[Module]:
        """Find the one module whose relpath ends with ``suffix``
        (e.g. ``common/messages.py``)."""
        suffix = suffix.replace(os.sep, "/")
        for module in self.modules:
            if module.relpath.endswith(suffix):
                return module
        return None


# ------------------------------------------------------------ shared helpers


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_arg(call: ast.Call, index: int, keyword: str) -> Optional[ast.AST]:
    """Positional-or-keyword argument of a call, else None."""
    if len(call.args) > index:
        return call.args[index]
    for kw in call.keywords:
        if kw.arg == keyword:
            return kw.value
    return None
