"""Rule ``rpc-contract``: the typed control-plane message surface is
closed — every message sent has a handler, and field usage matches the
declared dataclasses.

The servicer dispatches on ``isinstance`` and ends in ``raise
TypeError`` for unknown types, so a message class added to
``common/messages.py`` and sent by a client without a matching branch
only fails at runtime, mid-recovery, over RPC. Statically enforced
instead:

- every message constructed inside a ``*.call(...)`` anywhere in the
  package has an ``isinstance`` dispatch branch SOMEWHERE in the
  package (the master servicer is one dispatcher among several — the
  brain service and the strategy engine service run their own);
- every ``*Request`` message class is dispatched by some handler, and
  the ones the MASTER servicer handles also have a ``master_client``
  construction (the typed client is the API surface — a master request
  only reachable by hand-rolled RPC is a contract gap);
- every keyword in any ``m.X(...)`` construction is a declared field of
  ``X`` (dataclass kwargs explode at call time, far from the typo);
- inside an ``isinstance(msg, m.X)`` branch of any dispatcher, every
  ``msg.attr`` access is a declared field (or method) of ``X``.

Modules are located by path suffix (``common/messages.py``,
``master/servicer.py``, ``agent/master_client.py``), so fixtures can
supply miniature versions.
"""

from __future__ import annotations

import ast

from native.analyze.core import (
    Checker,
    Finding,
    Module,
    Project,
    dotted,
    register,
)

MESSAGES_SUFFIX = "common/messages.py"
SERVICER_SUFFIX = "master/servicer.py"
CLIENT_SUFFIX = "agent/master_client.py"

# Epoch fence (DESIGN.md §26): these response messages are the
# transport-independent carriers of the master's incarnation counter —
# loopback transports (the fleet simulator) have no RPC envelope, so
# removing the field silently disables restart detection there.
EPOCH_FENCED = ("HeartbeatResponse", "CommWorldResponse")


def message_classes(module: Module) -> dict[str, set[str]]:
    """class name -> declared field/method names."""
    classes: dict[str, set[str]] = {}
    for node in module.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        members: set[str] = set()
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                members.add(stmt.target.id)
            elif isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        members.add(target.id)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                members.add(stmt.name)
        # single inheritance between messages: fold base fields in
        for base in node.bases:
            base_name = (dotted(base) or "").rsplit(".", 1)[-1]
            if base_name in classes:
                members |= classes[base_name]
        classes[node.name] = members
    return classes


def _message_ref(node: ast.AST, classes: dict[str, set[str]]
                 ) -> str | None:
    """Resolve an expression like ``m.FooRequest``/``FooRequest`` to a
    known message class name."""
    text = dotted(node)
    if not text:
        return None
    name = text.rsplit(".", 1)[-1]
    return name if name in classes else None


def _isinstance_branch(test: ast.AST, classes: dict[str, set[str]]
                       ) -> tuple[str, str] | None:
    """(varname, class) for ``isinstance(<var>, m.X)`` tests."""
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Name) \
            and test.func.id == "isinstance" and len(test.args) == 2 \
            and isinstance(test.args[0], ast.Name):
        cls = _message_ref(test.args[1], classes)
        if cls is not None:
            return test.args[0].id, cls
    return None


@register
class RpcContractChecker(Checker):
    rule = "rpc-contract"
    description = ("every sent message has a servicer handler, every "
                   "*Request a master_client method, and constructor "
                   "kwargs / msg.attr accesses match declared fields")
    hint = ("add the isinstance branch to MasterServicer._dispatch and "
            "a typed method to agent/master_client.py; fields must be "
            "declared on the @register_message dataclass in "
            "common/messages.py")

    def check(self, project: Project) -> list[Finding]:
        messages = project.module_by_suffix(MESSAGES_SUFFIX)
        servicer = project.module_by_suffix(SERVICER_SUFFIX)
        client = project.module_by_suffix(CLIENT_SUFFIX)
        if messages is None or servicer is None or client is None:
            return []   # not a control-plane tree (fixture subsets)
        classes = message_classes(messages)
        findings: list[Finding] = []

        master_handled = self._handled_classes(servicer, classes)
        handled_anywhere: set[str] = set()
        for module in project.modules:
            handled_anywhere |= self._handled_classes(module, classes)
        client_built = self._constructed(client, classes)
        sent = self._sent_classes(project, classes)

        for cls, node in sorted(sent.items()):
            if cls not in handled_anywhere:
                module, site = node
                findings.append(self.finding(
                    module, site,
                    f"message {cls} is sent over RPC but no dispatcher "
                    "in the package has an isinstance branch for it — "
                    "the call raises TypeError at runtime",
                ))
        for cls in sorted(classes):
            if not cls.endswith("Request"):
                continue
            class_node = self._class_node(messages, cls)
            if cls not in handled_anywhere:
                findings.append(self.finding(
                    messages, class_node,
                    f"request message {cls} has no dispatcher handling "
                    "it anywhere in the package",
                ))
            if cls in master_handled and cls not in client_built:
                findings.append(self.finding(
                    messages, class_node,
                    f"master-handled request {cls} has no master_client "
                    "method constructing it",
                ))

        for cls in EPOCH_FENCED:
            if cls in classes and "master_epoch" not in classes[cls]:
                findings.append(self.finding(
                    messages, self._class_node(messages, cls),
                    f"epoch-fenced response {cls} must declare a "
                    "master_epoch field — without it, loopback "
                    "transports (fleetsim) cannot detect a master "
                    "restart (DESIGN.md §26)",
                ))

        findings.extend(self._kwarg_findings(project, classes))
        for module in project.modules:
            findings.extend(self._branch_field_findings(module, classes))
        return findings

    # ------------------------------------------------------------- helpers

    def _class_node(self, messages: Module, name: str) -> ast.AST:
        for node in messages.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == name:
                return node
        return messages.tree

    def _handled_classes(self, servicer: Module,
                         classes: dict[str, set[str]]) -> set[str]:
        handled: set[str] = set()
        for node in ast.walk(servicer.tree):
            if isinstance(node, ast.If):
                branch = _isinstance_branch(node.test, classes)
                if branch is not None:
                    handled.add(branch[1])
        return handled

    def _constructed(self, module: Module,
                     classes: dict[str, set[str]]) -> set[str]:
        built: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                cls = _message_ref(node.func, classes)
                if cls is not None:
                    built.add(cls)
        # typed pass-through methods (e.g. report_paral_config(config:
        # m.ParalConfig)) send a parameter instead of constructing
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for arg in node.args.args:
                    if arg.annotation is not None:
                        cls = _message_ref(arg.annotation, classes)
                        if cls is not None:
                            built.add(cls)
        return built

    def _sent_classes(self, project: Project,
                      classes: dict[str, set[str]]
                      ) -> dict[str, tuple[Module, ast.AST]]:
        """Message classes constructed directly inside a ``*.call(...)``
        argument anywhere in the package."""
        sent: dict[str, tuple[Module, ast.AST]] = {}
        for module in project.modules:
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "call" and node.args):
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    cls = _message_ref(arg.func, classes)
                    if cls is not None and cls not in sent:
                        sent[cls] = (module, node)
        return sent

    def _kwarg_findings(self, project: Project,
                        classes: dict[str, set[str]]) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                cls = _message_ref(node.func, classes)
                if cls is None:
                    continue
                # only message-module references (m.X / messages.X) or
                # names imported from the messages module count — a
                # same-named local class elsewhere is out of scope
                qual = module.qualname(node.func) or ""
                if "messages" not in qual and not module.relpath.endswith(
                        MESSAGES_SUFFIX):
                    continue
                fields = classes[cls]
                for kw in node.keywords:
                    if kw.arg is not None and kw.arg not in fields:
                        findings.append(self.finding(
                            module, node,
                            f"{cls}(...) constructed with unknown "
                            f"field {kw.arg!r} — declared fields: "
                            f"{sorted(fields)}",
                        ))
        return findings

    def _branch_field_findings(self, servicer: Module,
                               classes: dict[str, set[str]]
                               ) -> list[Finding]:
        findings: list[Finding] = []
        common = {"__class__", "__dict__"}
        for node in ast.walk(servicer.tree):
            if not isinstance(node, ast.If):
                continue
            branch = _isinstance_branch(node.test, classes)
            if branch is None:
                continue
            var, cls = branch
            fields = classes[cls] | common
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Attribute) \
                            and isinstance(sub.value, ast.Name) \
                            and sub.value.id == var \
                            and sub.attr not in fields:
                        findings.append(self.finding(
                            servicer, sub,
                            f"access {var}.{sub.attr} inside the "
                            f"isinstance({var}, {cls}) branch, but "
                            f"{cls} declares no field {sub.attr!r}",
                        ))
        return findings
