"""Rule ``aot-launder``: host-built pytrees must be laundered before a
deserialized ``Compiled`` call.

The incident (PR 6, memory: aot-executable-cpu-hazards): a deserialized
AOT executable skips pjit's input re-staging, and on the CPU backend
``jax.device_put`` may ADOPT an aligned host buffer — so with input
donation the executable's in-place update lands on one shared host
allocation per restored leaf and compounds across devices (observed +8
per step on an 8-device mesh; weight corruption when the buffers alias
the shm arena). The contract: any tree sourced from checkpoint restore,
``reshard_state`` or an shm read must pass through
``parallel.compile_cache.launder`` (a jitted copy — exactly the
re-staging pjit would have done) before reaching an executable obtained
from the compile cache (``load_or_compile(...).fn``,
``load_executable_blob``, ``deserialize_and_load``).

Dataflow, per function, statements in source order (a lint, not an
interpreter: both branches of a conditional are walked, taint survives
joins, reassignment clears it):

- ``x = restore*/reshard_state/load_raw/shm read`` taints ``x``;
- ``x = launder(y)`` (resolved cross-module) produces a clean tree;
- calling an AOT-sourced executable with a tainted variable anywhere in
  its arguments is the violation.
"""

from __future__ import annotations

import ast

from native.analyze.core import Checker, Finding, Module, Project, register

# a call whose callee's last dotted segment matches one of these (or
# starts with "restore") produces a HOST-BUILT tree
SOURCE_SUFFIXES = {
    "reshard_state",
    "restore",
    "load_raw",
    "load_snapshot",
    "read_snapshot",
    "shm_read",
    "read_state",
}
SOURCE_PREFIX = "restore"

LAUNDER_SUFFIX = "launder"

# calls that produce a deserialized/cached executable
AOT_LOADER_SUFFIXES = {"load_executable_blob", "deserialize_and_load"}
AOT_STEP_SUFFIX = "load_or_compile"   # returns AotStep; .fn is the callable


def _target_names(target: ast.AST) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        names = []
        for elt in target.elts:
            names.extend(_target_names(elt))
        return names
    return []


def _is_source_call(module: Module, call: ast.Call) -> bool:
    suffix = module.call_suffix(call)
    return suffix in SOURCE_SUFFIXES or (
        suffix.startswith(SOURCE_PREFIX) and suffix != SOURCE_PREFIX + "d"
    )


class _FunctionState:
    def __init__(self) -> None:
        self.tainted: dict[str, str] = {}   # var -> source description
        self.aot_callables: set[str] = set()
        self.aot_steps: set[str] = set()


@register
class AotLaunderChecker(Checker):
    rule = "aot-launder"
    description = ("trees from restore/reshard_state/shm reads must go "
                   "through compile_cache.launder before a deserialized "
                   "Compiled call")
    hint = ("state = compile_cache.launder(state)  # jitted copy: "
            "re-stages every leaf into proper per-device buffers before "
            "the donating AOT executable runs")

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            for _symbol, func in module.functions():
                findings.extend(self._check_function(module, func))
        return findings

    # ------------------------------------------------------------ per-func

    def _check_function(self, module: Module,
                        func: ast.FunctionDef) -> list[Finding]:
        state = _FunctionState()
        findings: list[Finding] = []
        for stmt in func.body:
            self._walk_stmt(module, stmt, state, findings)
        return findings

    def _walk_stmt(self, module: Module, stmt: ast.stmt,
                   state: _FunctionState,
                   findings: list[Finding]) -> None:
        # nested defs get their own pass via Module.functions()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Assign):
            self._sink_check(module, stmt.value, state, findings)
            names: list[str] = []
            for target in stmt.targets:
                names.extend(_target_names(target))
            self._transfer(module, stmt.value, names, state)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._sink_check(module, stmt.value, state, findings)
            self._transfer(module, stmt.value,
                           _target_names(stmt.target), state)
        else:
            for node in ast.iter_child_nodes(stmt):
                if isinstance(node, ast.stmt):
                    self._walk_stmt(module, node, state, findings)
                elif isinstance(node, ast.expr):
                    self._sink_check(module, node, state, findings)
            return

    def _transfer(self, module: Module, value: ast.AST,
                  targets: list[str], state: _FunctionState) -> None:
        """Propagate taint/executable facts through one assignment."""
        if not targets:
            return
        if isinstance(value, ast.Call):
            suffix = module.call_suffix(value)
            if suffix == LAUNDER_SUFFIX:
                for name in targets:
                    state.tainted.pop(name, None)
                return
            if _is_source_call(module, value):
                for name in targets:
                    state.tainted[name] = suffix
                return
            if suffix in AOT_LOADER_SUFFIXES:
                state.aot_callables.update(targets)
                return
            if suffix == AOT_STEP_SUFFIX:
                state.aot_steps.update(targets)
                return
            # result of calling the executable itself is properly staged
            for name in targets:
                state.tainted.pop(name, None)
                state.aot_callables.discard(name)
            return
        if isinstance(value, ast.Name):
            for name in targets:
                if value.id in state.tainted:
                    state.tainted[name] = state.tainted[value.id]
                else:
                    state.tainted.pop(name, None)
                if value.id in state.aot_callables:
                    state.aot_callables.add(name)
            return
        if isinstance(value, ast.Attribute) and value.attr == "fn" \
                and isinstance(value.value, ast.Name) \
                and value.value.id in state.aot_steps:
            state.aot_callables.update(targets)
            return
        if isinstance(value, ast.Tuple):
            # conservative: tuple packs lose tracking
            for name in targets:
                state.tainted.pop(name, None)
            return
        for name in targets:
            state.tainted.pop(name, None)

    def _sink_check(self, module: Module, expr: ast.AST,
                    state: _FunctionState,
                    findings: list[Finding]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            is_aot_call = (
                (isinstance(callee, ast.Name)
                 and callee.id in state.aot_callables)
                or (isinstance(callee, ast.Attribute)
                    and callee.attr == "fn"
                    and isinstance(callee.value, ast.Name)
                    and callee.value.id in state.aot_steps)
            )
            if not is_aot_call:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) \
                            and sub.id in state.tainted:
                        findings.append(self.finding(
                            module, node,
                            f"host-built tree {sub.id!r} (from "
                            f"{state.tainted[sub.id]}) reaches a "
                            "deserialized Compiled call without "
                            "compile_cache.launder — CPU donation/"
                            "adoption corrupts restored buffers",
                        ))
                        break
