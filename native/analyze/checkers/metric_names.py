"""Rule ``metric-name``: metric + chaos-fault-point naming contracts.

This is the ported PR-1/PR-4 lint (`native/check_metric_names.py`, now
a shim over this module): every ``registry().counter/gauge/histogram``
registration uses a literal ``dlrover_tpu_[a-z_]+`` name, names are
registered at exactly one call site, contract-family names and labels
appear verbatim in DESIGN.md, and ``chaos.fire`` injection points are
literal, well-formed and documented. Journal spans moved to the
dedicated ``journal-span`` rule (AST-based, adds open/close pairing);
the legacy ``scan_spans`` function is kept here because the shim and
the telemetry tests call it directly.

The scanning stays regex-based on purpose — it predates the framework,
its behavior is pinned by tier-1 tests, and the name/site extraction
has no need for dataflow. The checker class adapts its problem strings
into framework findings.
"""

from __future__ import annotations

import os
import re
import sys

from native.analyze.core import Checker, Finding, Project, register

NAME_RE = re.compile(r"^dlrover_tpu_[a-z_]+$")
REG_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)
SPAN_NAME_RE = re.compile(r"^[a-z_]+$")
SPAN_RE = re.compile(
    r"\.\s*(emit|begin|span)\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)
# the journal implementation itself forwards caller-supplied names
# (EventJournal.span -> self.begin(name, ...)): not an emission site
SPAN_SCAN_EXCLUDE = (os.path.join("telemetry", "journal.py"),)

POINT_NAME_RE = re.compile(r"^[a-z_]+$")
POINT_RE = re.compile(
    r"chaos\s*\.\s*fire\(\s*(?:\n\s*)?"
    r"(?:(?P<q>['\"])(?P<name>[^'\"]+)(?P=q)|(?P<nonlit>[A-Za-z_f][^,)]*))"
)
# the chaos package itself forwards caller-supplied point names and its
# docstrings discuss the call form: not injection sites
POINT_SCAN_EXCLUDE = (os.path.join("dlrover_tpu", "chaos") + os.sep,)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
PKG = os.path.join(_REPO, "dlrover_tpu")
DESIGN_MD = os.path.join(_REPO, "DESIGN.md")
# metric families whose names are an operator contract: every
# registered name under these prefixes must appear verbatim in DESIGN.md
DOCUMENTED_PREFIXES = (
    "dlrover_tpu_gateway_",
    "dlrover_tpu_standby_",
    "dlrover_tpu_snapshot_interval_",
    # elastic resharding + compile cache (DESIGN.md §17): the runbook
    # "failover is recompiling" keys on these names
    "dlrover_tpu_compile_cache_",
    "dlrover_tpu_reshard_",
    # efficiency observatory (DESIGN.md §18): the "MFU dropped" runbook
    # keys on the live MFU gauge, the step-phase histogram, and the
    # profiler-capture counters
    "dlrover_tpu_mfu",
    "dlrover_tpu_step_phase_",
    "dlrover_tpu_profile_",
    # parallel persist / verified restore (DESIGN.md §20): the "restore
    # after shrinking the job" runbook keys on the ckpt family
    "dlrover_tpu_ckpt_",
    # MPMD pipeline runtime (DESIGN.md §21): the "one pipeline stage is
    # slow / recompiling" runbook keys on the per-stage families
    "dlrover_tpu_pipeline_",
    # control-plane observatory (DESIGN.md §22): the "master is slow"
    # runbook keys on the dispatch/lock/ingest attribution families
    "dlrover_tpu_master_",
    # disaggregated serving data plane (DESIGN.md §23): the "TTFT is
    # spiking" runbook keys on the decode-stall histogram and the
    # paged-KV park/handoff counters
    "dlrover_tpu_engine_",
    # strategy autopilot (DESIGN.md §24): the "autopilot picked a bad
    # plan" runbook keys on the plan/retune counters and the
    # contradiction gauges
    "dlrover_tpu_autopilot_",
    # elastic embedding fabric (DESIGN.md §25): the "embedding
    # staleness is climbing" runbook keys on the staleness gauge and
    # the backpressure/apply-lag families
    "dlrover_tpu_embedding_",
    # master crash-failover (DESIGN.md §26): the "the master died"
    # runbook keys on the degraded/unreachable/reconcile/redelivery
    # families and the epoch gauge
    "dlrover_tpu_agent_",
    # causal trace fabric (DESIGN.md §27): the "where did this
    # request's / incident's time go" runbook keys on the span-write
    # and head-sampling-drop counters
    "dlrover_tpu_trace_",
    # rack sub-master tier (DESIGN.md §28): the "scaling past 1k
    # nodes" runbook keys on the merge/epoch/cache-lookup families
    # and the comm-world diff byte counters
    "dlrover_tpu_submaster_",
    # serving memory observatory (DESIGN.md §29): the "is the KV pool
    # the bottleneck" runbook keys on the request-latency family and
    # the engine kv_/draft_ gauges (covered by the engine_ prefix)
    "dlrover_tpu_serving_",
    # partition tolerance (DESIGN.md §30): the "a rack is partitioned
    # from the root" runbook keys on the link-transition/drop counters
    # and the lease-expiry / push-fence families
    "dlrover_tpu_partition_",
    # serving raw speed (DESIGN.md §31): the "acceptance collapsed"
    # runbook keys on the speculative-decode verify/accept families
    # (the COW kv_cow_ gauges ride the engine_/gateway_ prefixes)
    "dlrover_tpu_spec_",
)

# label names that are themselves an operator contract (dashboards and
# runbooks filter on them): each must be used by a registration in the
# package AND appear verbatim in DESIGN.md
CONTRACT_LABELS = ("straggler_phase",)


def check_contract_labels(pkg_dir: str = PKG,
                          design_path: str = DESIGN_MD) -> list[str]:
    """Contract labels must exist in code and be documented."""
    problems: list[str] = []
    source = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if fname.endswith(".py"):
                with open(os.path.join(root, fname),
                          encoding="utf-8") as f:
                    source.append(f.read())
    source_text = "\n".join(source)
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        return [f"cannot read {design_path}: {e}"]
    for label in CONTRACT_LABELS:
        if f'"{label}"' not in source_text \
                and f"'{label}'" not in source_text:
            problems.append(
                f"contract label {label!r} is not used by any metric "
                "registration in the package"
            )
        if label not in design:
            problems.append(
                f"contract label {label!r} is not documented in "
                "DESIGN.md; add it to its metrics table"
            )
    return problems


def check_documented(names: dict[str, list[str]],
                     design_path: str = DESIGN_MD) -> list[str]:
    """Every contract-family metric registered in code must appear in
    DESIGN.md (gateway, warm-standby, interval tuner)."""
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        return [f"cannot read {design_path}: {e}"]
    return [
        f"metric {name!r} ({', '.join(sites)}) is not documented in "
        f"DESIGN.md; add it to its metrics table"
        for name, sites in sorted(names.items())
        if any(name.startswith(p) for p in DOCUMENTED_PREFIXES)
        and name not in design
    ]


def scan_spans(pkg_dir: str = PKG,
               design_path: str = DESIGN_MD) -> tuple[dict[str, list[str]],
                                                      list[str]]:
    """(span name -> [emission sites], problems) for journal spans.

    Legacy entry point kept for the shim and the telemetry tests; the
    framework's ``journal-span`` rule supersedes it (AST walk + begin/
    end pairing) but asserts the same naming/documentation contract.
    """
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            if rel.endswith(SPAN_SCAN_EXCLUDE):
                continue
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in SPAN_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    problems.append(
                        f"{site}: journal span emitted with a non-literal "
                        f"name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not SPAN_NAME_RE.match(name):
                    problems.append(
                        f"{site}: span name {name!r} does not match "
                        f"{SPAN_NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        problems.append(f"cannot read {design_path}: {e}")
        return names, problems
    for name, sites in sorted(names.items()):
        if name not in design:
            problems.append(
                f"journal span {name!r} ({', '.join(sites)}) is not "
                f"documented in DESIGN.md; add it to the span-name table"
            )
    return names, problems


def scan_fault_points(pkg_dir: str = PKG,
                      design_path: str = DESIGN_MD
                      ) -> tuple[dict[str, list[str]], list[str]]:
    """(fault point name -> [injection sites], problems) for the chaos
    harness's ``chaos.fire("...")`` call sites."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            if any(ex in rel for ex in POINT_SCAN_EXCLUDE):
                continue
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in POINT_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    problems.append(
                        f"{site}: chaos fault point fired with a "
                        f"non-literal name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not POINT_NAME_RE.match(name):
                    problems.append(
                        f"{site}: fault point name {name!r} does not "
                        f"match {POINT_NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    try:
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
    except OSError as e:
        problems.append(f"cannot read {design_path}: {e}")
        return names, problems
    for name, sites in sorted(names.items()):
        if name not in design:
            problems.append(
                f"chaos fault point {name!r} ({', '.join(sites)}) is not "
                f"documented in DESIGN.md; add it to the fault-point table"
            )
    return names, problems


def scan(pkg_dir: str = PKG,
         design_path: str = DESIGN_MD
         ) -> tuple[dict[str, list[str]], list[str]]:
    """(name -> [call sites], problems)."""
    names: dict[str, list[str]] = {}
    problems: list[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, os.path.dirname(pkg_dir))
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for match in REG_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                site = f"{rel}:{line}"
                if match.group("name") is None:
                    # non-literal first argument: the lint (and grep-
                    # ability) relies on literal names at the call site
                    problems.append(
                        f"{site}: metric registered with a non-literal "
                        f"name ({match.group('nonlit')!r})"
                    )
                    continue
                name = match.group("name")
                if not NAME_RE.match(name):
                    problems.append(
                        f"{site}: metric name {name!r} does not match "
                        f"{NAME_RE.pattern}"
                    )
                names.setdefault(name, []).append(site)
    for name, sites in sorted(names.items()):
        if len(sites) > 1:
            problems.append(
                f"metric {name!r} registered at {len(sites)} call sites "
                f"({', '.join(sites)}); names must be unique"
            )
    problems.extend(check_documented(names, design_path=design_path))
    return names, problems


_SITE_RE = re.compile(r"^(?P<path>[^:\s]+):(?P<line>\d+): (?P<msg>.*)$",
                      re.DOTALL)


def _problem_to_finding(rule: str, problem: str, hint: str,
                        fallback_path: str) -> Finding:
    """Adapt a legacy 'rel:line: msg' problem string into a Finding.

    The line is carried separately and stripped from the message so the
    baseline key stays stable when code above the site moves.
    """
    match = _SITE_RE.match(problem)
    if match:
        return Finding(rule=rule, path=match.group("path"),
                       line=int(match.group("line")),
                       message=match.group("msg"), hint=hint)
    return Finding(rule=rule, path=fallback_path, line=1,
                   message=problem, hint=hint)


@register
class MetricNamesChecker(Checker):
    rule = "metric-name"
    description = ("metric registrations use unique literal "
                   "dlrover_tpu_[a-z_]+ names; contract families, "
                   "labels and chaos fault points documented in "
                   "DESIGN.md")
    hint = ('registry().counter("dlrover_tpu_<subsystem>_<what>", ...) '
            "with a string literal; add contract-family names to their "
            "DESIGN.md metrics table")

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        pkg = project.package_dir
        design = project.design_path
        _, problems = scan(pkg, design_path=design)
        for p in problems:
            findings.append(_problem_to_finding(
                self.rule, p, self.hint, project.package))
        _, point_problems = scan_fault_points(pkg, design_path=design)
        for p in point_problems:
            findings.append(_problem_to_finding(
                self.rule, p,
                'chaos.fire("<point_name>") with a literal [a-z_]+ name '
                "documented in the DESIGN.md fault-point table",
                project.package))
        for p in check_contract_labels(pkg, design_path=design):
            findings.append(_problem_to_finding(
                self.rule, p, self.hint, project.package))
        return findings


def main() -> int:
    names, problems = scan()
    span_names, span_problems = scan_spans()
    point_names, point_problems = scan_fault_points()
    problems = (problems + span_problems + point_problems
                + check_contract_labels())
    if problems:
        for p in problems:
            print(f"check_metric_names: {p}", file=sys.stderr)
        return 1
    print(f"check_metric_names: {len(names)} metric names, "
          f"{len(span_names)} span names, "
          f"{len(point_names)} chaos fault points OK")
    return 0
