"""Rule ``storage-interface``: every ``CheckpointStorage`` subclass
implements the full backend contract.

The checkpoint layer (DESIGN.md §20) treats storage as pluggable: the
agent persister, the integrity verifier and the topology-changing
restore all dispatch through the ``CheckpointStorage`` interface, and a
backend that silently lacks one of the six abstract operations fails at
the worst possible time — inside a recovery path, on the first restore
after a shrink. Python's ABC machinery only raises at INSTANTIATION,
which for the agent-side persister happens after a crash, via
``build_storage`` reflection — far from the author's test run. This
rule moves that failure to lint time: any class that (transitively,
within the project) inherits ``CheckpointStorage`` must define or
inherit ``write``/``read``/``exists``/``listdir``/``makedirs``/
``delete``. The ranged/chunked operations (``read_range``, ``size``,
``write_parallel``) have correct whole-blob defaults in the base class
and are deliberately not required.
"""

from __future__ import annotations

import ast

from native.analyze.core import (
    Checker,
    Finding,
    Module,
    Project,
    register,
)

BASE_NAME = "CheckpointStorage"
REQUIRED = ("write", "read", "exists", "listdir", "makedirs", "delete")


def _class_info(project: Project) -> dict[str, tuple[Module, ast.ClassDef,
                                                     list[str], set[str]]]:
    """qualified class name -> (module, node, resolved base paths,
    defined method names). Classes are also keyed by their bare name so
    same-module inheritance resolves without imports."""
    info: dict = {}
    for module in project.modules:
        mod_path = module.relpath[:-3].replace("/", ".")
        for node in module.classes():
            bases = []
            for base in node.bases:
                q = module.qualname(base)
                if q:
                    bases.append(q)
            methods = {
                item.name for item in node.body
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
            }
            entry = (module, node, bases, methods)
            info[f"{mod_path}.{node.name}"] = entry
            info.setdefault(node.name, entry)
    return info


@register
class StorageInterfaceChecker(Checker):
    rule = "storage-interface"
    description = ("CheckpointStorage subclasses define or inherit the "
                   "full abstract backend interface (write/read/exists/"
                   "listdir/makedirs/delete)")
    hint = ("implement the missing methods (or inherit a complete "
            "backend); the checkpoint layer reaches every one of them "
            "through build_storage reflection inside recovery paths, "
            "where an AttributeError costs the restore")

    def check(self, project: Project) -> list[Finding]:
        info = _class_info(project)
        findings: list[Finding] = []
        for name, (module, node, bases, _methods) in info.items():
            if "." not in name:
                continue  # bare-name alias entries
            if node.name == BASE_NAME:
                continue
            chain = self._storage_chain(name, info)
            if chain is None:
                continue
            inherited: set[str] = set()
            for link in chain:
                entry = info.get(link)
                if entry is None or entry[1].name == BASE_NAME:
                    # the ABC's own abstract stubs are declarations,
                    # not implementations — they must not satisfy the
                    # contract for a subclass
                    continue
                inherited |= entry[3]
            missing = [m for m in REQUIRED if m not in inherited]
            if missing:
                findings.append(self.finding(
                    module, node,
                    f"storage backend {node.name} is missing "
                    f"{', '.join(missing)} — the CheckpointStorage "
                    "contract the recovery paths dispatch through",
                ))
        return findings

    def _storage_chain(self, name: str, info: dict) -> list[str] | None:
        """The project-visible inheritance chain from ``name`` down to
        CheckpointStorage (inclusive of ``name``), or None when the
        class is not a storage backend."""
        seen: set[str] = set()
        chain: list[str] = []

        def walk(n: str) -> bool:
            if n in seen:
                return False
            seen.add(n)
            entry = info.get(n)
            if entry is None:
                # unknown base: a storage subclass only if the name
                # itself says so
                return n == BASE_NAME or n.endswith(f".{BASE_NAME}")
            chain.append(n)
            if entry[1].name == BASE_NAME:
                return True
            return any(walk(b) for b in entry[2])

        hit = walk(name)
        return chain if hit else None
