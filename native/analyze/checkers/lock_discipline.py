"""Rule ``lock-discipline``: shared state touched by daemon threads is
mutated under a held lock, and lock acquisition order is cycle-free.

The elastic recovery paths (ElasWave-style resharding, warm standby,
buddy replication — PAPERS.md) are full of classes that spawn
``threading.Thread(target=self._loop, daemon=True)`` and then mutate
``self.*`` attributes both from that loop and from the caller-facing
API. Until now the "hold the lock" rule was convention enforced by
review; this checker makes it structural:

- Per class, find *thread-entry* methods: ``target=self.X`` of any
  ``threading.Thread(...)`` construction in the class, plus ``run`` on
  ``Thread`` subclasses. Methods reachable from an entry through
  ``self.Y()`` calls count as thread context too.
- An attribute mutated (assigned/augassigned/subscript-stored) both in
  thread context and in non-thread methods (``__init__`` excluded —
  it runs before the thread exists) is *shared*; every mutation site of
  a shared attribute must sit inside ``with self.<lock>:`` for some
  lock attribute (``threading.Lock/RLock/Condition`` created in the
  class). A class with shared mutations and no lock at all is flagged
  once at the class line.
- While walking ``with self.A:`` bodies, nested ``with self.B:`` adds
  the edge ``Class.A -> Class.B`` to a project-wide acquisition graph;
  any cycle is a deadlock ordering and is reported on one edge site.
"""

from __future__ import annotations

import ast
import dataclasses

from native.analyze.core import (
    Checker,
    Finding,
    Module,
    Project,
    dotted,
    register,
)

# TimedLock is master/saturation.py's instrumented threading.Lock
# wrapper: same guard semantics, so it earns the same credit
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "TimedLock"}
_MUTATOR_EXEMPT_METHODS = {"__init__", "__post_init__"}


def _is_self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class _Mutation:
    attr: str
    method: str
    node: ast.AST
    guarded: bool


class _ClassInfo:
    def __init__(self, module: Module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.methods: dict[str, ast.FunctionDef] = {
            child.name: child for child in node.body
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: set[str] = set()
        self.thread_entries: set[str] = set()
        self.calls: dict[str, set[str]] = {}   # method -> self.X() callees
        self.mutations: list[_Mutation] = []
        self.lock_edges: list[tuple[str, str, ast.AST]] = []
        self._scan()

    # ------------------------------------------------------------- scanning

    def _scan(self) -> None:
        is_thread_subclass = any(
            (dotted(base) or "").endswith("Thread")
            for base in self.node.bases
        )
        if is_thread_subclass and "run" in self.methods:
            self.thread_entries.add("run")
        for name, method in self.methods.items():
            self._scan_method(name, method)

    def _scan_method(self, method_name: str,
                     method: ast.FunctionDef) -> None:
        callees: set[str] = set()
        self.calls[method_name] = callees
        held: list[str] = []   # stack of held self-lock attrs

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not method:
                return   # nested defs: skip (closures get no credit)
            if isinstance(node, ast.With):
                # only bare `with self._lock:` counts — explicit
                # .acquire() calls don't establish a guard scope
                lock_attrs = [
                    attr for item in node.items
                    if (attr := _is_self_attr(item.context_expr))
                    is not None and attr in self.lock_attrs
                ]
                for attr in lock_attrs:
                    for holder in held:
                        if holder != attr:
                            self.lock_edges.append((holder, attr, node))
                held.extend(lock_attrs)
                for item in node.items:
                    visit(item.context_expr)
                for child in node.body:
                    visit(child)
                for _ in lock_attrs:
                    held.pop()
                return
            # lock attribute creation
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                suffix = self.module.call_suffix(node.value)
                if suffix in _LOCK_FACTORIES:
                    for target in node.targets:
                        attr = _is_self_attr(target)
                        if attr is not None:
                            self.lock_attrs.add(attr)
            # thread entry discovery: threading.Thread(target=self.X)
            if isinstance(node, ast.Call):
                suffix = self.module.call_suffix(node)
                if suffix == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            attr = _is_self_attr(kw.value)
                            if attr is not None:
                                self.thread_entries.add(attr)
                callee_attr = _is_self_attr(node.func)
                if callee_attr is not None:
                    callees.add(callee_attr)
            # mutations
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    self._record_mutation(target, method_name, held)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                self._record_mutation(node.target, method_name, held)
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(method)

    def _record_mutation(self, target: ast.AST, method_name: str,
                         held: list[str]) -> None:
        attr = _is_self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            attr = _is_self_attr(target.value)
        if attr is None and isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_mutation(elt, method_name, held)
            return
        if attr is None:
            return
        self.mutations.append(_Mutation(
            attr=attr, method=method_name, node=target,
            guarded=bool(held),
        ))

    # ------------------------------------------------------------ analysis

    def thread_methods(self) -> set[str]:
        """Entries plus methods reachable from them via self.X() calls."""
        reachable = set(self.thread_entries)
        frontier = list(reachable)
        while frontier:
            current = frontier.pop()
            for callee in self.calls.get(current, ()):
                if callee in self.methods and callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)
        return reachable

    def shared_unguarded(self) -> tuple[list[_Mutation], set[str]]:
        """Unguarded mutation sites of attributes mutated in both thread
        and non-thread contexts; plus the set of shared attrs."""
        in_thread = self.thread_methods()
        by_attr: dict[str, list[_Mutation]] = {}
        for mutation in self.mutations:
            if mutation.method in _MUTATOR_EXEMPT_METHODS:
                continue
            if mutation.attr in self.lock_attrs:
                continue
            by_attr.setdefault(mutation.attr, []).append(mutation)
        shared: set[str] = set()
        unguarded: list[_Mutation] = []
        for attr, sites in by_attr.items():
            contexts = {site.method in in_thread for site in sites}
            if contexts != {True, False}:
                continue   # mutated from one side only
            shared.add(attr)
            seen_methods: set[str] = set()
            for site in sites:
                if site.guarded or site.method in seen_methods:
                    continue
                seen_methods.add(site.method)   # one finding per method
                unguarded.append(site)
        return unguarded, shared


@register
class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = ("attributes mutated both inside daemon-thread context "
                   "and outside must be mutated under a held lock; the "
                   "lock acquisition graph must be cycle-free")
    hint = ("guard every mutation site: `with self._lock: self.attr = "
            "...` (create `self._lock = threading.Lock()` in __init__); "
            "for ordering cycles, acquire locks in one global order or "
            "collapse to a single lock")

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        all_edges: list[tuple[str, str, Module, ast.AST]] = []
        for module in project.modules:
            for class_node in module.classes():
                info = _ClassInfo(module, class_node)
                if not info.thread_entries:
                    continue
                unguarded, shared = info.shared_unguarded()
                if unguarded and not info.lock_attrs:
                    findings.append(self.finding(
                        module, class_node,
                        f"class {info.name} runs thread(s) "
                        f"({', '.join(sorted(info.thread_entries))}) and "
                        f"mutates shared attribute(s) "
                        f"{sorted(shared)} with no lock attribute at all",
                    ))
                    continue
                for site in unguarded:
                    findings.append(self.finding(
                        module, site.node,
                        f"{info.name}.{site.attr} is mutated in "
                        f"{site.method}() without a held lock, but is "
                        "also mutated from "
                        + ("thread context"
                           if site.method not in info.thread_methods()
                           else "non-thread context"),
                    ))
                for src, dst, node in info.lock_edges:
                    all_edges.append((f"{info.name}.{src}",
                                      f"{info.name}.{dst}", module, node))
        findings.extend(self._cycle_findings(all_edges))
        return findings

    def _cycle_findings(
        self, edges: list[tuple[str, str, Module, ast.AST]]
    ) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        sites: dict[tuple[str, str], tuple[Module, ast.AST]] = {}
        for src, dst, module, node in edges:
            graph.setdefault(src, set()).add(dst)
            sites.setdefault((src, dst), (module, node))
        findings: list[Finding] = []
        reported: set[frozenset[str]] = set()

        def dfs(node: str, stack: list[str], visiting: set[str],
                done: set[str]) -> None:
            visiting.add(node)
            stack.append(node)
            for nxt in sorted(graph.get(node, ())):
                if nxt in visiting:
                    cycle = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        module, site = sites[(node, nxt)]
                        findings.append(self.finding(
                            module, site,
                            "lock acquisition cycle "
                            + " -> ".join(cycle)
                            + " — two threads taking opposite ends "
                            "deadlock",
                        ))
                elif nxt not in done:
                    dfs(nxt, stack, visiting, done)
            stack.pop()
            visiting.discard(node)
            done.add(node)

        done: set[str] = set()
        for node in sorted(graph):
            if node not in done:
                dfs(node, [], set(), done)
        return findings
