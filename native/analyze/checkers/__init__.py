"""Production checkers. Importing this package registers every rule
with ``native.analyze.core.CHECKERS``."""

from native.analyze.checkers import (  # noqa: F401
    aot_launder,
    atomic_write,
    env_registry,
    journal_span,
    lock_discipline,
    metric_names,
    rpc_contract,
    storage_interface,
)
