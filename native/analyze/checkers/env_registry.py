"""Rule ``env-registry``: every ``DLROVER_TPU_*`` env var resolves
through the ``common/envspec.py`` registry.

What used to be 100+ scattered ``os.environ`` reads with duplicated
defaults is now a closed contract (see ``common/envspec.py``'s module
docstring for the full rationale):

1. ``DLROVER_TPU_*`` string literals are legal ONLY in
   ``common/constants.py`` (the ``EnvKey`` names) and
   ``common/envspec.py`` (the registry) — call sites must go through
   ``EnvKey``/envspec helpers, so every var name is greppable from one
   place;
2. ``EnvKey`` constants and registry entries are a bijection — you
   cannot add a name without declaring default/restart/anchor metadata,
   nor register a var no constant exposes;
3. every registered var appears verbatim in DESIGN.md (the generated
   §19 reference table);
4. module-level (import-time) env reads are legal only for vars
   declared ``restart_required=True`` — an import-time read silently
   freezes the value per process, so the registry must say so.

All checks are static (the registry and EnvKey are parsed, never
imported), so the rule works on test fixtures too.
"""

from __future__ import annotations

import ast
import re

from native.analyze.core import (
    Checker,
    Finding,
    Module,
    Project,
    call_arg,
    dotted,
    literal_str,
    register,
)

VAR_RE = re.compile(r"^DLROVER_TPU_[A-Z0-9_]*[A-Z0-9]$")

ALLOWED_LITERAL_SUFFIXES = ("common/constants.py", "common/envspec.py")

CONSTANTS_SUFFIX = "common/constants.py"
ENVSPEC_SUFFIX = "common/envspec.py"


def parse_envkey(module: Module) -> dict[str, str]:
    """EnvKey attribute -> literal var name, from constants.py."""
    out: dict[str, str] = {}
    for node in module.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "EnvKey":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.targets[0], ast.Name):
                    value = literal_str(stmt.value)
                    if value is not None:
                        out[stmt.targets[0].id] = value
    return out


def parse_envspec(module: Module) -> dict[str, dict]:
    """var name -> {restart_required, anchor, line}, from the EnvVar
    constructions in envspec.py."""
    specs: dict[str, dict] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name_node = dotted(node.func)
        if not name_node or name_node.rsplit(".", 1)[-1] != "EnvVar":
            continue
        name_arg = call_arg(node, 0, "name")
        name = literal_str(name_arg) if name_arg is not None else None
        if name is None:
            continue
        restart = False
        restart_arg = call_arg(node, 4, "restart_required")
        if isinstance(restart_arg, ast.Constant):
            restart = bool(restart_arg.value)
        anchor_arg = call_arg(node, 3, "anchor")
        anchor = literal_str(anchor_arg) if anchor_arg is not None else ""
        specs[name] = {
            "restart_required": restart,
            "anchor": anchor or "",
            "line": node.lineno,
        }
    return specs


def _env_read_name(node: ast.Call | ast.Subscript, module: Module,
                   envkey: dict[str, str]) -> str | None:
    """The var name an os.environ read resolves to (literal or EnvKey
    attribute), else None."""
    if isinstance(node, ast.Call):
        base = dotted(node.func)
        if not base or not base.endswith("environ.get"):
            return None
        arg = call_arg(node, 0, "key")
    else:
        base = dotted(node.value)
        if not base or not base.endswith("environ"):
            return None
        arg = node.slice
    if arg is None:
        return None
    lit = literal_str(arg)
    if lit is not None:
        return lit
    text = dotted(arg)
    if text:
        attr = text.rsplit(".", 1)[-1]
        if attr in envkey:
            return envkey[attr]
    return None


@register
class EnvRegistryChecker(Checker):
    rule = "env-registry"
    description = ("DLROVER_TPU_* env vars resolve through the "
                   "common/envspec.py registry: literals only in "
                   "constants/envspec, EnvKey<->registry bijection, "
                   "DESIGN.md documented, import-time reads only when "
                   "restart_required")
    hint = ("declare the var once: EnvKey.<NAME> in common/constants.py "
            "+ EnvVar(...) in common/envspec.py (default, restart flag, "
            "DESIGN.md anchor), then read via os.environ.get(EnvKey.X) "
            "or envspec.get/get_bool; refresh the DESIGN.md table with "
            "`python -m native.analyze --env-table`")

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        constants = project.module_by_suffix(CONSTANTS_SUFFIX)
        envspec = project.module_by_suffix(ENVSPEC_SUFFIX)
        envkey = parse_envkey(constants) if constants else {}
        specs = parse_envspec(envspec) if envspec else {}

        # 1. literals outside the two declaration files
        for module in project.modules:
            if module.relpath.endswith(ALLOWED_LITERAL_SUFFIXES):
                continue
            for node in ast.walk(module.tree):
                value = literal_str(node)
                if value is not None and VAR_RE.match(value):
                    findings.append(self.finding(
                        module, node,
                        f"raw env-var literal {value!r} outside the "
                        "registry — use EnvKey/envspec so the name "
                        "resolves through common/envspec.py",
                    ))

        # 2. bijection (only when both declaration files exist — test
        # fixtures may exercise just the literal rule)
        if constants is not None and envspec is not None:
            for attr, name in sorted(envkey.items()):
                if name not in specs:
                    findings.append(self.finding(
                        constants, constants.tree,
                        f"EnvKey.{attr} ({name}) has no EnvVar entry in "
                        "common/envspec.py",
                    ))
            for name, meta in sorted(specs.items()):
                if VAR_RE.match(name) and name not in envkey.values():
                    findings.append(Finding(
                        rule=self.rule, path=envspec.relpath,
                        line=meta["line"],
                        message=f"registered var {name} has no EnvKey "
                                "constant",
                        hint=self.hint, symbol="<module>",
                    ))
            # 3. documentation
            for name, meta in sorted(specs.items()):
                if name not in project.design_text:
                    findings.append(Finding(
                        rule=self.rule, path=envspec.relpath,
                        line=meta["line"],
                        message=f"registered var {name} is not "
                                "documented in DESIGN.md; regenerate "
                                "the §19 env table",
                        hint=self.hint, symbol="<module>",
                    ))

        # 4. import-time reads
        for module in project.modules:
            findings.extend(
                self._import_time_reads(module, envkey, specs)
            )
        return findings

    def _module_level_nodes(self, module: Module):
        """Statements executed at import: module body plus class bodies
        at module level (function bodies excluded)."""
        def expand(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.ClassDef):
                    yield from expand(stmt.body)
                else:
                    yield stmt

        yield from expand(module.tree.body)

    def _import_time_reads(self, module: Module,
                           envkey: dict[str, str],
                           specs: dict[str, dict]) -> list[Finding]:
        findings: list[Finding] = []
        if not specs:
            return findings
        for stmt in self._module_level_nodes(module):
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.Call, ast.Subscript)):
                    continue
                name = _env_read_name(node, module, envkey)
                if name is None or not VAR_RE.match(name):
                    continue
                spec = specs.get(name)
                if spec is not None and spec["restart_required"]:
                    continue
                findings.append(self.finding(
                    module, node,
                    f"import-time read of {name} which is not declared "
                    "restart_required in envspec — the value freezes "
                    "per process; move the read into the consumer or "
                    "flag the var restart_required",
                ))
        return findings
