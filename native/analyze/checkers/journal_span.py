"""Rule ``journal-span``: journal emissions use literal, documented
names, and every ``begin`` has a matching ``end``.

Span names are the contract ``telemetry/report.py`` attributes lost
time by and ``telemetry/timeline.py`` renders lanes from; an
undocumented or dynamic name is a span the operator cannot read, and a
``begin`` with no ``end`` renders every run as "process died inside
the span" even when it didn't. Subsumes (as AST, not regex) the span
half of the original ``native/check_metric_names.py`` lint and adds
the open/close pairing the regex could never see:

- ``.emit("name")`` / ``.begin("name")`` / ``.span("name")`` first
  arguments must be string literals matching ``[a-z_]+`` and appear
  verbatim in DESIGN.md;
- a ``sid = X.begin("name")`` must be paired, within the same function
  or (via a ``self.attr``) the same class, with an ``X.end(sid, ...)``
  — the ``span()`` context manager pairs itself and is always fine;
- a ``remote_parent=`` argument must be an expression (an envelope /
  payload / spawn-env field), never a string literal: a literal
  context would hard-wire fake causality into the trace fabric
  (DESIGN.md §27).

``telemetry/journal.py`` is excluded: it implements the API and
forwards caller-supplied names.
"""

from __future__ import annotations

import ast
import re

from native.analyze.core import (
    Checker,
    Finding,
    Module,
    Project,
    literal_str,
    register,
)

SPAN_NAME_RE = re.compile(r"^[a-z_]+$")
EXCLUDE_SUFFIXES = ("telemetry/journal.py",)
SPAN_METHODS = ("emit", "begin", "span")


def _first_arg(call: ast.Call) -> ast.AST | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


@register
class JournalSpanChecker(Checker):
    rule = "journal-span"
    description = ("journal span names are literal [a-z_]+ documented "
                   "in DESIGN.md; every .begin() is paired with .end() "
                   "in the same function or class")
    hint = ('use `with journal.span("name"):` (self-pairing), or keep '
            "the begin's span id and call `journal.end(sid, \"name\", "
            "start=t0)` on every exit path; document the name in the "
            "DESIGN.md span table")

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            if module.relpath.endswith(EXCLUDE_SUFFIXES):
                continue
            findings.extend(self._check_names(module, project))
            findings.extend(self._check_pairing(module))
        return findings

    # ----------------------------------------------------------- span names

    def _check_names(self, module: Module,
                     project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in SPAN_METHODS):
                continue
            arg = _first_arg(node)
            if arg is None:
                continue
            name = literal_str(arg)
            if name is None:
                # non-literal: f-strings/vars defeat grep and the
                # DESIGN.md contract
                findings.append(self.finding(
                    module, node,
                    f"journal .{node.func.attr}() with a non-literal "
                    "span name — names must be grep-able literals",
                ))
                continue
            if not SPAN_NAME_RE.match(name):
                findings.append(self.finding(
                    module, node,
                    f"span name {name!r} does not match "
                    f"{SPAN_NAME_RE.pattern}",
                ))
                continue
            if name not in project.design_text:
                findings.append(self.finding(
                    module, node,
                    f"journal span {name!r} is not documented in "
                    "DESIGN.md; add it to the span-name table",
                ))
            for kw in node.keywords:
                if kw.arg == "remote_parent" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value:
                    # a literal remote_parent fabricates causality: the
                    # context must arrive through an RPC envelope,
                    # message payload field, or the spawn environment
                    findings.append(self.finding(
                        module, node,
                        f"journal span {name!r} passes a literal "
                        "remote_parent — the context string must come "
                        "from an envelope/payload/spawn-env field "
                        "(§27), never be hard-wired",
                    ))
        return findings

    # -------------------------------------------------------- begin pairing

    def _check_pairing(self, module: Module) -> list[Finding]:
        findings: list[Finding] = []
        # class-level pass: begin stored to self.attr may be ended in a
        # sibling method
        for class_node in module.classes():
            ended_attrs = self._ended_self_attrs(class_node)
            for item in class_node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    findings.extend(self._check_function(
                        module, item, ended_attrs))
        # module-level functions
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(module, node, set()))
        return findings

    def _ended_self_attrs(self, class_node: ast.ClassDef) -> set[str]:
        """self attributes passed as first arg to any .end() call in the
        class."""
        ended: set[str] = set()
        for node in ast.walk(class_node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "end" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Attribute) \
                        and isinstance(first.value, ast.Name) \
                        and first.value.id == "self":
                    ended.add(first.attr)
        return ended

    def _check_function(self, module: Module, func: ast.FunctionDef,
                        class_ended: set[str]) -> list[Finding]:
        begins: list[tuple[str | None, str | None, ast.Call]] = []
        ended_names: set[str] = set()
        for node in ast.walk(func):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr == "end" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    ended_names.add(first.id)
                elif isinstance(first, ast.Attribute) \
                        and isinstance(first.value, ast.Name) \
                        and first.value.id == "self":
                    ended_names.add(f"self.{first.attr}")
        # find begin assignments and bare begins
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr == "begin":
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    begins.append((target.id, None, node.value))
                elif isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self":
                    begins.append((None, target.attr, node.value))
                else:
                    begins.append((None, None, node.value))
            elif isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr == "begin":
                # begin whose span id is dropped can never be ended
                begins.append((None, None, node.value))
        findings: list[Finding] = []
        for var, attr, call in begins:
            if var is not None and var in ended_names:
                continue
            if attr is not None and (attr in class_ended
                                     or f"self.{attr}" in ended_names):
                continue
            name = literal_str(_first_arg(call) or ast.Constant(value=""))
            findings.append(self.finding(
                module, call,
                f"journal .begin({(name or '<dynamic>')!r}) has no "
                "matching .end() in the same function/class — the span "
                "reads as 'process died inside' on every run",
            ))
        return findings
