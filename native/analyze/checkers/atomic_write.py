"""Rule ``atomic-write``: files another process consumes must be
published atomically.

The integrity story PR 4 built (Orbax-style, PAPERS.md) only holds if
every cross-process handoff file — promotion payloads, request/ready/
done markers, port files, snapshots, chaos plans — appears on disk
either complete or not at all. ``common/storage.atomic_write_file``
(tmp + fsync + rename) is the blessed publisher; it is also the chaos
harness's ``storage_write`` injection point, so a handoff that bypasses
it silently escapes fault coverage too.

Heuristic: an ``open(path, "w"/"wb")`` (or ``.write_text``/
``.write_bytes``) whose path expression mentions a handoff token
(payload/request/ready/done/port/plan/...) is flagged, unless the
enclosing function already implements the tmp+rename idiom
(``os.replace``/``os.rename`` present) or delegates to
``atomic_write_file``. ``common/storage.py`` itself is exempt (it is
the implementation and the chaos torn-write site).
"""

from __future__ import annotations

import ast
import re

from native.analyze.core import Checker, Finding, Module, Project, register

HANDOFF_TOKENS = (
    "payload",
    "request",
    "response",
    "ready",
    "done",
    "marker",
    "port",
    "plan",
    "prepare",
    "handshake",
    "snapshot",
)

EXEMPT_SUFFIXES = ("common/storage.py",)

_ATOMIC_CALLS = {"replace", "rename", "atomic_write_file"}


def _write_mode(call: ast.Call) -> bool:
    """True for open(..., "w"/"wb"/"w+"...) literal modes."""
    mode_node = None
    if len(call.args) > 1:
        mode_node = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
    if not isinstance(mode_node, ast.Constant) \
            or not isinstance(mode_node.value, str):
        return False
    mode = mode_node.value
    return "w" in mode and "r" not in mode and "a" not in mode


_WORD_RE = re.compile(r"[a-z]+")


def _expr_tokens(node: ast.AST) -> set[str]:
    """Lowercased word chunks of an expression (identifiers split on
    underscores/case so 'report'/'transport' never match 'port')."""
    try:
        text = ast.unparse(node).lower()
    except Exception:  # pragma: no cover - unparse of odd nodes
        return set()
    return set(_WORD_RE.findall(text))


def _function_is_atomic(func: ast.AST) -> bool:
    """The enclosing scope already publishes via rename or delegates to
    atomic_write_file."""
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            callee = node.func
            name = callee.attr if isinstance(callee, ast.Attribute) \
                else getattr(callee, "id", "")
            if name in _ATOMIC_CALLS:
                return True
    return False


@register
class AtomicWriteChecker(Checker):
    rule = "atomic-write"
    description = ("cross-process handoff files (payload/request/ready/"
                   "done/port/plan/snapshot paths) must be published via "
                   "atomic_write_file, never a bare open('w')")
    hint = ("from dlrover_tpu.common.storage import atomic_write_file\n"
            "    atomic_write_file(content, path)  # tmp + fsync + "
            "rename; also the chaos storage_write injection point")

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            if module.relpath.endswith(EXEMPT_SUFFIXES):
                continue
            findings.extend(self._check_module(module))
        return findings

    def _check_module(self, module: Module) -> list[Finding]:
        findings: list[Finding] = []
        # map each write site to its innermost enclosing function so the
        # tmp+rename idiom suppression is scoped correctly
        scopes: list[ast.AST] = [module.tree]

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
            if is_scope:
                scopes.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                scopes.pop()
                return
            if not isinstance(node, ast.Call):
                return
            site = self._handoff_write(node)
            if site is None:
                return
            kind, tokens = site
            if _function_is_atomic(scopes[-1]):
                return
            token = next(
                (t for t in HANDOFF_TOKENS if t in tokens), "?"
            )
            findings.append(self.finding(
                module, node,
                f"{kind} to a cross-process handoff path "
                f"(token {token!r}) bypasses atomic_write_file — a "
                "crash mid-write publishes a torn file to its reader",
            ))

        visit(module.tree)
        return findings

    def _handoff_write(self, call: ast.Call
                       ) -> tuple[str, set[str]] | None:
        """(description, path word chunks) when this call is a
        non-atomic handoff write candidate."""
        callee = call.func
        if isinstance(callee, ast.Name) and callee.id == "open" \
                and call.args:
            if not _write_mode(call):
                return None
            tokens = _expr_tokens(call.args[0])
            if tokens & set(HANDOFF_TOKENS):
                return "open(mode='w')", tokens
            return None
        if isinstance(callee, ast.Attribute) \
                and callee.attr in ("write_text", "write_bytes"):
            tokens = _expr_tokens(callee.value)
            if tokens & set(HANDOFF_TOKENS):
                return f".{callee.attr}()", tokens
        return None
