import sys

from native.analyze.cli import main

if __name__ == "__main__":
    sys.exit(main())
