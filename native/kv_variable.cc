// Host-side hash-table embedding runtime (KvVariable analog).
//
// Reference analog: tfplus/tfplus/kv_variable/kernels/kv_variable.h:89
// (concurrent hash-table embedding variable for unbounded sparse ids:
// per-key rows + optimizer slots, frequency tracking, under-threshold
// filtering on export, import/export for checkpoints) and the sparse
// optimizer kernels in kernels/training_ops.cc (Adam/GroupAdam family).
//
// TPU-native role: XLA programs need static shapes, so the unbounded table
// lives host-side in C++; the trainer gathers the batch's rows into a dense
// [n, dim] buffer that goes to the device, and sparse optimizer updates
// apply host-side to exactly the touched rows. Sharded locking gives
// concurrent lookups from data-loading threads.
//
// Exposed as a C API consumed via ctypes (no pybind11 in the image).

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <cmath>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumShards = 16;  // power of two

// chunk sentinel: the row lives in the spill file; offset is its disk slot
constexpr uint32_t kDiskChunk = 0xFFFFFFFFu;

struct Row {
  uint32_t chunk;
  uint32_t offset;  // row index within the chunk (or disk slot)
  uint32_t freq;
  // weight values changed since the last clearing delta export (set on
  // insert / optimizer update / import, NOT on lookup frequency bumps —
  // marking reads would make every delta a full export)
  uint8_t dirty;

  bool on_disk() const { return chunk == kDiskChunk; }
};

struct Shard {
  std::mutex mu;
  std::unordered_map<int64_t, Row> index;
  // chunked arena: each chunk holds kChunkRows rows of width row_width
  std::vector<std::unique_ptr<float[]>> chunks;
  uint32_t next_offset = 0;  // next free row in the last chunk
  // arena slots released by eviction/removal, reused by insert (bounds
  // host memory under spill — the whole point of the hybrid tier)
  std::vector<std::pair<uint32_t, uint32_t>> free_slots;
  // keys removed since the last clearing removed-log drain (delta
  // restore must replay deletions before upserts)
  std::vector<int64_t> removed_log;
};

// Per-shard bound on the removed log: a table that removes keys but never
// drains deltas (plain full-export checkpointing) must not leak memory.
// On overflow the shard's log is dropped and the table-wide overflow flag
// set — the delta chain is broken and the next checkpoint must be a base.
constexpr size_t kRemovedLogShardCap = 1 << 16;

struct KvTable {
  int dim = 0;        // embedding width
  int num_slots = 0;  // optimizer slot vectors per row (Adam: 2)
  int row_width = 0;  // dim * (1 + num_slots)
  uint64_t seed = 0;
  float init_scale = 0.05f;
  Shard shards[kNumShards];
  std::atomic<int64_t> size{0};
  // removed-log overflow is a monotonic generation + an acked watermark:
  // "overflowed" means gen > ack. The saver acks the generation it
  // observed BEFORE draining, so an overflow racing the save stays
  // pending and forces the next save to be a base too.
  std::atomic<int64_t> overflow_gen{0};
  std::atomic<int64_t> overflow_ack{0};
  // spill-tier read failures (checkpoint correctness depends on them
  // being surfaced, not papered over)
  std::atomic<int64_t> io_errors{0};

  // hybrid (multi-tier) storage: cold rows spill to a fixed-width-record
  // file and fault back in on access (reference: the hybrid_embedding
  // MemStorageTable + secondary storage tables, table_manager.h)
  int spill_fd = -1;
  std::mutex disk_mu;              // guards the two members below
  std::vector<uint32_t> disk_free; // reusable disk slots
  uint32_t disk_next = 0;          // next fresh disk slot
  std::atomic<int64_t> disk_rows{0};

  static constexpr uint32_t kChunkRows = 4096;

  Shard& shard_for(int64_t key) {
    // splitmix64 finalizer: avoids shard hotspots for sequential ids
    uint64_t x = static_cast<uint64_t>(key) + 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    x ^= x >> 31;
    return shards[x & (kNumShards - 1)];
  }

  // caller holds the shard lock; r must be in memory
  float* row_ptr(Shard& s, const Row& r) {
    return s.chunks[r.chunk].get() + static_cast<size_t>(r.offset) * row_width;
  }

  // caller holds the shard lock: grab a free arena slot or grow
  std::pair<uint32_t, uint32_t> alloc_slot(Shard& s) {
    if (!s.free_slots.empty()) {
      auto slot = s.free_slots.back();
      s.free_slots.pop_back();
      return slot;
    }
    if (s.chunks.empty() || s.next_offset == kChunkRows) {
      s.chunks.emplace_back(new float[static_cast<size_t>(kChunkRows) * row_width]);
      s.next_offset = 0;
    }
    return {static_cast<uint32_t>(s.chunks.size() - 1), s.next_offset++};
  }

  // caller holds the shard lock; initializes embedding part, zeroes slots
  Row& insert(Shard& s, int64_t key) {
    auto [chunk, off] = alloc_slot(s);
    Row r{chunk, off, 0, 1};
    float* p = row_ptr(s, r);
    // deterministic per-key init: uniform(-scale, scale) from key+seed
    std::mt19937_64 gen(seed ^ static_cast<uint64_t>(key));
    std::uniform_real_distribution<float> dist(-init_scale, init_scale);
    for (int i = 0; i < dim; ++i) p[i] = dist(gen);
    std::memset(p + dim, 0, sizeof(float) * dim * num_slots);
    auto it = s.index.emplace(key, r).first;
    size.fetch_add(1, std::memory_order_relaxed);
    return it->second;
  }

  size_t row_bytes() const { return sizeof(float) * row_width; }

  // caller holds the shard lock; reads a spilled row without faulting it in
  bool read_spilled(const Row& r, float* out) {
    ssize_t want = static_cast<ssize_t>(row_bytes());
    if (pread(spill_fd, out, want,
              static_cast<off_t>(r.offset) * want) != want) {
      io_errors.fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    return true;
  }

  // caller holds the shard lock: bring a spilled row back to the arena
  bool fault_in(Shard& s, Row& r) {
    if (!r.on_disk()) return true;
    auto [chunk, off] = alloc_slot(s);
    Row mem{chunk, off, r.freq, r.dirty};
    if (!read_spilled(r, row_ptr(s, mem))) {
      s.free_slots.emplace_back(chunk, off);
      return false;
    }
    {
      std::lock_guard<std::mutex> dlock(disk_mu);
      disk_free.push_back(r.offset);
    }
    r = mem;
    disk_rows.fetch_sub(1, std::memory_order_relaxed);
    return true;
  }
};

// Shared per-row scaffold for every sparse optimizer: find-or-insert,
// fault in spilled rows, keep freq/dirty semantics identical across the
// family (reference: the per-optimizer kernels in
// tfplus/kv_variable/kernels/training_ops.cc repeat this dance ~7x).
// ``update`` runs under the shard lock with (w_row, grad_row).
template <typename F>
void apply_sparse_update(KvTable* t, const int64_t* keys, const float* grads,
                         int64_t n, F&& update) {
  const int dim = t->dim;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = t->shard_for(keys[i]);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(keys[i]);
    Row* r = it != s.index.end() ? &it->second : &t->insert(s, keys[i]);
    if (r->on_disk() && !t->fault_in(s, *r)) continue;  // I/O error: skip
    // a row that receives updates is live: export's frequency filtering
    // must never drop trained weights just because no lookup preceded
    if (r->freq == 0) r->freq = 1;
    r->dirty = 1;
    update(t->row_ptr(s, *r), grads + i * dim);
  }
}

// Proximal group-lasso row shrinkage (the "Group" in GroupAdam /
// GroupAdagrad, reference kv_variable/python/training/group_adam.py:272):
// shrink the row's L2 norm by ``thresh``, zeroing rows that fall below —
// feature pruning for stale/noisy ids.
inline void group_lasso_prox(float* w, int dim, float thresh) {
  float norm = 0.0f;
  for (int d = 0; d < dim; ++d) norm += w[d] * w[d];
  norm = std::sqrt(norm);
  if (norm <= thresh) {
    std::memset(w, 0, sizeof(float) * dim);
  } else {
    float scale = 1.0f - thresh / norm;
    for (int d = 0; d < dim; ++d) w[d] *= scale;
  }
}

}  // namespace

extern "C" {

void* kv_create(int dim, int num_slots, uint64_t seed, float init_scale) {
  auto* t = new KvTable();
  t->dim = dim;
  t->num_slots = num_slots;
  t->row_width = dim * (1 + num_slots);
  t->seed = seed;
  t->init_scale = init_scale;
  return t;
}

void kv_free(void* handle) {
  auto* t = static_cast<KvTable*>(handle);
  if (t->spill_fd >= 0) close(t->spill_fd);
  delete t;
}

// Enable the disk spill tier backed by ``path`` (created/truncated).
// Returns 0 on success, -1 when the file cannot be opened, -2 when a
// spill tier is already active (re-pointing it would orphan every
// spilled row's disk slot — rows would silently read as garbage).
int kv_enable_spill(void* handle, const char* path) {
  auto* t = static_cast<KvTable*>(handle);
  if (t->spill_fd >= 0) return -2;
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  t->spill_fd = fd;
  return 0;
}

// Cumulative spill-tier read failures. Checkpoint/export callers compare
// before/after: a change means the snapshot silently omitted rows.
int64_t kv_io_errors(void* handle) {
  return static_cast<KvTable*>(handle)
      ->io_errors.load(std::memory_order_relaxed);
}

// Evict rows with freq <= max_freq to the spill file, at most max_rows
// (<=0: unlimited). Returns the number spilled. Eviction frees the rows'
// arena slots, bounding host memory; spilled rows fault back in on
// lookup/update and are still seen by export/delta export.
//
// Disk writes happen OUTSIDE the shard lock (a lock held across a long
// pwrite sweep would stall every lookup/update hashing to the shard):
// candidates are staged in batches under the lock, written unlocked,
// then re-verified under the lock (bytes unchanged, same arena slot)
// before flipping to disk — a row updated during the window is skipped.
int64_t kv_evict(void* handle, uint32_t max_freq, int64_t max_rows) {
  auto* t = static_cast<KvTable*>(handle);
  if (t->spill_fd < 0) return 0;
  constexpr size_t kBatch = 512;
  const size_t rb = t->row_bytes();
  const ssize_t want = static_cast<ssize_t>(rb);
  int64_t evicted = 0;
  std::vector<int64_t> keys;
  std::vector<Row> staged;
  std::vector<float> buf;
  std::vector<uint32_t> slots;
  std::vector<uint8_t> ok;
  for (auto& s : t->shards) {
    bool more = true;
    while (more) {
      keys.clear();
      staged.clear();
      buf.clear();
      {
        std::lock_guard<std::mutex> lock(s.mu);
        for (auto& [key, row] : s.index) {
          if (max_rows > 0 &&
              evicted + static_cast<int64_t>(keys.size()) >= max_rows) {
            break;
          }
          if (row.on_disk() || row.freq > max_freq) continue;
          keys.push_back(key);
          staged.push_back(row);
          const float* p = t->row_ptr(s, row);
          buf.insert(buf.end(), p, p + t->row_width);
          if (keys.size() == kBatch) break;
        }
        more = keys.size() == kBatch;
      }
      if (keys.empty()) break;
      // allocate disk slots + write, unlocked
      slots.assign(keys.size(), 0);
      ok.assign(keys.size(), 0);
      for (size_t i = 0; i < keys.size(); ++i) {
        uint32_t slot;
        {
          std::lock_guard<std::mutex> dlock(t->disk_mu);
          if (!t->disk_free.empty()) {
            slot = t->disk_free.back();
            t->disk_free.pop_back();
          } else {
            slot = t->disk_next++;
          }
        }
        slots[i] = slot;
        ok[i] = pwrite(t->spill_fd, buf.data() + i * t->row_width, want,
                       static_cast<off_t>(slot) * want) == want;
        if (!ok[i]) {
          std::lock_guard<std::mutex> dlock(t->disk_mu);
          t->disk_free.push_back(slot);
        }
      }
      // re-verify + flip under the lock
      int64_t batch_evicted = 0;
      {
        std::lock_guard<std::mutex> lock(s.mu);
        for (size_t i = 0; i < keys.size(); ++i) {
          if (!ok[i]) continue;
          auto it = s.index.find(keys[i]);
          bool valid = it != s.index.end() && !it->second.on_disk() &&
                       it->second.chunk == staged[i].chunk &&
                       it->second.offset == staged[i].offset &&
                       std::memcmp(t->row_ptr(s, it->second),
                                   buf.data() + i * t->row_width, rb) == 0;
          if (!valid) {
            std::lock_guard<std::mutex> dlock(t->disk_mu);
            t->disk_free.push_back(slots[i]);
            continue;
          }
          s.free_slots.emplace_back(it->second.chunk, it->second.offset);
          it->second.chunk = kDiskChunk;
          it->second.offset = slots[i];
          t->disk_rows.fetch_add(1, std::memory_order_relaxed);
          ++evicted;
          ++batch_evicted;
        }
      }
      // a full batch that evicted nothing (disk full, or every staged
      // row was concurrently updated) would re-stage the same rows
      // forever — stop; a later evict() call retries
      if (batch_evicted == 0) break;
      if (max_rows > 0 && evicted >= max_rows) break;
    }
  }
  return evicted;
}

int64_t kv_disk_rows(void* handle) {
  return static_cast<KvTable*>(handle)
      ->disk_rows.load(std::memory_order_relaxed);
}

int64_t kv_size(void* handle) {
  return static_cast<KvTable*>(handle)->size.load(std::memory_order_relaxed);
}

// Gather rows for keys[n] into out[n*dim]. Missing keys are inserted
// (init_missing=1) or zero-filled (0). Bumps frequency on hit/insert.
void kv_lookup(void* handle, const int64_t* keys, int64_t n, float* out,
               int init_missing) {
  auto* t = static_cast<KvTable*>(handle);
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = t->shard_for(keys[i]);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(keys[i]);
    if (it == s.index.end()) {
      if (!init_missing) {
        std::memset(out + i * t->dim, 0, sizeof(float) * t->dim);
        continue;
      }
      Row& r = t->insert(s, keys[i]);
      r.freq = 1;
      std::memcpy(out + i * t->dim, t->row_ptr(s, r), sizeof(float) * t->dim);
      continue;
    }
    it->second.freq++;
    if (it->second.on_disk() && !t->fault_in(s, it->second)) {
      std::memset(out + i * t->dim, 0, sizeof(float) * t->dim);
      continue;
    }
    std::memcpy(out + i * t->dim, t->row_ptr(s, it->second),
                sizeof(float) * t->dim);
  }
}

// Sparse Adam with optional group-lasso shrinkage (GroupAdam,
// reference: kv_variable/python/training/group_adam.py:272).
// Duplicate keys in one batch are applied sequentially (gradient order).
// Requires num_slots >= 2 (m, v). step is the 1-based global step for
// bias correction.
void kv_apply_adam(void* handle, const int64_t* keys, const float* grads,
                   int64_t n, float lr, float beta1, float beta2, float eps,
                   int64_t step, float l2, float group_lasso) {
  auto* t = static_cast<KvTable*>(handle);
  const int dim = t->dim;
  const float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  const float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  apply_sparse_update(t, keys, grads, n, [&](float* w, const float* g) {
    float* m = w + dim;
    float* v = w + 2 * dim;
    for (int d = 0; d < dim; ++d) {
      float gd = g[d] + l2 * w[d];
      m[d] = beta1 * m[d] + (1.0f - beta1) * gd;
      v[d] = beta2 * v[d] + (1.0f - beta2) * gd * gd;
      float mhat = m[d] / bc1;
      float vhat = v[d] / bc2;
      w[d] -= lr * mhat / (std::sqrt(vhat) + eps);
    }
    if (group_lasso > 0.0f) group_lasso_prox(w, dim, lr * group_lasso);
  });
}

// Sparse (Group)Adagrad: per-coordinate accumulator in slot 0, optional
// L2 and proximal group-lasso row shrinkage. Reference:
// tfplus/kv_variable/kernels/training_ops.cc KvResourceSparseApplyAdagrad
// + python/training/group_adagrad.py. Requires num_slots >= 1; returns
// -1 otherwise, 0 on success.
int kv_apply_adagrad(void* handle, const int64_t* keys, const float* grads,
                     int64_t n, float lr, float eps, float l2,
                     float group_lasso) {
  auto* t = static_cast<KvTable*>(handle);
  if (t->num_slots < 1) return -1;
  const int dim = t->dim;
  apply_sparse_update(t, keys, grads, n, [&](float* w, const float* g) {
    float* a = w + dim;
    for (int d = 0; d < dim; ++d) {
      float gd = g[d] + l2 * w[d];
      a[d] += gd * gd;
      w[d] -= lr * gd / (std::sqrt(a[d]) + eps);
    }
    if (group_lasso > 0.0f) group_lasso_prox(w, dim, lr * group_lasso);
  });
  return 0;
}

// Sparse (Group)FTRL-proximal: slots are z (slot 0) and the squared-grad
// accumulator nn (slot 1). Per-coordinate closed form with L1/L2, then
// the row-level group-lasso prox — the sparse-group penalty of the
// reference's SparseGroupFtrl (tfplus training_ops.cc
// KvResourceSparseApplyFtrl family). Requires num_slots >= 2.
int kv_apply_ftrl(void* handle, const int64_t* keys, const float* grads,
                  int64_t n, float lr, float l1, float l2, float beta,
                  float group_lasso) {
  auto* t = static_cast<KvTable*>(handle);
  if (t->num_slots < 2) return -1;
  const int dim = t->dim;
  apply_sparse_update(t, keys, grads, n, [&](float* w, const float* g) {
    float* z = w + dim;
    float* nn = w + 2 * dim;
    for (int d = 0; d < dim; ++d) {
      float gd = g[d];
      float n_new = nn[d] + gd * gd;
      float sigma = (std::sqrt(n_new) - std::sqrt(nn[d])) / lr;
      z[d] += gd - sigma * w[d];
      nn[d] = n_new;
      if (std::fabs(z[d]) <= l1) {
        w[d] = 0.0f;
      } else {
        float sgn = z[d] > 0.0f ? 1.0f : -1.0f;
        w[d] = -(z[d] - sgn * l1) /
               ((beta + std::sqrt(n_new)) / lr + 2.0f * l2);
      }
    }
    if (group_lasso > 0.0f) group_lasso_prox(w, dim, lr * group_lasso);
  });
  return 0;
}

// Sparse Rectified Adam: Adam whose adaptive step is gated by the
// variance-rectification term (warmup-free adaptivity; reference:
// tfplus kv_variable/python/training/rectified_adam.py over its
// training_ops.cc kernel). Slots: m, v. Requires num_slots >= 2.
int kv_apply_radam(void* handle, const int64_t* keys, const float* grads,
                   int64_t n, float lr, float beta1, float beta2, float eps,
                   int64_t step, float l2) {
  auto* t = static_cast<KvTable*>(handle);
  if (t->num_slots < 2) return -1;
  const int dim = t->dim;
  const float st = static_cast<float>(step);
  const float b2t = std::pow(beta2, st);
  const float bc1 = 1.0f - std::pow(beta1, st);
  const float bc2 = 1.0f - b2t;
  const float rho_inf = 2.0f / (1.0f - beta2) - 1.0f;
  const float rho_t = rho_inf - 2.0f * st * b2t / bc2;
  float rect = 0.0f;
  const bool rectify = rho_t > 4.0f;
  if (rectify) {
    rect = std::sqrt(((rho_t - 4.0f) * (rho_t - 2.0f) * rho_inf) /
                     ((rho_inf - 4.0f) * (rho_inf - 2.0f) * rho_t));
  }
  apply_sparse_update(t, keys, grads, n, [&](float* w, const float* g) {
    float* m = w + dim;
    float* v = w + 2 * dim;
    for (int d = 0; d < dim; ++d) {
      float gd = g[d] + l2 * w[d];
      m[d] = beta1 * m[d] + (1.0f - beta1) * gd;
      v[d] = beta2 * v[d] + (1.0f - beta2) * gd * gd;
      float mhat = m[d] / bc1;
      if (rectify) {
        float vhat = std::sqrt(v[d] / bc2);
        w[d] -= lr * rect * mhat / (vhat + eps);
      } else {
        // variance not yet tractable: un-adapted SGD-with-momentum step
        w[d] -= lr * mhat;
      }
    }
  });
  return 0;
}

// Export keys with freq >= min_freq. Two-phase: call with keys_out=null to
// get the count, then with buffers sized [capacity] / [capacity*dim] /
// [capacity*dim*num_slots] (slots_out may be null) / [capacity]. The fill
// pass never writes more than ``capacity`` rows and returns the number
// actually written — the table may have grown between the two calls
// (concurrent lookups hold only shard locks).
int64_t kv_export(void* handle, uint32_t min_freq, int64_t* keys_out,
                  float* values_out, float* slots_out, uint32_t* freq_out,
                  int64_t capacity, int64_t* err_out) {
  auto* t = static_cast<KvTable*>(handle);
  const int dim = t->dim;
  const int slot_width = dim * t->num_slots;
  std::vector<float> scratch(t->row_width);
  int64_t count = 0, errs = 0;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> lock(s.mu);
    for (auto& [key, row] : s.index) {
      if (row.freq < min_freq) continue;
      if (keys_out != nullptr) {
        if (count >= capacity) {
          if (err_out != nullptr) *err_out = errs;
          return count;
        }
        const float* p;
        if (row.on_disk()) {  // snapshot spilled rows without faulting in
          if (!t->read_spilled(row, scratch.data())) {
            // this call's snapshot is missing a row — report it scoped
            // to the call (the global io_errors counter also counts
            // unrelated lookup-path failures)
            ++errs;
            continue;
          }
          p = scratch.data();
        } else {
          p = t->row_ptr(s, row);
        }
        keys_out[count] = key;
        std::memcpy(values_out + count * dim, p, sizeof(float) * dim);
        if (slots_out != nullptr && slot_width > 0) {
          std::memcpy(slots_out + count * slot_width, p + dim,
                      sizeof(float) * slot_width);
        }
        if (freq_out != nullptr) freq_out[count] = row.freq;
      }
      ++count;
    }
  }
  if (err_out != nullptr) *err_out = errs;
  return count;
}

// Import n rows (checkpoint restore). slots/freq may be null (zeroed).
void kv_import(void* handle, const int64_t* keys, const float* values,
               const float* slots, const uint32_t* freq, int64_t n) {
  auto* t = static_cast<KvTable*>(handle);
  const int dim = t->dim;
  const int slot_width = dim * t->num_slots;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = t->shard_for(keys[i]);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(keys[i]);
    Row* r = it != s.index.end() ? &it->second : &t->insert(s, keys[i]);
    if (r->on_disk()) {
      // import overwrites the whole row — no need to read the spilled
      // copy, just move the row back to a fresh arena slot
      {
        std::lock_guard<std::mutex> dlock(t->disk_mu);
        t->disk_free.push_back(r->offset);
      }
      t->disk_rows.fetch_sub(1, std::memory_order_relaxed);
      auto [chunk, off] = t->alloc_slot(s);
      r->chunk = chunk;
      r->offset = off;
    }
    float* p = t->row_ptr(s, *r);
    std::memcpy(p, values + i * dim, sizeof(float) * dim);
    if (slots != nullptr && slot_width > 0) {
      std::memcpy(p + dim, slots + i * slot_width, sizeof(float) * slot_width);
    } else {
      std::memset(p + dim, 0, sizeof(float) * slot_width);
    }
    r->freq = freq != nullptr ? freq[i] : 1;
    r->dirty = 1;
  }
}

// Delta export: dirty rows AND the removed-keys log in ONE pass, each
// shard drained atomically under its lock — a key's value export and its
// removal can never interleave within one drain, which is what makes the
// delta replayable (removals before upserts) without resurrecting keys.
//
// Count mode (keys_out == null): counts_out[0] = dirty rows,
// counts_out[1] = logged removals; nothing cleared; returns 1.
// Fill mode: emits per shard only when BOTH remaining capacities fit the
// whole shard (a partially-drained shard would split one key's events
// across drains); stops early otherwise. ``clear`` resets marks/logs of
// the emitted shards. counts_out gets the written counts; returns 1 when
// every shard was processed, 0 on an early stop (call again to drain the
// rest — leftover changes simply surface in the next drain). counts_out
// is [rows_written, removals_written, spill_read_errors]; error rows
// keep their dirty marks.
int64_t kv_delta_export(void* handle, int64_t* keys_out, float* values_out,
                        float* slots_out, uint32_t* freq_out,
                        int64_t capacity, int64_t* removed_out,
                        int64_t removed_capacity, int64_t* counts_out,
                        int clear) {
  auto* t = static_cast<KvTable*>(handle);
  const int dim = t->dim;
  const int slot_width = dim * t->num_slots;
  std::vector<float> scratch(t->row_width);
  int64_t rows = 0, removed = 0, errs = 0;
  int64_t complete = 1;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> lock(s.mu);
    if (keys_out == nullptr) {
      for (auto& [key, row] : s.index) rows += row.dirty ? 1 : 0;
      removed += static_cast<int64_t>(s.removed_log.size());
      continue;
    }
    int64_t shard_rows = 0;
    for (auto& [key, row] : s.index) shard_rows += row.dirty ? 1 : 0;
    int64_t shard_removed = static_cast<int64_t>(s.removed_log.size());
    if (rows + shard_rows > capacity ||
        removed + shard_removed > removed_capacity) {
      complete = 0;
      break;
    }
    for (auto& [key, row] : s.index) {
      if (!row.dirty) continue;
      const float* p;
      if (row.on_disk()) {
        if (!t->read_spilled(row, scratch.data())) {
          // the row stays dirty (clear is skipped) so the change
          // surfaces in the next drain; report it so callers that need
          // a COMPLETE snapshot now (peek consumers, the checkpoint
          // manager's durability accounting) can react
          ++errs;
          continue;
        }
        p = scratch.data();
      } else {
        p = t->row_ptr(s, row);
      }
      keys_out[rows] = key;
      std::memcpy(values_out + rows * dim, p, sizeof(float) * dim);
      if (slots_out != nullptr && slot_width > 0) {
        std::memcpy(slots_out + rows * slot_width, p + dim,
                    sizeof(float) * slot_width);
      }
      if (freq_out != nullptr) freq_out[rows] = row.freq;
      if (clear) row.dirty = 0;
      ++rows;
    }
    for (int64_t key : s.removed_log) removed_out[removed++] = key;
    if (clear) s.removed_log.clear();
  }
  counts_out[0] = rows;
  counts_out[1] = removed;
  counts_out[2] = errs;
  return complete;
}

// Nonzero when an unacked removed-log overflow exists (deletions were
// dropped): the delta chain is broken and the next checkpoint must be a
// full export.
int kv_delta_overflowed(void* handle) {
  auto* t = static_cast<KvTable*>(handle);
  return t->overflow_gen.load() > t->overflow_ack.load() ? 1 : 0;
}

// Current overflow generation. The saver reads it BEFORE draining, and
// acks that value once the covering full export is durable — an overflow
// racing the save keeps gen > ack and forces another base.
int64_t kv_overflow_gen(void* handle) {
  return static_cast<KvTable*>(handle)->overflow_gen.load();
}

void kv_ack_overflow(void* handle, int64_t gen) {
  auto* t = static_cast<KvTable*>(handle);
  int64_t cur = t->overflow_ack.load();
  while (gen > cur && !t->overflow_ack.compare_exchange_weak(cur, gen)) {
  }
}

// Reset delta tracking (after a full/base export: the base already
// captures every row, so pending dirty marks and removal logs are moot).
void kv_clear_deltas(void* handle) {
  auto* t = static_cast<KvTable*>(handle);
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> lock(s.mu);
    for (auto& [key, row] : s.index) row.dirty = 0;
    s.removed_log.clear();
  }
  t->overflow_ack.store(t->overflow_gen.load());
}

// Re-mark keys dirty (checkpoint-write failure recovery: the rows were
// exported with their marks cleared but never durably saved). Keys no
// longer present are skipped — their removal sits in the removed log.
void kv_mark_dirty(void* handle, const int64_t* keys, int64_t n) {
  auto* t = static_cast<KvTable*>(handle);
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = t->shard_for(keys[i]);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(keys[i]);
    if (it != s.index.end()) it->second.dirty = 1;
  }
}

// Remove keys[n]; rows are tombstoned (arena space not reclaimed — the
// reference behaves the same until a full export/import compaction).
int64_t kv_remove(void* handle, const int64_t* keys, int64_t n) {
  auto* t = static_cast<KvTable*>(handle);
  int64_t removed = 0;
  for (int64_t i = 0; i < n; ++i) {
    Shard& s = t->shard_for(keys[i]);
    std::lock_guard<std::mutex> lock(s.mu);
    auto it = s.index.find(keys[i]);
    if (it != s.index.end()) {
      // reclaim the row's storage (arena slot or disk slot)
      if (it->second.on_disk()) {
        std::lock_guard<std::mutex> dlock(t->disk_mu);
        t->disk_free.push_back(it->second.offset);
        t->disk_rows.fetch_sub(1, std::memory_order_relaxed);
      } else {
        s.free_slots.emplace_back(it->second.chunk, it->second.offset);
      }
      s.index.erase(it);
      ++removed;
      if (s.removed_log.size() >= kRemovedLogShardCap) {
        s.removed_log.clear();
        t->overflow_gen.fetch_add(1, std::memory_order_relaxed);
      }
      s.removed_log.push_back(keys[i]);
    }
  }
  t->size.fetch_sub(removed, std::memory_order_relaxed);
  return removed;
}

}  // extern "C"
