"""Native tooling: C++ kernels (kv_variable) and repo lint/analysis."""
