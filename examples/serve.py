"""Serve a trained checkpoint with the continuous-batching engine.

The inference-side twin of examples/train_transformer.py: restore the
flash checkpoint it wrote (shm fast path or storage), then serve token
prompts through serving/engine.py. Prompts are one-per-line token id
lists ("12 7 99") on stdin or --prompt args; each line returns the
sampled continuation.

    python examples/train_transformer.py ... --ckpt-dir /tmp/ckpt
    python examples/serve.py --model tiny --ckpt-dir /tmp/ckpt \
        --prompt "5 9 2" --prompt "7 7 7" --max-new 32
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable from a checkout without installing the package
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser("serve")
    p.add_argument("--model", default="tiny")
    p.add_argument("--ckpt-dir", default="",
                   help="flash-checkpoint dir to restore params from; "
                        "empty = random init (smoke testing)")
    p.add_argument("--prompt", action="append", default=[],
                   help="space-separated token ids; repeatable. "
                        "Reads stdin lines when omitted")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--eos-id", type=int, default=-1)
    p.add_argument("--seed", type=int, default=-1,
                   help="per-request sampling seed (same seed -> same "
                        "continuation regardless of batching); -1 = "
                        "engine-generated")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-len", type=int, default=0)
    p.add_argument("--prefill-len", type=int, default=0)
    p.add_argument("--decode-block", type=int, default=16)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax

    from dlrover_tpu.models import transformer as tfm
    from dlrover_tpu.serving import InferenceEngine, SamplingParams
    from dlrover_tpu.trainer import bootstrap

    bootstrap.setup_compilation_cache()
    cfg = tfm.CONFIGS[args.model]
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))

    if args.ckpt_dir:
        from dlrover_tpu.checkpoint.engine import CheckpointEngine

        engine = CheckpointEngine(args.ckpt_dir)
        # the training state holds params under .params; serve only them
        from dlrover_tpu.trainer.train_step import TrainState

        import jax.numpy as jnp
        import optax

        template = TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=optax.adamw(1e-3).init(params),
        )
        loaded = engine.load(template)
        engine.close()
        if loaded is None:
            print("no checkpoint found; serving random init",
                  file=sys.stderr)
        else:
            step, state = loaded
            params = state.params
            print(f"restored step {step} from {args.ckpt_dir}",
                  file=sys.stderr)

    eng = InferenceEngine(
        params, cfg, slots=args.slots, max_len=args.max_len or 0,
        prefill_len=args.prefill_len or 0,
        decode_block=args.decode_block,
    )
    sp = SamplingParams(
        temperature=args.temperature, top_k=args.top_k,
        top_p=args.top_p, max_new_tokens=args.max_new,
        eos_id=None if args.eos_id < 0 else args.eos_id,
        seed=None if args.seed < 0 else args.seed,
    )

    lines = args.prompt or [ln.strip() for ln in sys.stdin
                            if ln.strip()]
    for line in lines:
        eng.submit([int(t) for t in line.split()], sp)
    t0 = time.monotonic()
    results = eng.run()
    wall = time.monotonic() - t0
    total = sum(len(r.tokens) for r in results)
    for r in sorted(results, key=lambda r: r.id):
        print(json.dumps({
            "prompt": r.prompt, "tokens": r.tokens,
            "finish_reason": r.finish_reason,
        }))
    print(
        f"{len(results)} requests, {total} tokens in {wall:.2f}s "
        f"({total / max(wall, 1e-9):.0f} tok/s)", file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
