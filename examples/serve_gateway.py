"""Serve a checkpoint through the elastic gateway (HTTP front door).

The pool-of-replicas twin of examples/serve.py: N continuous-batching
engine replicas behind admission control, least-loaded + prefix-affinity
routing, preemption draining, and a telemetry-driven autoscaler that
resizes the pool through the ScalePlan path.

    python examples/serve_gateway.py --model tiny --replicas 2 \
        --max-replicas 4 --port 8000
    curl -s localhost:8000/v1/generate \
        -d '{"prompt": [5, 9, 2], "max_new_tokens": 16}'
    curl -s localhost:8000/healthz
    curl -s localhost:8000/metrics | grep dlrover_tpu_gateway

Kill tolerance demo: start with --preemption-file '/tmp/pre-{node_id}',
then `touch /tmp/pre-0` — replica 0 finishes its in-flight requests,
detaches, and the autoscaler brings a replacement.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# runnable from a checkout without installing the package
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser("serve_gateway")
    p.add_argument("--model", default="tiny")
    p.add_argument("--ckpt-dir", default="",
                   help="flash-checkpoint dir to restore params from; "
                        "empty = random init (smoke testing)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--replicas", type=int, default=2,
                   help="initial replica count (autoscaler floor "
                        "unless --min-replicas says otherwise)")
    p.add_argument("--min-replicas", type=int, default=0,
                   help="0 = use --replicas")
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-len", type=int, default=0)
    p.add_argument("--prefill-len", type=int, default=64)
    p.add_argument("--decode-block", type=int, default=8)
    p.add_argument("--prefix-cache-entries", type=int, default=8)
    p.add_argument("--prefill-replicas", type=int, default=0,
                   help="> 0 disaggregates: a prefill pool of this "
                        "size ships paged KV bundles to the decode "
                        "pool (DESIGN.md §23)")
    p.add_argument("--max-prefill-replicas", type=int, default=0,
                   help="0 = use --prefill-replicas")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="physical KV pages per engine (paged "
                        "admission + park/resume; 0 = dense slots)")
    p.add_argument("--page-size", type=int, default=0,
                   help="tokens per KV page (default: prefill-len)")
    p.add_argument("--admission-deadline", type=float, default=30.0,
                   help="seconds of estimated queue wait past which "
                        "the gateway answers 429 + Retry-After")
    p.add_argument("--target-p95", type=float, default=0.0,
                   help="autoscaler latency objective in seconds "
                        "(0 = scale on queue/occupancy only)")
    p.add_argument("--autoscale-interval", type=float, default=2.0)
    p.add_argument("--preemption-file", default="",
                   help="notice-file template with {node_id} = replica "
                        "id (defaults to DLROVER_TPU_PREEMPTION_FILE)")
    return p.parse_args(argv)


def _load_params(args, cfg):
    import jax

    from dlrover_tpu.models import transformer as tfm

    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    if not args.ckpt_dir:
        return params
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.trainer.train_step import TrainState

    engine = CheckpointEngine(args.ckpt_dir)
    template = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=optax.adamw(1e-3).init(params),
    )
    loaded = engine.load(template)
    engine.close()
    if loaded is None:
        print("no checkpoint found; serving random init",
              file=sys.stderr)
        return params
    step, state = loaded
    print(f"restored step {step} from {args.ckpt_dir}", file=sys.stderr)
    return state.params


def main(argv=None) -> int:
    args = parse_args(argv)

    from dlrover_tpu.gateway import (
        DisaggAutoscaler,
        Gateway,
        GatewayAutoscaler,
        GatewayHTTPServer,
        PoolScaler,
    )
    from dlrover_tpu.models import transformer as tfm
    from dlrover_tpu.serving import InferenceEngine
    from dlrover_tpu.telemetry import exposition
    from dlrover_tpu.trainer import bootstrap

    bootstrap.setup_compilation_cache()
    cfg = tfm.CONFIGS[args.model]
    params = _load_params(args, cfg)

    def engine_factory():
        return InferenceEngine(
            params, cfg, slots=args.slots,
            max_len=args.max_len or 0,
            prefill_len=args.prefill_len,
            decode_block=args.decode_block,
            prefix_cache_entries=args.prefix_cache_entries,
            kv_pages=args.kv_pages,
            page_size=args.page_size,
        )

    gateway = Gateway(
        engine_factory, replicas=args.replicas,
        prefill_len=args.prefill_len,
        prefill_replicas=args.prefill_replicas,
        admission_deadline_s=args.admission_deadline,
        preemption_file=args.preemption_file or None,
    )
    if args.prefill_replicas:
        autoscaler = DisaggAutoscaler(
            gateway,
            PoolScaler(gateway.prefill_pool, group="prefill"),
            PoolScaler(gateway.pool, group="decode"),
            min_prefill=args.prefill_replicas,
            max_prefill=max(args.max_prefill_replicas,
                            args.prefill_replicas),
            min_decode=args.min_replicas or args.replicas,
            max_decode=max(args.max_replicas,
                           args.min_replicas or args.replicas),
            interval_s=args.autoscale_interval,
            target_p95_s=args.target_p95,
        ).start()
    else:
        autoscaler = GatewayAutoscaler(
            gateway, PoolScaler(gateway.pool),
            min_replicas=args.min_replicas or args.replicas,
            max_replicas=max(args.max_replicas,
                             args.min_replicas or args.replicas),
            interval_s=args.autoscale_interval,
            target_p95_s=args.target_p95,
        ).start()
    server = GatewayHTTPServer(gateway, host=args.host,
                               port=args.port).start()
    exposition.start_from_env()  # optional extra bare /metrics port
    print(f"gateway on http://{args.host}:{server.port} "
          f"({args.replicas} x {args.model}, {args.slots} slots each); "
          "POST /v1/generate, GET /healthz, GET /metrics",
          file=sys.stderr)
    try:
        while True:
            time.sleep(5)
            stats = gateway.stats()
            print(f"[gateway] ready={stats['ready']} "
                  f"queue={stats['queue_depth']} "
                  f"occ={stats['slot_occupancy']:.2f}", file=sys.stderr)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        autoscaler.stop()
        gateway.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
