"""Elastic transformer training example.

The TPU-native analog of the reference's GPT-2 + Flash Checkpoint example
(BASELINE.md config 2; reference flow dlrover/trainer/torch/elastic_run.py
-> user script with Checkpointer). Run it under the agent:

    python -m dlrover_tpu.run --standalone examples/train_transformer.py \
        -- --model tiny --max-steps 50

Kill the training process mid-run: the agent persists the shm snapshot,
re-rendezvouses, respawns this script, and it resumes from the in-memory
checkpoint — the wow-path this example exists to demonstrate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# runnable from a checkout without installing the package
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser("train_transformer")
    p.add_argument("--model", default="tiny")
    p.add_argument("--attention", default="",
                   help="override the model's attention impl "
                        "(dense|flash|ring)")
    p.add_argument("--remat", default="",
                   help="per-layer remat policy (e.g. dots_no_batch, "
                        "save_attn); empty = model default")
    p.add_argument("--ce-chunks", type=int, default=0,
                   help="blockwise cross-entropy chunks (0 = model "
                        "default)")
    p.add_argument("--strategy", default="dp",
                   help="strategy preset name (parallel/strategy.py), "
                        "or 'auto' for the autopilot planner "
                        "(autopilot/planner.py: AOT-enumerated "
                        "strategy x mesh x schedule, cost-model/"
                        "history ranked, closed-loop retuned)")
    p.add_argument("--autopilot-history", default="",
                   help="measured-history sqlite for --strategy auto "
                        "(empty = <ckpt-dir>/autopilot_history.sqlite, "
                        "'0' disables history seeding/recording)")
    p.add_argument("--schedule", default="spmd",
                   choices=["spmd", "mpmd", "auto"],
                   help="pipeline runtime: spmd = the single-program "
                        "roll (parallel/pipeline.py), mpmd = per-stage "
                        "programs + host 1F1B (parallel/mpmd.py, "
                        "per-stage compile cache + recovery), auto = "
                        "cost-model gate (parallel/cost_model.py)")
    p.add_argument("--objective", default="clm", choices=["clm", "mlm"],
                   help="clm: causal next-token; mlm: BERT-class "
                        "bidirectional masked-LM (models/encoder.py)")
    p.add_argument("--max-steps", type=int, default=50)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--micro-batch", type=int, default=0,
                   help="0 -> global_batch / dp (no accumulation)")
    p.add_argument("--seq", type=int, default=0, help="0 -> model max")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--ckpt-dir", default="/tmp/dlrover_tpu_ckpt")
    p.add_argument("--ckpt-interval", type=int, default=10,
                   help="persist to storage every N steps")
    p.add_argument("--mem-ckpt-interval", type=int, default=1,
                   help="shm snapshot every N steps")
    p.add_argument("--dataset-size", type=int, default=100000)
    p.add_argument("--data-file", default="",
                   help="flat binary token file (trainer/token_dataset "
                        "pack_tokens format); empty = synthetic data")
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--shard-size", type=int, default=256)
    p.add_argument("--sharded-ckpt", action="store_true",
                   help="per-shard snapshots + reshard-on-load (FSDP-style)")
    p.add_argument("--result-file", default="")
    p.add_argument("--goodput-log", default="",
                   help="append per-step goodput events (JSONL) here; "
                        "aggregate with utils/goodput.compute_goodput")
    p.add_argument("--log-interval", type=int, default=10)
    p.add_argument("--crash-at-step", type=int, default=0,
                   help="fault injection: hard-exit at this step "
                        "(first incarnation only unless --crash-always)")
    p.add_argument("--crash-always", action="store_true",
                   help="crash at --crash-at-step in every incarnation")
    p.add_argument("--crash-exit", type=int, default=17,
                   help="exit code for the injected crash (210=OOM, "
                        "211=hardware per the failure contract)")
    p.add_argument("--step-delay", type=float, default=0.0,
                   help="sleep this long after each step (fault-injection "
                        "tests pace the run so kills land at a known "
                        "training position)")
    p.add_argument("--crash-once-file", default="",
                   help="crash only if this marker file is absent "
                        "(created before crashing) — survives node "
                        "relaunches, unlike the restart-count gate")
    p.add_argument("--hang-at-step", type=int, default=0,
                   help="fault injection: wedge forever at this step "
                        "(first incarnation only) — exercises the "
                        "agent's hang detector")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax
    import optax

    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.checkpoint.shm_handler import _leaf_paths
    from dlrover_tpu.models import transformer as tfm
    from dlrover_tpu.parallel.mesh import data_parallel_size
    from dlrover_tpu.parallel.strategy import PRESETS
    from dlrover_tpu.trainer import bootstrap
    from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer
    from dlrover_tpu.trainer.train_step import compile_train

    import dataclasses

    if not args.sharded_ckpt \
            and not os.environ.get("DLROVER_TPU_STANDBY_FILE"):
        # overlapped restore: kick off the storage read + integrity
        # verification NOW, so it runs concurrently with the
        # distributed/coordination bring-up inside init_from_env and the
        # XLA compile below; engine.load() joins it before the first
        # step. A STANDBY must not prefetch here — it is parked long
        # before the failure, so this read would see pre-failure state;
        # its prefetch starts from the agent's post-persist `.prepare`
        # signal instead (agent/standby.py), which is always fresh.
        from dlrover_tpu.checkpoint.engine import start_restore_prefetch

        start_restore_prefetch(args.ckpt_dir)

    ctx = bootstrap.init_from_env()
    cfg = tfm.CONFIGS[args.model]
    if args.attention:
        cfg = dataclasses.replace(cfg, attention=args.attention)
    if args.remat:
        cfg = dataclasses.replace(cfg, remat_scan=True,
                                  remat_policy=args.remat)
    if args.ce_chunks:
        cfg = dataclasses.replace(cfg, ce_chunks=args.ce_chunks)
    seq = args.seq or cfg.max_seq_len

    if args.objective == "mlm":
        from dlrover_tpu.models.encoder import (
            encoder_config,
            make_mlm_loss_fn,
        )

        cfg = encoder_config(cfg)

        def loss_for(s, m):
            return make_mlm_loss_fn(cfg, s, m)
    else:
        def loss_for(s, m):
            return tfm.make_loss_fn(cfg, s, m)

    autopilot_plan = None
    autopilot_ranked = None
    autopilot_history = None
    if args.strategy == "auto":
        # the autopilot planner (DESIGN.md §24): AOT-enumerate feasible
        # (strategy x mesh x schedule) points, rank by the cost model
        # seeded from measured history, and launch the winner as a
        # typed Plan. Cached next to the checkpoints so an elastic
        # restart reuses the ranked list instead of burning the
        # recovery window on N candidate compiles.
        from dlrover_tpu.autopilot import PlanHistory, load_or_plan

        bsz = max(1, args.global_batch)
        if args.objective == "mlm":
            example_batch = {
                "tokens": np.zeros((1, bsz, seq), np.int32),
                "targets": np.zeros((1, bsz, seq), np.int32),
                "mlm_mask": np.ones((1, bsz, seq), bool),
            }
        else:
            example_batch = {
                "tokens": np.zeros((1, bsz, seq + 1), np.int32)
            }
        if args.autopilot_history != "0":
            autopilot_history = PlanHistory(
                db_path=args.autopilot_history or os.path.join(
                    args.ckpt_dir, "autopilot_history.sqlite"
                )
            )
        n_dev = len(jax.devices())
        ranked = load_or_plan(
            os.path.join(args.ckpt_dir, "autopilot_plan.json"),
            model=args.model,
            loss_fn_for=loss_for,
            init_params_fn=lambda rng: tfm.init_params(cfg, rng),
            logical_params=tfm.logical_axes(cfg),
            optimizer=optax.adamw(args.lr),
            example_batch=example_batch,
            batch=bsz, seq=seq,
            history=autopilot_history,
            model_cfg=cfg,
            # the MPMD schedule axis: only for the clm stage programs
            # and only when the world splits into whole stages
            mpmd_stages=(2 if args.objective == "clm"
                         and n_dev % 2 == 0 and n_dev >= 4 else 0),
        )
        autopilot_ranked = ranked
        autopilot_plan = ranked.winner
        strategy = autopilot_plan.strategy()
        print(f"[trainer] autopilot plan: {autopilot_plan.name} "
              f"(source={autopilot_plan.source}, pred "
              f"{autopilot_plan.pred_step_s:.4f}s/step, "
              f"{len(ranked.plans)} feasible)", flush=True)
    else:
        strategy = PRESETS[args.strategy]()

    # ---- schedule resolution (DESIGN.md §21): the MPMD runtime builds
    # per-stage programs instead of one SPMD step; the "auto" gate asks
    # the schedule-aware cost model which schedule this geometry favors
    schedule = args.schedule
    sx = getattr(strategy, "extra", {}) or {}
    if sx.get("mpmd"):
        schedule = "mpmd"
    pp_stages = int(sx.get("pipeline_stages", 0) or 0) or 2
    if schedule == "auto":
        from dlrover_tpu.parallel.mpmd import choose_schedule

        schedule, ests = choose_schedule(
            cfg, num_stages=pp_stages,
            step_batch=max(1, args.global_batch), seq=seq,
            microbatches=int(sx.get("pipeline_microbatches", 0) or 0),
            interleave=int(sx.get("pipeline_interleave", 1) or 1),
        )
        print(f"[trainer] schedule gate picked {schedule} "
              f"(est step s: { {k: round(v, 6) for k, v in ests.items()} })",
              flush=True)
    mpmd_mode = schedule == "mpmd"
    if mpmd_mode and args.objective == "mlm":
        raise SystemExit("--schedule mpmd supports the clm objective "
                         "only (the stage programs are token->CE)")

    if mpmd_mode:
        # stage submeshes are built by the runtime; dp is stage 0's
        # data axis (the batch-sharding world)
        compiled = None
        mesh = None
        dp = max(1, len(jax.devices()) // pp_stages)
    else:
        mesh = strategy.build_mesh()
        compiled = compile_train(
            strategy=strategy,
            mesh=mesh,
            loss_fn=loss_for(strategy, mesh),
            init_params_fn=lambda rng: tfm.init_params(cfg, rng),
            logical_params=tfm.logical_axes(cfg),
            optimizer=optax.adamw(args.lr),
        )
        dp = data_parallel_size(mesh)
    # honor the master's paral-config suggestion (e.g. OOM -> higher grad
    # accumulation at a fixed global batch) unless the user pinned one
    from dlrover_tpu.agent.config_tuner import ParalConfigReader

    paral = ParalConfigReader()
    micro = args.micro_batch
    if not micro:
        suggested_accum = int(paral.get("grad_accum_steps", 0) or 0)
        if suggested_accum > 0:
            micro = max(1, args.global_batch // (dp * suggested_accum))
            print(f"[trainer] paral-config: accum={suggested_accum} -> "
                  f"micro_batch={micro}", flush=True)
        else:
            micro = max(1, args.global_batch // dp)

    # ---- elastic compile cache (DESIGN.md §17): the train-step
    # executable for this exact (topology, model, strategy, shapes) may
    # already exist — compiled by the pre-failure incarnation, by the
    # fallback-AOT daemon for this world size, or by another node — so
    # recovery loads it in ~0.1s instead of re-paying the XLA compile.
    # state/batch abstracts come from eval_shape: no compile, no arrays.
    from dlrover_tpu.parallel import compile_cache as cc

    cache_client = cc.CompileCacheClient()
    if mpmd_mode:
        # per-stage programs, each load_or_compile'd under its own
        # stage fingerprint (DESIGN.md §21) — recovery after a
        # single-stage failure recompiles only that stage
        from dlrover_tpu.parallel.mpmd import MpmdTrain

        accum = max(1, args.global_batch // (micro * dp))
        compiled = MpmdTrain(
            cfg, strategy, optax.adamw(args.lr),
            num_stages=pp_stages,
            microbatches=int(sx.get("pipeline_microbatches", 0) or 0),
            seq=seq, step_batch=micro * dp, accum=accum,
            cache=cache_client, num_nodes=ctx.num_nodes,
            extra_fingerprint={"lr": args.lr,
                               "objective": args.objective},
        )
        mesh = compiled.mesh
        state_abs = compiled.abstract_state()
        print(f"[trainer] mpmd runtime: {compiled.num_stages} stages x "
              f"{compiled.microbatches} microbatches, "
              f"{'warm' if compiled.cache_hit else 'cold'} stage "
              f"programs, bubble bound "
              f"{compiled.bubble_bound:.3f}", flush=True)
    else:
        state_abs = jax.eval_shape(compiled.init, jax.random.PRNGKey(0))

    def _batch_abstract(mesh_, compiled_, micro_, accum_):
        step_batch = micro_ * data_parallel_size(mesh_)
        if args.objective == "mlm":
            shapes = {"tokens": ((accum_, step_batch, seq), np.int32),
                      "targets": ((accum_, step_batch, seq), np.int32),
                      "mlm_mask": ((accum_, step_batch, seq), np.bool_)}
        else:
            shapes = {"tokens": ((accum_, step_batch, seq + 1), np.int32)}
        return {
            k: jax.ShapeDtypeStruct(shp, dt,
                                    sharding=compiled_.batch_sharding)
            for k, (shp, dt) in shapes.items()
        }

    if not mpmd_mode:
        accum = max(1, args.global_batch // (micro * dp))
        state_abs_sharded = jax.tree.map(
            lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                                  sharding=sh),
            state_abs, compiled.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
        batch_abs = _batch_abstract(mesh, compiled, micro, accum)
        key, key_inputs = cc.compile_fingerprint(
            num_nodes=ctx.num_nodes,
            total_devices=len(jax.devices()),
            mesh_axes=dict(mesh.shape),
            model=cfg,
            strategy=strategy,
            args_signature=cc.abstract_signature((state_abs_sharded,
                                                  batch_abs)),
            extra={"lr": args.lr, "objective": args.objective},
        )
        aot = cc.load_or_compile(
            key, key_inputs,
            compile_fn=lambda: compiled.step.lower(
                state_abs_sharded, batch_abs).compile(),
            cache=cache_client,
        )
        compiled.step = aot.fn
        compiled.cache_hit = aot.cache_hit
        # the compiled program's FLOPs ride the AOT envelope (a warm
        # load never re-lowers just to count) and feed the live MFU
        # gauge
        compiled.flops_per_step = aot.flops
        verb = ("loaded from compile cache" if aot.cache_hit
                else "compiled")
        print(f"[trainer] train step {verb} in {aot.seconds:.2f}s "
              f"({aot.source})", flush=True)

    # multi-node state is sharded across processes: only the sharded
    # engine can snapshot it (each node persists its addressable pieces)
    if args.sharded_ckpt or ctx.num_nodes > 1:
        from dlrover_tpu.checkpoint.sharded import ShardedCheckpointEngine

        state = compiled.init(jax.random.PRNGKey(0))
        engine = ShardedCheckpointEngine(
            args.ckpt_dir, node_id=ctx.node_id, node_rank=ctx.node_rank,
            world_size=ctx.num_nodes,
        )
        loaded = engine.load_sharded(state, compiled.state_shardings)
    else:
        engine = CheckpointEngine(args.ckpt_dir, node_id=ctx.node_id,
                                  node_rank=ctx.node_rank,
                                  world_size=ctx.num_nodes)
        shard_of = dict(_leaf_paths(compiled.state_shardings))
        # restore against the ABSTRACT template: every leaf arrives via
        # device_put from the snapshot, so a successful restore never
        # pays the init program's compile (the other recompile-class
        # cost on the recovery path)
        try:
            loaded = engine.load(
                state_abs,
                put=lambda name, arr: jax.device_put(arr, shard_of[name]),
                zero_copy=True,
            )
        except (KeyError, ValueError) as e:
            # snapshot from an older model/optimizer shape: fall back to
            # a fresh init rather than installing mismatched leaves
            print(f"[trainer] snapshot incompatible ({e}); starting "
                  "fresh", flush=True)
            loaded = None
        if loaded is None:
            state = compiled.init(jax.random.PRNGKey(0))
    resumed_from = 0
    if loaded is not None:
        resumed_from, state = loaded
        # restored leaves were built by device_put from host buffers;
        # the AOT step executable donates its inputs and skips pjit's
        # input re-staging, so they must be rebuilt into proper
        # per-device buffers first (see compile_cache.launder —
        # skipping this corrupts state on the CPU backend)
        state = cc.launder(state)
        print(f"[trainer] resumed from step {resumed_from}", flush=True)

    trainer = ElasticTrainer(
        compiled,
        global_batch_size=args.global_batch,
        micro_batch_size=micro,
        model_name=args.model,
    )

    # ---- autopilot closed loop (DESIGN.md §24): arm the master-side
    # controller with the launched plan + ranked alternatives (it rides
    # the trainer's metrics-snapshot pushes), and hot-apply any retune
    # it sends back through the paral-config channel — the job never
    # restarts for a strategy change.
    if autopilot_plan is not None and not mpmd_mode:
        from dlrover_tpu.common.constants import EnvKey

        if ctx.node_rank == 0 and os.environ.get(EnvKey.MASTER_ADDR):
            from dlrover_tpu.agent.master_client import MasterClient

            try:
                MasterClient.singleton().report_autopilot_plan(
                    autopilot_plan.to_json(),
                    [p.to_json()
                     for p in autopilot_ranked.alternatives()],
                    step_batch=trainer.step_batch_size,
                )
            except (ConnectionError, RuntimeError, OSError) as e:
                print(f"[trainer] autopilot plan report failed: {e}",
                      flush=True)

        from dlrover_tpu.autopilot import Plan
        from dlrover_tpu.autopilot import apply as autopilot_apply

        apply_batch = {
            k: np.zeros(v.shape, v.dtype) for k, v in batch_abs.items()
        }

        vetoed: set = set()

        def _retune_hook(step: int, st):
            nonlocal autopilot_plan
            pj = paral.get("autopilot_plan", "")
            if not pj:
                return None
            try:
                target = Plan.from_json(pj)
            except (ValueError, TypeError, KeyError):
                return None
            if target.fingerprint == autopilot_plan.fingerprint \
                    or target.fingerprint in vetoed:
                return None
            if not autopilot_apply.can_apply(
                    autopilot_plan, target,
                    step_batch=trainer.step_batch_size):
                vetoed.add(target.fingerprint)
                print(f"[trainer] autopilot retune to {target.name} "
                      "not applicable in-process; ignoring", flush=True)
                return None
            applied = autopilot_apply.apply_plan(
                target,
                state=st,
                loss_fn_for=loss_for,
                init_params_fn=lambda rng: tfm.init_params(cfg, rng),
                logical_params=tfm.logical_axes(cfg),
                optimizer=optax.adamw(args.lr),
                model_cfg=cfg,
                path="hot" if dict(target.mesh_axes)
                == dict(autopilot_plan.mesh_axes) else "reshard",
                cache=cache_client,
                num_nodes=ctx.num_nodes,
                example_batch=apply_batch,
                extra_fingerprint={"lr": args.lr,
                                   "objective": args.objective},
            )
            autopilot_plan = target
            print(f"[trainer] autopilot retune applied: {target.name} "
                  f"in {applied.seconds:.2f}s (no restart)", flush=True)
            return applied.compiled, applied.state

        trainer.retune_hook = _retune_hook

    # ---- fallback-topology AOT daemon: pre-compile the N−1/N+1 worlds
    # in the background and publish them to the compile cache, so a
    # membership change finds its executable already resident. Compile
    # is host-side (parallel/dry_run.py does the same offline), so this
    # never touches the accelerator's execution stream. Multi-node only
    # by default: a standalone world has no neighbor topologies.
    from dlrover_tpu.common.constants import EnvKey

    fallback_on = os.environ.get(EnvKey.FALLBACK_AOT, "")
    if (fallback_on != "0" and (ctx.num_nodes > 1 or fallback_on == "1")
            and cc.aot_cache_enabled() and not mpmd_mode):
        def _build_for_nodes(n_nodes: int):
            devices = jax.devices()
            per_node = max(1, len(devices) // ctx.num_nodes)
            subset = devices[:n_nodes * per_node]
            if n_nodes == ctx.num_nodes or not subset \
                    or len(subset) != n_nodes * per_node:
                return None
            try:
                fb_mesh = strategy.build_mesh(subset)
            except (ValueError, AssertionError):
                return None  # mesh axes don't divide this world
            fb = compile_train(
                strategy=strategy, mesh=fb_mesh,
                loss_fn=loss_for(strategy, fb_mesh),
                init_params_fn=lambda rng: tfm.init_params(cfg, rng),
                logical_params=tfm.logical_axes(cfg),
                optimizer=optax.adamw(args.lr),
            )
            fb_dp = data_parallel_size(fb_mesh)
            fb_micro = max(1, args.global_batch // fb_dp)
            if args.global_batch % (fb_micro * fb_dp):
                return None
            fb_accum = args.global_batch // (fb_micro * fb_dp)
            fb_state = jax.eval_shape(fb.init, jax.random.PRNGKey(0))
            fb_state = jax.tree.map(
                lambda leaf, sh: jax.ShapeDtypeStruct(
                    leaf.shape, leaf.dtype, sharding=sh),
                fb_state, fb.state_shardings,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
            )
            fb_batch = _batch_abstract(fb_mesh, fb, fb_micro, fb_accum)
            fb_key, fb_inputs = cc.compile_fingerprint(
                num_nodes=n_nodes,
                total_devices=len(subset),
                mesh_axes=dict(fb_mesh.shape),
                model=cfg,
                strategy=strategy,
                args_signature=cc.abstract_signature((fb_state, fb_batch)),
                extra={"lr": args.lr, "objective": args.objective},
            )
            return fb_key, fb_inputs, (
                lambda: fb.step.lower(fb_state, fb_batch).compile()
            )

        cc.FallbackPrecompiler(
            _build_for_nodes,
            world_sizes=[ctx.num_nodes - 1, ctx.num_nodes + 1],
            cache=cache_client,
        ).start()

    # ---- data: master-fed dynamic shards under the agent, local otherwise
    vocab = cfg.vocab_size
    rng_seed = 1234

    packed = None
    if args.data_file:
        # real data: flat binary token file, windowed (the master's
        # shard indices address windows)
        from dlrover_tpu.trainer.token_dataset import PackedTokenDataset

        packed = PackedTokenDataset(args.data_file, seq=seq)
        args.dataset_size = len(packed)

        def tokens_for(idx: int) -> np.ndarray:
            return packed[idx]["tokens"]
    else:
        def tokens_for(idx: int) -> np.ndarray:
            g = np.random.Generator(np.random.Philox(key=rng_seed + idx))
            return g.integers(0, vocab, seq + 1, dtype=np.int32)

    from dlrover_tpu.trainer.data import ElasticDataset, PrefetchLoader

    dataset = ElasticDataset(
        args.dataset_size, name="synthetic", shard_size=args.shard_size,
        num_epochs=args.epochs, shuffle=True, under_agent=ctx.under_agent,
    )
    if args.objective == "mlm":
        mask_id = vocab - 1

        def sample_fn(idx: int):
            # mask keyed per sample index (an independent Philox stream
            # from tokens_for): a resumed run reproduces the exact same
            # corruption, like the token stream itself
            t = tokens_for(idx)[:seq]
            g = np.random.Generator(
                np.random.Philox(key=(rng_seed << 32) ^ idx)
            )
            return t, g.random(t.shape) < 0.15

        def collate(samples):
            t = np.stack([s[0] for s in samples])
            m = np.stack([s[1] for s in samples])
            return {
                "tokens": np.where(m, mask_id, t).astype(np.int32),
                "targets": t,
                "mlm_mask": m,
            }
    else:
        sample_fn = tokens_for

        def collate(samples):
            return {"tokens": np.stack(samples)}

    loader = PrefetchLoader(
        dataset,
        sample_fn=sample_fn,
        collate=collate,
        accum=trainer.accum,
        batch_size=trainer.local_step_batch,
        config_reader=paral,
    )

    on_cpu = jax.devices()[0].platform == "cpu"

    def mem_interval() -> int:
        # Young-Daly tuned cadence from the master (paral-config push,
        # hot-applied — snapshot cadence is not compile-baked); the CLI
        # value stands until the tuner's first retune arrives
        suggested = int(paral.get("snapshot_interval", 0) or 0)
        return suggested if suggested > 0 else args.mem_ckpt_interval

    def checkpointer(step: int, st) -> None:
        if os.environ.get("DLROVER_TPU_DEBUG_LEAF"):
            import jax as _j
            print(f"[dbg] host={step} leaf={int(_j.device_get(st.step))}",
                  flush=True)
        if step % mem_interval() == 0:
            if step % args.ckpt_interval == 0:
                engine.save_to_storage(step, st)
            else:
                # zero-stall where safe; the engine self-gates
                # (sharded/CPU fall back to the sync path)
                engine.save_to_memory_async(step, st)

    losses: list[float] = []
    goodput = None
    if args.goodput_log and ctx.node_rank == 0:
        from dlrover_tpu.utils.goodput import GoodputRecorder

        goodput = GoodputRecorder(args.goodput_log,
                                  restart_count=ctx.restart_count)

    def _should_crash() -> bool:
        if args.crash_once_file:
            try:
                # O_EXCL create makes the once-claim atomic even when
                # several nodes share the marker path
                with open(args.crash_once_file, "x") as f:
                    f.write("crashed")
                return True
            except FileExistsError:
                return False
        return args.crash_always or ctx.restart_count == 0

    # On CPU, pace the host to the device each step: dispatch runs ahead
    # of execution by hundreds of steps there, so host-side step events
    # (goodput log) and snapshot timings would charge queue-drain waits
    # to the wrong step. In-process fetch is ~free on CPU; on TPU the
    # tunnel RTT makes pacing expensive AND async dispatch is the point.
    pace_host = on_cpu

    def on_step(step: int, metrics: dict) -> None:
        if pace_host:
            jax.device_get(metrics["loss"])
        if goodput is not None:
            goodput.step(step)
        if args.hang_at_step and step == args.hang_at_step \
                and ctx.restart_count == 0:
            print(f"[trainer] injected hang at step {step}", flush=True)
            while True:  # wedged: alive but no progress
                time.sleep(3600)
        if args.crash_at_step and step == args.crash_at_step \
                and _should_crash():
            print(f"[trainer] injected crash at step {step} "
                  f"(exit {args.crash_exit})", flush=True)
            sys.stdout.flush()
            os._exit(args.crash_exit)
        if step % args.log_interval == 0:
            loss = float(jax.device_get(metrics["loss"]))
            losses.append(loss)
            print(f"[trainer] step {step} loss {loss:.4f}", flush=True)
        if args.step_delay > 0:
            # sync first so the delay paces the DEVICE, not just dispatch
            jax.device_get(metrics["loss"])
            time.sleep(args.step_delay)

    start = time.monotonic()
    state = trainer.run_batches(
        state,
        iter(loader),
        max_steps=args.max_steps,
        on_step=on_step,
        checkpointer=checkpointer,
        checkpoint_interval=1,
    )
    loader.close()
    final_step = int(state.step)
    if goodput is not None:
        goodput.done()
        goodput.close()
    # persist this run's measurement into the autopilot history: the
    # next job with the same workload fingerprint ranks from evidence
    # (journaled `autopilot_plan source=history`) instead of the model
    if autopilot_plan is not None and autopilot_history is not None \
            and ctx.node_rank == 0:
        measured = trainer.efficiency.step_seconds()
        if measured and measured > 0:
            # key the record by the plan's STAMPED shape fields — the
            # planner's lookup keys on the same tuple (incl. hbm_gb
            # from the device envelope), and a mismatched key would
            # silently never seed a later ranking
            autopilot_history.record(
                autopilot_plan.strategy_json, measured,
                model=autopilot_plan.model or args.model,
                n_devices=autopilot_plan.n_devices or len(jax.devices()),
                batch=autopilot_plan.batch or max(1, args.global_batch),
                seq=autopilot_plan.seq or seq,
                hbm_gb=autopilot_plan.hbm_gb,
                mfu=trainer.efficiency.mfu(),
            )
            print(f"[trainer] autopilot history: recorded "
                  f"{measured:.4f}s/step for {autopilot_plan.name}",
                  flush=True)
    if autopilot_history is not None:
        autopilot_history.close()
    engine.save_to_storage(final_step, state)
    waited = engine.wait_for_persist(final_step, timeout=120)
    if not waited:
        print(f"[train] WARNING: final step {final_step} not durable "
              f"(newest committed: {waited.persisted_step})", flush=True)
    engine.close()

    if args.result_file and ctx.node_rank == 0:
        with open(args.result_file, "w") as f:
            json.dump(
                {
                    "final_step": final_step,
                    "resumed_from": resumed_from,
                    "restart_count": ctx.restart_count,
                    "num_nodes": ctx.num_nodes,
                    "last_loss": losses[-1] if losses else None,
                    "wall_s": round(time.monotonic() - start, 2),
                },
                f,
            )
    print(f"[trainer] done at step {final_step}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
