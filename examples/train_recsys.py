"""Sparse recommendation training: KvVariable embeddings + JAX dense tower.

The TPU-native analog of the reference's tfplus DeepRec PS-worker
recommendation path (BASELINE.md config 5; tfplus/kv_variable/python/ops/
embedding_ops.py over the C++ KvVariable kernels). Architecture: unbounded
sparse ids live in the host-side C++ table (dlrover_tpu/embedding); each
step gathers the batch's rows into a dense [B, F, dim] block that goes to
the device; the dense tower trains under jit; embedding-row gradients come
back with jax.grad and apply host-side via sparse GroupAdam.

Run standalone or under the agent:
    python -m dlrover_tpu.run --standalone examples/train_recsys.py -- \
        --steps 200
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser("train_recsys")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--fields", type=int, default=8,
                   help="sparse feature fields per example")
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--id-space", type=int, default=1_000_000)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--group-lasso", type=float, default=0.0)
    p.add_argument("--sparse-optimizer", default="adam",
                   choices=["adam", "group_adam", "adagrad",
                            "group_adagrad", "ftrl", "group_ftrl",
                            "radam"],
                   help="host-side sparse optimizer for the embedding "
                        "table (reference: tfplus training_ops.cc "
                        "family)")
    p.add_argument("--ckpt-dir", default="")
    p.add_argument("--result-file", default="")
    p.add_argument("--log-interval", type=int, default=50)
    p.add_argument("--spill-dir", default="",
                   help="hybrid storage: spill cold rows (freq <= "
                        "--spill-max-freq) to a file in this dir every "
                        "--spill-interval steps, bounding host memory")
    p.add_argument("--spill-interval", type=int, default=100)
    p.add_argument("--spill-max-freq", type=int, default=1)
    p.add_argument("--incremental-ckpt", action="store_true",
                   help="with --ckpt-dir: base+delta embedding "
                        "checkpoints (only changed rows per save) every "
                        "--log-interval steps")
    p.add_argument("--table-shards", type=int, default=0,
                   help="shard the embedding table across N server "
                        "processes (the elastic-PS analog, "
                        "embedding/service.py); 0 = in-process table")
    p.add_argument("--table-coordinator", default="",
                   help="connect to an existing embedding coordinator "
                        "instead of spawning local shard servers")
    p.add_argument("--fabric", type=int, default=0,
                   help="elastic embedding fabric (DESIGN.md §25): run "
                        "the table as a consistent-hash ring of N "
                        "in-process shard servers with async gradient "
                        "streaming and verified shard checkpoints")
    p.add_argument("--fabric-coordinator", default="",
                   help="connect to an existing fabric coordinator "
                        "(host:port) instead of spawning a local ring")
    p.add_argument("--sync-apply", action="store_true",
                   help="fabric only: block every step on the sparse "
                        "update instead of streaming it asynchronously")
    p.add_argument("--serve-port", type=int, default=0,
                   help="fabric only: serve the LIVE training ring "
                        "over HTTP on this port (POST "
                        "/v1/embedding/lookup — the train+serve-from-"
                        "one-table path; 0 = off)")
    return p.parse_args(argv)


def _start_fabric(args):
    """Fabric-mode table: ring client (async apply), optional restore,
    optional live-serving HTTP front door. Returns (client, cleanup,
    persist_fn) — persist_fn(step) runs the drain barrier + verified
    ring checkpoint when a checkpoint dir is configured."""
    from dlrover_tpu.embedding.fabric import FabricClient, start_local_fabric

    coord = None
    servers: list = []
    http = None
    serve_client = None
    fabric_ckpt = (os.path.join(args.ckpt_dir, "embedding-fabric")
                   if args.ckpt_dir else "")
    if args.fabric_coordinator:
        coord_addr = args.fabric_coordinator
    else:
        coord, servers = start_local_fabric(
            args.fabric, dim=args.dim, num_slots=2, seed=1234,
            ckpt_dir=fabric_ckpt,
        )
        coord_addr = coord.addr
    client = FabricClient(coordinator_addr=coord_addr, dim=args.dim,
                          async_apply=not args.sync_apply)
    restored = None
    if coord is not None and fabric_ckpt:
        restored = coord.restore()
        if restored:
            print(f"[recsys] fabric restored step {restored['step']} "
                  f"({restored['rows']} rows from a "
                  f"{restored['num_shards']}-shard save onto "
                  f"{len(client.route.members)} shards)", flush=True)
            client.resume_from(restored["applied_version"])
    if args.serve_port:
        from dlrover_tpu.gateway.server import GatewayHTTPServer

        serve_client = FabricClient(coordinator_addr=coord_addr,
                                    dim=args.dim, mode="serve")
        http = GatewayHTTPServer(
            None, host="127.0.0.1", port=args.serve_port,
            embedding_client=serve_client,
        ).start()
        print(f"[recsys] live embedding lookups on port {http.port}",
              flush=True)

    def persist_fn(step: int) -> None:
        info = client.persist(step)
        print(f"[recsys] fabric ckpt step {step}: {info['rows']} rows "
              f"across {info['num_shards']} shards "
              f"(applied v{info['applied_version']})", flush=True)

    def cleanup() -> None:
        if http is not None:
            http.stop()
        if serve_client is not None:
            serve_client.close()
        client.close()
        if coord is not None:
            coord.stop()
        for s in servers:
            s.stop()

    # an external coordinator owns its own checkpoint dir; a local ring
    # persists only when --ckpt-dir gave it one
    can_persist = bool(fabric_ckpt or args.fabric_coordinator)
    return client, cleanup, (persist_fn if can_persist else None)


def _spawn_sharded_table(args, ckpt_dir: str):
    """Spawn --table-shards local shard-server processes + coordinator;
    returns (client, cleanup). The multi-host deployment runs the same
    ``python -m dlrover_tpu.embedding.service`` servers on CPU hosts and
    passes --table-coordinator instead."""
    import atexit
    import subprocess

    from dlrover_tpu.embedding.service import (
        EmbeddingCoordinator,
        ShardedKvClient,
    )

    procs, addrs = [], []

    def _kill_procs():
        for p_ in procs:
            p_.terminate()
        for p_ in procs:
            try:
                p_.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p_.kill()

    try:
        for i in range(args.table_shards):
            cmd = [sys.executable, "-m", "dlrover_tpu.embedding.service",
                   "--dim", str(args.dim), "--host", "127.0.0.1",
                   "--index", str(i),
                   "--num-shards", str(args.table_shards)]
            if ckpt_dir:
                cmd += ["--ckpt-dir",
                        os.path.join(ckpt_dir, "embedding-shards")]
            if args.spill_dir:
                cmd += ["--spill-dir", args.spill_dir]
            proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu",
                     "DLROVER_TPU_PLATFORM": "cpu"},
            )
            procs.append(proc)
            line = proc.stdout.readline().strip()
            if not line.startswith("PORT "):
                raise RuntimeError(
                    f"shard server {i} failed to start: {line!r}")
            addrs.append(f"127.0.0.1:{line.split()[1]}")
        coord = EmbeddingCoordinator(addrs, host="127.0.0.1").start()
        client = ShardedKvClient(
            coordinator_addr=f"127.0.0.1:{coord.port}", dim=args.dim
        )
    except BaseException:
        _kill_procs()
        raise

    def cleanup():
        if procs:
            client.close()
            coord.stop()
            _kill_procs()
            procs.clear()

    # a mid-training crash must not orphan the server processes (their
    # main loop sleeps forever); atexit covers every interpreter exit
    # path short of SIGKILL, and cleanup() is idempotent for the
    # success path's explicit call
    atexit.register(cleanup)
    return client, cleanup


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.embedding import KvEmbeddingTable
    from dlrover_tpu.trainer import bootstrap

    ctx = bootstrap.init_from_env()
    sharded_cleanup = None
    inc_mgr = None
    fabric_persist = None
    if args.fabric or args.fabric_coordinator:
        table, sharded_cleanup, fabric_persist = _start_fabric(args)
    elif args.table_coordinator:
        from dlrover_tpu.embedding.service import ShardedKvClient

        table = ShardedKvClient(
            coordinator_addr=args.table_coordinator, dim=args.dim
        )
    elif args.table_shards:
        table, sharded_cleanup = _spawn_sharded_table(args, args.ckpt_dir)
        if args.incremental_ckpt and args.ckpt_dir:
            restored = table.ckpt_restore()
            if any(restored):
                print(f"[recsys] sharded table restored at versions "
                      f"{restored} ({len(table)} rows)", flush=True)
    else:
        table = KvEmbeddingTable(dim=args.dim, num_slots=2, seed=1234)
        if args.spill_dir:
            os.makedirs(args.spill_dir, exist_ok=True)
            table.enable_spill(os.path.join(
                args.spill_dir, f"recsys-{ctx.node_id}.spill"
            ))
        if args.incremental_ckpt and args.ckpt_dir:
            from dlrover_tpu.embedding.kv_table import (
                IncrementalCheckpointManager,
            )

            # node-scoped like the spill file and the CheckpointEngine:
            # each node's table has its own base/delta chain
            inc_mgr = IncrementalCheckpointManager(
                table,
                os.path.join(args.ckpt_dir, f"embedding-inc-{ctx.node_id}"),
            )
            restored = inc_mgr.restore()
            if restored:
                print(f"[recsys] embedding table restored at version "
                      f"{restored} ({len(table)} rows)", flush=True)

    # dense tower: concat field embeddings -> MLP -> logit
    d_in = args.fields * args.dim
    k0, k1 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "w1": jax.random.normal(k0, (d_in, 64), jnp.float32) / np.sqrt(d_in),
        "b1": jnp.zeros((64,)),
        "w2": jax.random.normal(k1, (64, 1), jnp.float32) / 8.0,
        "b2": jnp.zeros((1,)),
    }
    optimizer = optax.adam(args.lr)
    opt_state = optimizer.init(params)

    def forward(params, emb):
        x = emb.reshape(emb.shape[0], -1)
        h = jax.nn.relu(x @ params["w1"] + params["b1"])
        return (h @ params["w2"] + params["b2"])[:, 0]

    def loss_fn(params, emb, labels):
        logits = forward(params, emb)
        return optax.sigmoid_binary_cross_entropy(logits, labels).mean()

    @jax.jit
    def train_step(params, opt_state, emb, labels):
        loss, (grads, emb_grads) = jax.value_and_grad(
            loss_fn, argnums=(0, 1)
        )(params, emb, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, emb_grads

    rng = np.random.default_rng(7)

    def make_batch():
        ids = rng.zipf(1.3, size=(args.batch, args.fields)).astype(
            np.int64
        ) % args.id_space
        # learnable synthetic signal: the first field's id parity — each
        # hot id's embedding can memorize its label
        labels = (ids[:, 0] % 2).astype(np.float32)
        return ids, labels

    losses = []
    start = time.monotonic()
    for step in range(1, args.steps + 1):
        ids, labels = make_batch()
        emb = table.lookup(ids)                          # host gather
        params, opt_state, loss, emb_grads = train_step(
            params, opt_state, jnp.asarray(emb), jnp.asarray(labels)
        )
        kwargs = {"lr": args.lr}                         # host sparse update
        if args.group_lasso and args.sparse_optimizer != "radam":
            kwargs["group_lasso"] = args.group_lasso
        table.apply(args.sparse_optimizer, ids, np.asarray(emb_grads),
                    **kwargs)
        if step % args.log_interval == 0:
            losses.append(float(loss))
            print(f"[recsys] step {step} loss {losses[-1]:.4f} "
                  f"table={len(table)}", flush=True)
            if fabric_persist is not None:
                try:
                    fabric_persist(step)
                except (OSError, RuntimeError, TimeoutError) as e:
                    # a failed ring save never blocks training; the
                    # next interval (and the final save) retry it
                    print(f"[recsys] fabric ckpt postponed: {e}",
                          flush=True)
            elif inc_mgr is not None:
                try:
                    path = inc_mgr.save()
                    print(f"[recsys] incremental ckpt: "
                          f"{os.path.basename(path)}", flush=True)
                except OSError as e:
                    # the manager parks the drained changes; the next
                    # interval's save retries them — keep training
                    print(f"[recsys] incremental ckpt postponed: {e}",
                          flush=True)
            elif (args.incremental_ckpt and args.ckpt_dir
                  and hasattr(table, "ckpt_save")):
                paths = table.ckpt_save()
                print(f"[recsys] sharded incremental ckpt: "
                      f"{[os.path.basename(p) for p in paths]}",
                      flush=True)
        if (args.spill_dir and hasattr(table, "evict")
                and step % args.spill_interval == 0):
            spilled = table.evict(max_freq=args.spill_max_freq)
            if spilled:
                print(f"[recsys] spilled {spilled} cold rows "
                      f"(disk={table.disk_rows})", flush=True)
    wall = time.monotonic() - start

    if args.ckpt_dir:
        from dlrover_tpu.checkpoint.engine import CheckpointEngine

        engine = CheckpointEngine(args.ckpt_dir, node_id=ctx.node_id)
        if fabric_persist is not None:
            # the ring checkpoints itself (drain barrier + verified
            # shard manifest); the engine carries only the dense tower
            fabric_persist(args.steps)
            state = {"dense": params}
        else:
            state = {"dense": params, "embedding": table.export()}
        engine.save_to_storage(args.steps, state)
        waited = engine.wait_for_persist(args.steps, timeout=120)
        if not waited:
            print("[recsys] WARNING: final checkpoint not durable "
                  f"(newest committed: {waited.persisted_step})",
                  flush=True)
        engine.close()
        print(f"[recsys] checkpointed {len(table)} rows", flush=True)

    if args.result_file:
        with open(args.result_file, "w") as f:
            json.dump(
                {
                    "final_step": args.steps,
                    "last_loss": losses[-1] if losses else None,
                    "first_loss": losses[0] if losses else None,
                    "table_rows": len(table),
                    "examples_per_s": round(args.steps * args.batch / wall),
                    **({"staleness": table.staleness()}
                       if hasattr(table, "staleness") else {}),
                },
                f,
            )
    print(f"[recsys] done: {args.steps * args.batch / wall:.0f} examples/s",
          flush=True)
    if sharded_cleanup is not None:
        sharded_cleanup()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
