"""MFU probe: measure one training-step config on the attached device.

Usage: python examples/mfu_probe.py --policy dots_no_batch --batch 32 \
           --attention splash --steps 20 [--no-remat] [--unroll 12]

Prints one JSON line with step time and model-FLOPs MFU so configs can be
swept from the shell (used to chase the r03 MFU ceiling; see bench.py's
bench_train_step for the production config and DESIGN.md §9 for numbers).

Deliberately mirrors bench_train_step's protocol (same warmup/timing/sync
and the same PaLM 6N + 12*L*S*d accounting) — a sweep number here must be
directly comparable to the bench's reported MFU. If the accounting there
changes, change it here too.
"""

import argparse
import dataclasses
import json
import time
from functools import partial

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-small")
    p.add_argument("--policy", default="dots_no_batch")
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--attention", default="splash")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--unroll", type=int, default=12)
    p.add_argument("--interval", type=int, default=1)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--ce-chunks", type=int, default=16)
    args = p.parse_args()

    import jax
    import optax

    from dlrover_tpu.models import transformer as tfm
    from dlrover_tpu.parallel import strategy as strat_lib
    from dlrover_tpu.trainer.train_step import compile_train

    dev = jax.devices()[0]
    cfg = dataclasses.replace(
        tfm.CONFIGS[args.model],
        remat_scan=not args.no_remat,
        remat_policy=args.policy,
        attention=args.attention,
        ce_chunks=args.ce_chunks,
        scan_unroll=args.unroll,
        remat_interval=1 if args.no_remat else args.interval,
        int8_matmuls=args.int8,
    )
    args.seq = min(cfg.max_seq_len, args.seq)
    strat = strat_lib.dp()
    mesh = strat.build_mesh(jax.devices()[:1])
    compiled = compile_train(
        strategy=strat,
        mesh=mesh,
        loss_fn=partial(tfm.loss_fn, cfg=cfg),
        init_params_fn=lambda rng: tfm.init_params(cfg, rng),
        logical_params=tfm.logical_axes(cfg),
        optimizer=optax.adamw(1e-4),
    )
    state = compiled.init(jax.random.PRNGKey(0))
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, args.batch, args.seq + 1), dtype=np.int32
    )
    batch = jax.device_put({"tokens": tokens}, compiled.batch_sharding)

    t0 = time.monotonic()
    state, metrics = compiled.step(state, batch)
    loss0 = float(jax.device_get(metrics["loss"]))
    compile_s = time.monotonic() - t0
    for _ in range(2):
        state, metrics = compiled.step(state, batch)
    float(jax.device_get(metrics["loss"]))

    t0 = time.monotonic()
    for _ in range(args.steps):
        state, metrics = compiled.step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    step_s = (time.monotonic() - t0) / args.steps

    from dlrover_tpu.utils.profiler import device_peak_flops

    n = cfg.param_count
    fpt = 6 * n + 12 * cfg.n_layers * args.seq * cfg.d_model
    flops = fpt * args.batch * args.seq
    peak = device_peak_flops(dev)
    print(json.dumps({
        "policy": args.policy if not args.no_remat else "none",
        "attention": args.attention,
        "batch": args.batch,
        "unroll": args.unroll,
        "interval": cfg.remat_interval,
        "int8": cfg.int8_matmuls,
        "compile_s": round(compile_s, 1),
        "step_s": round(step_s, 4),
        "mfu": round(flops / step_s / peak, 4) if peak else None,
        "loss0": round(loss0, 3),
        "loss": round(loss, 3),
    }))


if __name__ == "__main__":
    main()
