"""Standalone data worker: serve ready batches to remote trainers.

The coworker-pod entrypoint (reference: atorch CPU coworker pods feeding
GPU trainers via the data service): run one of these per CPU host —
trainers consume with ``RemoteBatchLoader([host:port, ...])`` (or pass
the addresses to your training script). Batches come from a packed
binary token file (trainer/token_dataset.py format) or are synthetic.

    python examples/data_worker.py --port 9300 --data-file corpus.bin \
        --batch 8 --seq 1024
"""

import argparse
import time

import numpy as np

from dlrover_tpu.trainer.data_service import DataServiceServer


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9300)
    p.add_argument("--data-file", default="",
                   help="packed token file; empty -> synthetic")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--count", type=int, default=0,
                   help="stop after N batches (0 = until data runs out; "
                        "synthetic data never does)")
    # a fleet of workers must serve a PARTITION, not copies: give each
    # worker its shard index, and all the same shard count + seed
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--shard-index", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    if not 0 <= args.shard_index < args.num_shards:
        raise SystemExit("--shard-index must be in [0, --num-shards)")

    def produce():
        if args.data_file:
            from dlrover_tpu.trainer.token_dataset import PackedTokenDataset

            packed = PackedTokenDataset(args.data_file, seq=args.seq)
            # one shared permutation (same seed fleet-wide), strided by
            # shard: disjoint per worker, jointly covering the epoch
            order = np.random.default_rng(args.seed).permutation(
                len(packed))[args.shard_index::args.num_shards]
            n = 0
            for start in range(0, len(order) - args.batch + 1, args.batch):
                idx = order[start:start + args.batch]
                yield {"tokens": np.stack(
                    [packed[int(i)]["tokens"] for i in idx])}
                n += 1
                if args.count and n >= args.count:
                    return
        else:
            g = np.random.default_rng(args.seed + args.shard_index)
            n = 0
            while not args.count or n < args.count:
                yield {"tokens": g.integers(
                    0, args.vocab, (args.batch, args.seq + 1),
                    dtype=np.int32)}
                n += 1

    srv = DataServiceServer(produce, host=args.host, port=args.port)
    srv.start()
    print(f"data worker serving on {args.host}:{srv.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
