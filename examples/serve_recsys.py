"""Score with a trained recsys checkpoint: the sparse serving path.

The inference-side twin of examples/train_recsys.py (reference analog:
tfplus models serve through TF with the KvVariable table restored from
checkpoint): restore the dense tower + the C++ embedding table from the
flash checkpoint, then run lookup -> dense forward over request batches
and report scores (+ accuracy on the example's synthetic parity signal,
as a restore-correctness check).

    python examples/train_recsys.py --steps 300 --ckpt-dir /tmp/rc
    python examples/serve_recsys.py --ckpt-dir /tmp/rc --requests 2000
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser("serve_recsys")
    p.add_argument("--ckpt-dir", default="",
                   help="flash checkpoint with the dense tower (+ the "
                        "embedding table unless --coordinator serves "
                        "it live)")
    p.add_argument("--coordinator", default="",
                   help="serve embeddings from a LIVE training fabric "
                        "ring (DESIGN.md §25): read-only version-"
                        "pinned lookups with the applied training "
                        "version stamped on every batch")
    p.add_argument("--fields", type=int, default=8)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--id-space", type=int, default=1_000_000)
    p.add_argument("--requests", type=int, default=1024)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--result-file", default="")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax
    import jax.numpy as jnp

    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.embedding import KvEmbeddingTable

    if not args.ckpt_dir and not args.coordinator:
        print("need --ckpt-dir and/or --coordinator", file=sys.stderr)
        return 2

    # dense tower: from the flash checkpoint when available, else a
    # fresh tower (live-ring smoke mode — scores are untrained)
    step = None
    arrays: dict = {}
    if args.ckpt_dir:
        # raw (template-free) restore: the embedding arrays' row count
        # is only known from the checkpoint itself
        engine = CheckpointEngine(args.ckpt_dir)
        loaded = engine.load_raw()
        engine.close()
        if loaded is None:
            print("no checkpoint found", file=sys.stderr)
            return 1
        step, arrays = loaded
    if arrays:
        params = {
            name.split("/", 1)[1]: jnp.asarray(arr)
            for name, arr in arrays.items() if name.startswith("dense/")
        }
    else:
        k0, k1 = jax.random.split(jax.random.PRNGKey(0))
        d_in = args.fields * args.dim
        params = {
            "w1": jax.random.normal(k0, (d_in, 64)) / np.sqrt(d_in),
            "b1": jnp.zeros((64,)),
            "w2": jax.random.normal(k1, (64, 1)) / 8.0,
            "b2": jnp.zeros((1,)),
        }

    if args.coordinator:
        # one table, training and serving: a read-only fabric client
        # over the live ring — lookups never materialize rows, every
        # batch is version-pinned and stamped with the applied
        # training version it reflects
        from dlrover_tpu.embedding.fabric import FabricClient

        table = FabricClient(coordinator_addr=args.coordinator,
                             dim=args.dim, mode="serve")
        print(f"serving from live ring v{table.version} "
              f"({table.route.members})", file=sys.stderr)
    else:
        table = KvEmbeddingTable(dim=args.dim, num_slots=2, seed=1234)
        table.import_({
            name.split("/", 1)[1]: np.asarray(arr)
            for name, arr in arrays.items()
            if name.startswith("embedding/")
        })
        print(f"restored step {step}: {len(table)} embedding rows",
              file=sys.stderr)

    @jax.jit
    def forward(params, emb):
        x = emb.reshape(emb.shape[0], -1)
        h = jax.nn.relu(x @ params["w1"] + params["b1"])
        return jax.nn.sigmoid((h @ params["w2"] + params["b2"])[:, 0])

    rng = np.random.default_rng(7)  # the training example's id law
    n_done = 0
    correct = 0
    t0 = time.monotonic()
    while n_done < args.requests:
        b = min(args.batch, args.requests - n_done)
        ids = rng.zipf(1.3, size=(b, args.fields)).astype(np.int64) \
            % args.id_space
        labels = (ids[:, 0] % 2).astype(np.float32)
        # serving lookups must not mutate the model: unseen ids score
        # with a zero vector instead of materializing a fresh row
        emb = table.lookup(ids, init_missing=False)
        scores = np.asarray(forward(params, jnp.asarray(emb)))
        correct += int(((scores > 0.5) == (labels > 0.5)).sum())
        n_done += b
    wall = time.monotonic() - t0
    acc = correct / n_done
    out = {
        "requests": n_done,
        "accuracy": round(acc, 4),
        "scores_per_s": round(n_done / wall),
        "table_rows": len(table),
        "restored_step": step,
    }
    if args.coordinator:
        info = table.last_lookup_info
        out["ring_version"] = info.get("version")
        out["applied_version"] = info.get("applied_version")
        out["staleness"] = info.get("staleness")
        table.close()
    print(json.dumps(out))
    if args.result_file:
        with open(args.result_file, "w") as f:
            json.dump(out, f)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
