"""Ring attention correctness vs the dense einsum reference.

SURVEY.md §5.7: the reference has no true ring attention (only all-reduce
softmax SP); this is the TPU-native gap-fill, validated on the virtual
CPU mesh.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.ops.ring_attention import make_ring_attention


def _mesh(names_shape: dict[str, int]) -> Mesh:
    n = int(np.prod(list(names_shape.values())))
    devs = np.asarray(jax.devices()[:n]).reshape(tuple(names_shape.values()))
    return Mesh(devs, tuple(names_shape))


def _qkv(b=2, s=64, h=4, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("seq_size", [2, 4, 8])
    def test_matches_dense(self, causal, seq_size):
        mesh = _mesh({"sequence": seq_size})
        q, k, v = _qkv()
        ref = tfm.dense_attention(q, k, v, causal=causal)
        ring = make_ring_attention(mesh)
        out = jax.jit(partial(ring, causal=causal))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_matches_dense_with_data_axis(self):
        mesh = _mesh({"data": 2, "sequence": 4})
        q, k, v = _qkv(b=4)
        ref = tfm.dense_attention(q, k, v, causal=True)
        out = jax.jit(make_ring_attention(mesh))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_gradients_match_dense(self):
        mesh = _mesh({"sequence": 4})
        q, k, v = _qkv()
        ring = make_ring_attention(mesh)

        def f_ring(q, k, v):
            return ring(q, k, v, causal=True).astype(jnp.float32).sum()

        def f_dense(q, k, v):
            return tfm.dense_attention(
                q, k, v, causal=True
            ).astype(jnp.float32).sum()

        g_ring = jax.jit(jax.grad(f_ring, argnums=(0, 1, 2)))(q, k, v)
        g_dense = jax.jit(jax.grad(f_dense, argnums=(0, 1, 2)))(q, k, v)
        for gr, gd, name in zip(g_ring, g_dense, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gd), atol=5e-5, rtol=5e-5,
                err_msg=f"grad wrt {name}",
            )

    def test_matches_dense_with_tensor_axis_sharded_heads(self):
        """Heads stay sharded over the tensor axis inside the ring."""
        mesh = _mesh({"sequence": 2, "tensor": 4})
        q, k, v = _qkv(h=4)
        ref = tfm.dense_attention(q, k, v, causal=True)
        out = jax.jit(make_ring_attention(mesh))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_no_sequence_axis_degrades_to_dense(self):
        mesh = _mesh({"data": 8})
        assert make_ring_attention(mesh) is tfm.dense_attention


class TestLongContextModel:
    def test_model_loss_ring_equals_dense(self):
        """Full transformer under the long_context strategy: loss matches
        the dense-attention run bit-for-bit-ish."""
        from dlrover_tpu.parallel.strategy import long_context, dp

        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, cfg.max_seq_len + 1), 0,
            cfg.vocab_size,
        )
        batch = {"tokens": tokens}

        strat_lc = long_context(sequence_size=4, data_size=2)
        mesh_lc = strat_lc.build_mesh()
        loss_ring = jax.jit(tfm.make_loss_fn(cfg, strat_lc, mesh_lc))(
            params, batch
        )

        strat_dp = dp()
        mesh_dp = strat_dp.build_mesh()
        loss_dense = jax.jit(tfm.make_loss_fn(cfg, strat_dp, mesh_dp))(
            params, batch
        )
        np.testing.assert_allclose(
            float(loss_ring), float(loss_dense), atol=2e-4, rtol=2e-4
        )
