"""ShardedPPOTrainer: the RL model-engine analog on a real mesh.

Round-2 verdict Missing #6 / Next #8: rl/ppo.py was single-host with one
shared config. rl/engine.py runs actor/critic/reference under the
strategy layer — per-model sharding rules on one mesh, ZeRO-style
optimizer-state sharding, KV-cached decode jitted with those shardings.
Reference analog: atorch/atorch/rl/model_engine/model_engine.py:1,
atorch/rl/trainer/ppo_trainer.py:1.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.parallel.strategy import dp, fsdp, fsdp_tp
from dlrover_tpu.rl.engine import ShardedPPOTrainer
from dlrover_tpu.rl.ppo import PPOConfig, PPOTrainer

CFG = tfm.CONFIGS["tiny"]


def _reward(tokens: np.ndarray) -> np.ndarray:
    # favors sequences whose generated tail hits even token ids
    return (tokens[:, -8:] % 2 == 0).mean(axis=1).astype(np.float32)


@pytest.fixture(scope="module")
def engine():
    return ShardedPPOTrainer(
        CFG, PPOConfig(gen_len=8, ppo_epochs=1), _reward,
        jax.random.PRNGKey(0),
        strategy=fsdp_tp(tensor_size=2),
        ref_strategy=dp(),
    )


class TestShardedEngine:
    def test_params_and_opt_state_are_sharded(self, engine):
        # the actor's attention weights shard over fsdp x tensor
        wq = engine.params["model"]["layers"]["wq"]
        assert len(wq.sharding.spec) > 0, wq.sharding
        assert not wq.sharding.is_fully_replicated
        # ZeRO: adam moments follow the param layout
        mu_wq = jax.tree_util.tree_leaves(
            jax.tree.map(lambda x: x, engine.opt_state)
        )
        assert any(
            getattr(leaf, "sharding", None) is not None
            and not leaf.sharding.is_fully_replicated
            for leaf in mu_wq
            if hasattr(leaf, "sharding") and leaf.ndim >= 2
        )
        # per-model strategy: the frozen reference is replicated (dp)
        ref_wq = engine.ref_params["model"]["layers"]["wq"]
        assert ref_wq.sharding.is_fully_replicated

    def test_value_head_replicated(self, engine):
        assert engine.params["value_head"].sharding.is_fully_replicated
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow

    def test_train_step_runs_sharded(self, engine):
        prompts = np.random.default_rng(0).integers(
            0, CFG.vocab_size, (8, 16), dtype=np.int64
        )
        metrics = engine.train_step(prompts, jax.random.PRNGKey(1))
        assert np.isfinite(metrics["loss"])
        assert np.isfinite(metrics["policy_loss"])
        assert np.isfinite(metrics["score_mean"])
        # params stayed sharded through the donated update
        wq = engine.params["model"]["layers"]["wq"]
        assert not wq.sharding.is_fully_replicated

    def test_rollout_fields_are_dp_sharded(self, engine):
        prompts = np.random.default_rng(1).integers(
            0, CFG.vocab_size, (8, 16), dtype=np.int64
        )
        batch = engine.rollout(prompts, jax.random.PRNGKey(2))
        assert batch["tokens"].shape == (8, 16 + 8)
        spec = batch["old_logp"].sharding.spec
        assert len(spec) >= 1 and spec[0] is not None, spec


class TestParityWithSingleHost:
    # slow tier (tier-1 envelope): compile + decode-heavy serving parity
    # body (~10s each on XLA:CPU). `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_update_matches_unsharded_trainer(self):
        """One FIXED rollout batch through both trainers' update step:
        fsdp sharding is a layout, not an algorithm change, so the PPO
        loss must agree to float tolerance. (Comparing full train_steps
        would be flaky: sampling can flip a token on low-bit logit
        differences from sharded reduction order.)"""
        import dataclasses

        # f32 compute: in bf16 the sharded matmuls' reduction order
        # alone moves the loss ~1e-2 relative, drowning the comparison
        cfg = dataclasses.replace(CFG, dtype="float32")
        ppo = PPOConfig(gen_len=4, ppo_epochs=1)
        prompts = np.random.default_rng(7).integers(
            0, cfg.vocab_size, (8, 8), dtype=np.int64
        )
        base = PPOTrainer(cfg, ppo, _reward, jax.random.PRNGKey(5))
        batch = jax.device_get(
            base.rollout(prompts, jax.random.PRNGKey(6))
        )
        _, _, m0 = base._update(base.params, base.opt_state, batch)
        sharded = ShardedPPOTrainer(
            cfg, ppo, _reward, jax.random.PRNGKey(5), strategy=fsdp(),
        )
        _, _, m1 = sharded._update(
            sharded.params, sharded.opt_state, batch
        )
        for k in ("loss", "policy_loss", "value_loss"):
            assert float(m0[k]) == pytest.approx(float(m1[k]),
                                                 rel=1e-4, abs=1e-5), k


class TestServingRollouts:
    """Rollouts through the continuous-batching serving engine — the
    vLLM-inference-backend analog (atorch
    rl/inference_backend/vllm_backend.py:1) with per-iteration weight
    handoff."""

    def _trainer(self, temperature: float) -> ShardedPPOTrainer:
        return ShardedPPOTrainer(
            CFG,
            PPOConfig(gen_len=8, ppo_epochs=1, temperature=temperature),
            _reward, jax.random.PRNGKey(0), strategy=dp(),
        )

    # slow tier (tier-1 envelope): compile + decode-heavy serving parity
    # body (~10s each on XLA:CPU). `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_greedy_serving_matches_in_mesh_decode(self):
        """temperature=0: both backends must emit the SAME tokens from
        the same weights, and the rollout's logprobs (computed on those
        tokens by the training forward) must match exactly."""
        t_mesh = self._trainer(0.0)
        t_srv = self._trainer(0.0)
        t_srv.enable_serving_rollouts(slots=4, decode_block=4,
                                      max_len=CFG.max_seq_len)
        prompts = np.tile(
            np.arange(1, 7, dtype=np.int32)[None], (8, 1)
        ) + np.arange(8, dtype=np.int32)[:, None]
        key = jax.random.PRNGKey(3)
        b_mesh = t_mesh.rollout(prompts, key)
        b_srv = t_srv.rollout(prompts, key)
        np.testing.assert_array_equal(
            np.asarray(b_mesh["tokens"]), np.asarray(b_srv["tokens"])
        )
        np.testing.assert_allclose(
            np.asarray(b_mesh["old_logp"]),
            np.asarray(b_srv["old_logp"]), rtol=1e-5, atol=1e-6,
        )

    # slow tier (tier-1 envelope): compile + decode-heavy serving parity
    # body (~10s each on XLA:CPU). `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_weight_handoff_tracks_updates(self):
        """After a train step the serving engine must generate from the
        UPDATED weights (no stale-weights window)."""
        t = self._trainer(0.0)
        t.enable_serving_rollouts(slots=4, decode_block=4,
                                  max_len=CFG.max_seq_len)
        prompts = np.tile(np.arange(1, 7, dtype=np.int32)[None], (8, 1))
        t.train_step(prompts, jax.random.PRNGKey(0))
        # engine now generates exactly what the in-mesh decode does from
        # the post-update params
        from dlrover_tpu.models.decode import generate

        got = np.asarray(t._generate(prompts, jax.random.PRNGKey(1)))
        want = np.asarray(generate(
            t.params["model"], jax.numpy.asarray(prompts), t.cfg,
            t.ppo.gen_len, jax.random.PRNGKey(1), temperature=0.0,
        ))
        np.testing.assert_array_equal(got, want)

    # slow tier (tier-1 envelope): compile + decode-heavy serving parity
    # body (~10s each on XLA:CPU). `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_sampled_rollout_trains(self):
        """temperature > 0: a full PPO step through the serving backend
        runs and produces finite metrics."""
        t = self._trainer(0.7)
        t.enable_serving_rollouts(slots=4, decode_block=4,
                                  max_len=CFG.max_seq_len)
        prompts = np.tile(np.arange(1, 7, dtype=np.int32)[None], (8, 1))
        metrics = t.train_step(prompts, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss"]))
