"""Sharded checkpoint + reshard-on-load.

Reference analog: the reference restores FSDP flat-param checkpoints onto a
different world size (atorch/atorch/utils/fsdp_save_util.py:523); here:
save on mesh A, restore bitwise-identically onto mesh B.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dlrover_tpu.checkpoint.sharded import (
    CoverageError,
    PieceSource,
    ShardedCheckpointEngine,
    assemble,
)


def _state(seed: int = 0):
    k = jax.random.PRNGKey(seed)
    return {
        "w": jax.random.normal(k, (16, 32), jnp.float32),
        "b": jnp.arange(32, dtype=jnp.float32),
        "step": jnp.asarray(7, jnp.int32),
    }


def _place(state, mesh, specs):
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in state.items()
    }


def _mesh(n, names=("data",), shape=None):
    devs = np.asarray(jax.devices()[:n])
    shape = shape or (n,)
    return Mesh(devs.reshape(shape), names)


SPECS_FSDP = {
    "w": PartitionSpec("data"),
    "b": PartitionSpec("data"),
    "step": PartitionSpec(),
}
SPECS_TP = {
    "w": PartitionSpec(None, "model"),
    "b": PartitionSpec("model"),
    "step": PartitionSpec(),
}
SPECS_REPL = {
    "w": PartitionSpec(),
    "b": PartitionSpec(),
    "step": PartitionSpec(),
}


def _engine(tmp_path, node_id=0, **kw):
    return ShardedCheckpointEngine(
        str(tmp_path / "ckpt"), node_id=node_id, **kw
    )


def _assert_equal(restored, reference):
    for k in reference:
        np.testing.assert_array_equal(
            np.asarray(restored[k]), np.asarray(reference[k]), err_msg=k
        )


class TestReshardOnLoad:
    def test_same_mesh_restore_from_shm(self, tmp_ipc_dir, tmp_path):
        mesh = _mesh(8)
        state = _place(_state(), mesh, SPECS_FSDP)
        engine = _engine(tmp_path)
        try:
            assert engine.save_to_memory(11, state)
            shardings = {
                k: NamedSharding(mesh, SPECS_FSDP[k]) for k in state
            }
            loaded = engine.load_sharded(state, shardings)
            assert loaded is not None and loaded[0] == 11
            _assert_equal(loaded[1], _state())
        finally:
            engine.close()

    def test_reshard_8dev_fsdp_to_4dev_tp(self, tmp_ipc_dir, tmp_path):
        mesh_a = _mesh(8)
        state = _place(_state(), mesh_a, SPECS_FSDP)
        engine = _engine(tmp_path)
        try:
            assert engine.save_to_storage(21, state)
            assert engine.wait_for_persist(21, timeout=60)

            mesh_b = _mesh(4, names=("model",))
            shardings = {
                k: NamedSharding(mesh_b, SPECS_TP[k]) for k in state
            }
            loaded = engine.load_sharded(state, shardings)
            assert loaded is not None and loaded[0] == 21
            out = loaded[1]
            assert out["w"].sharding.mesh.shape["model"] == 4
            _assert_equal(out, _state())
        finally:
            engine.close()

    def test_reshard_2d_to_replicated(self, tmp_ipc_dir, tmp_path):
        mesh_a = _mesh(8, names=("data", "model"), shape=(2, 4))
        specs_2d = {
            "w": PartitionSpec("data", "model"),
            "b": PartitionSpec("model"),
            "step": PartitionSpec(),
        }
        state = _place(_state(), mesh_a, specs_2d)
        engine = _engine(tmp_path)
        try:
            assert engine.save_to_storage(33, state)
            assert engine.wait_for_persist(33, timeout=60)
            mesh_b = _mesh(2)
            shardings = {
                k: NamedSharding(mesh_b, SPECS_REPL[k]) for k in state
            }
            loaded = engine.load_sharded(state, shardings)
            assert loaded is not None and loaded[0] == 33
            _assert_equal(loaded[1], _state())
        finally:
            engine.close()

    def test_two_node_save_commit_and_assembly(self, tmp_ipc_dir, tmp_path):
        """Two 'nodes' each own half the shards; tracker commits only after
        both persisted; restore assembles across both node files."""
        mesh = _mesh(8)
        state = _place(_state(), mesh, SPECS_FSDP)

        def owned_by(node: int):
            # each simulated node owns the shards on "its" devices — the
            # real multi-host rule, where addressable_shards already
            # restricts to local devices
            def owned(shard):
                return (shard.replica_id == 0
                        and (shard.device.id < 4) == (node == 0))

            return owned

        e0 = _engine(tmp_path, node_id=0, node_rank=0, world_size=2,
                     owned=owned_by(0))
        e1 = _engine(tmp_path, node_id=1, node_rank=1, world_size=2,
                     owned=owned_by(1))
        try:
            import time

            # rank 1 persists first: its files land but no commit happens
            # (rank 0's done marker is missing)
            assert e1.save_to_storage(5, state)
            done_1 = tmp_path / "ckpt" / "step-5" / "done_1_w2"
            deadline = time.time() + 30
            while time.time() < deadline and not done_1.exists():
                time.sleep(0.1)
            assert done_1.exists()
            time.sleep(0.5)
            assert e1.latest_persisted_step() < 0, \
                "tracker committed before all shards were done"
            assert e0.save_to_storage(5, state)
            assert e0.wait_for_persist(5, timeout=60)

            mesh_b = _mesh(4, names=("model",))
            shardings = {
                k: NamedSharding(mesh_b, SPECS_TP[k]) for k in state
            }
            # new engine with empty shm: forces storage assembly from both
            e2 = _engine(tmp_path, node_id=2)
            try:
                loaded = e2.load_sharded(state, shardings)
                assert loaded is not None and loaded[0] == 5
                _assert_equal(loaded[1], _state())
            finally:
                e2.close()
        finally:
            e0.close()
            e1.close()


class TestStaleWorldIsolation:
    def test_stale_incarnation_files_ignored(self, tmp_ipc_dir, tmp_path):
        """A re-saved step must not blend shard files left by a previous
        incarnation with a different world size."""
        import json as _json
        import os as _os

        sdir = tmp_path / "ckpt" / "step-9"
        _os.makedirs(sdir)
        # stale garbage from a crashed 4-node incarnation: covers the whole
        # of 'w' so any blending would corrupt the restore
        garbage = np.full((16, 32), -1.0, np.float32)
        (sdir / "node_7.bin").write_bytes(garbage.tobytes())
        (sdir / "node_7.meta.json").write_text(_json.dumps({
            "step": 9, "total_size": garbage.nbytes, "num_shards": 4,
            "metas": {"w::piece0": {"offset": 0, "shape": [16, 32],
                                    "dtype": "float32",
                                    "nbytes": garbage.nbytes}},
            "sharded_index": {"w::piece0": {
                "path": "w", "global_shape": [16, 32], "dtype": "float32",
                "index": [[0, 16], [0, 32]]}},
        }))
        (sdir / "done_7_w4").write_bytes(b"")

        mesh = _mesh(8)
        state = _place(_state(), mesh, SPECS_FSDP)
        engine = _engine(tmp_path, world_size=1)
        try:
            assert engine.save_to_storage(9, state)
            assert engine.wait_for_persist(9, timeout=60)
            engine.shm_handler.clear()  # force the storage path
            shardings = {
                k: NamedSharding(mesh, SPECS_FSDP[k]) for k in state
            }
            loaded = engine.load_sharded(state, shardings)
            assert loaded is not None and loaded[0] == 9
            _assert_equal(loaded[1], _state())
        finally:
            engine.close()


class TestOrbaxCompat:
    def test_flash_to_orbax_roundtrip(self, tmp_ipc_dir, tmp_path):
        """Flash checkpoint -> Orbax export -> Orbax restore -> flash
        import: bitwise equality end to end."""
        from dlrover_tpu.checkpoint.engine import CheckpointEngine
        from dlrover_tpu.checkpoint.orbax_compat import (
            export_flash_to_orbax,
            import_orbax_to_flash,
            load_orbax,
        )

        state = {
            k: np.asarray(v) for k, v in _state().items()
        }
        engine = CheckpointEngine(str(tmp_path / "flash"), node_id=50)
        try:
            engine.save_to_storage(7, state)
            assert engine.wait_for_persist(7, timeout=60)
            orbax_dir = str(tmp_path / "orbax_ckpt")
            step = export_flash_to_orbax(engine, state, orbax_dir)
            assert step == 7
            restored = load_orbax(orbax_dir)
            for k in state:
                np.testing.assert_array_equal(restored[k], state[k])
        finally:
            engine.close()

        # seed a NEW flash pipeline from the orbax checkpoint
        engine2 = CheckpointEngine(str(tmp_path / "flash2"), node_id=51)
        try:
            import_orbax_to_flash(engine2, orbax_dir, step=7,
                                  template=state)
            loaded = engine2.load(state)
            assert loaded is not None and loaded[0] == 7
            for k in state:
                np.testing.assert_array_equal(loaded[1][k], state[k])
        finally:
            engine2.close()

    def test_sharded_export(self, tmp_ipc_dir, tmp_path):
        from dlrover_tpu.checkpoint.orbax_compat import (
            export_flash_to_orbax,
            load_orbax,
        )

        mesh = _mesh(8)
        state = _place(_state(), mesh, SPECS_FSDP)
        engine = _engine(tmp_path, node_id=52)
        try:
            assert engine.save_to_storage(9, state)
            assert engine.wait_for_persist(9, timeout=60)
            shardings = {
                k: NamedSharding(mesh, SPECS_FSDP[k]) for k in state
            }
            orbax_dir = str(tmp_path / "orbax_sharded")
            step = export_flash_to_orbax(
                engine, state, orbax_dir, shardings=shardings
            )
            assert step == 9
            restored = load_orbax(orbax_dir)
            _assert_equal(restored, _state())
        finally:
            engine.close()


class TestAssemble:
    def _piece(self, arr, index):
        return PieceSource(
            path="x", global_shape=(8, 8), dtype=arr.dtype,
            index=index, read=lambda: arr,
        )

    def test_overlap_and_exact_cover(self):
        full = np.arange(64, dtype=np.float32).reshape(8, 8)
        pieces = [
            self._piece(full[:4], [[0, 4], [0, 8]]),
            self._piece(full[4:], [[4, 8], [0, 8]]),
        ]
        out = assemble([[2, 6], [1, 7]], np.float32, pieces)
        np.testing.assert_array_equal(out, full[2:6, 1:7])

    def test_gap_raises(self):
        full = np.arange(64, dtype=np.float32).reshape(8, 8)
        pieces = [self._piece(full[:4], [[0, 4], [0, 8]])]
        with pytest.raises(CoverageError):
            assemble([[2, 6], [0, 8]], np.float32, pieces)


class TestConsensusRollbackUnits:
    """Direct coverage for the preemption-recovery rollback pieces
    (exercised end-to-end in test_preemption_e2e; pinned here)."""

    def test_full_host_state_assembles_and_validates(self):
        from dlrover_tpu.checkpoint.sharded import (
            CoverageError,
            PieceSource,
            ShardedCheckpointEngine,
        )

        full = np.arange(12, dtype=np.float32).reshape(3, 4)
        pieces = {
            "params/w": [
                PieceSource("params/w", (3, 4), np.dtype(np.float32),
                            [[0, 2], [0, 4]], lambda: full[:2]),
                PieceSource("params/w", (3, 4), np.dtype(np.float32),
                            [[2, 3], [0, 4]], lambda: full[2:]),
            ],
        }
        template = {"params": {"w": np.zeros((3, 4), np.float32)}}
        eng = ShardedCheckpointEngine.__new__(ShardedCheckpointEngine)
        got = eng._full_host_state(template, pieces)
        np.testing.assert_array_equal(got["params"]["w"], full)

        # a gap raises CoverageError (-> storage fallback, not garbage)
        gappy = {"params/w": pieces["params/w"][:1]}
        with pytest.raises(CoverageError):
            eng._full_host_state(template, gappy)

        # dtype drift (fp16 template vs fp32 snapshot — numpy has no
        # native bfloat16, same code path) raises ValueError -> storage
        # fallback; a mismatched broadcast tree would wedge the
        # recovery collective
        fp16_template = {
            "params": {"w": np.zeros((3, 4), np.float16)}
        }
        with pytest.raises(ValueError, match="dtype"):
            eng._full_host_state(fp16_template, pieces)

    def test_allgather_steps_single_process(self):
        from dlrover_tpu.checkpoint.sharded import (
            ShardedCheckpointEngine,
        )

        steps = ShardedCheckpointEngine._allgather_steps(7)
        assert steps.tolist() == [7]
        assert ShardedCheckpointEngine._allgather_steps(-1).tolist() \
            == [-1]
