"""MPMD pipeline runtime (parallel/mpmd.py) on the 8-device CPU mesh.

The ISSUE-10 acceptance surface: per-stage programs must compute what
the SPMD pipeline computes (within the reduction-order bound
``RTOL_CROSS_LAYOUT`` of tests/test_pipeline.py), the host 1F1B
schedule's measured bubble must sit at the ``(P-1)/(M+P-1)`` bound, a
single-stage failure must recompile ONLY that stage (journal-pinned
``pipeline_stage_compile`` trail), and the per-stage weight update must
actually shard the optimizer state ZeRO-style over the stage submesh's
data axis.
"""

import dataclasses
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import transformer as T
from dlrover_tpu.parallel import strategy as S
from dlrover_tpu.parallel.mpmd import (
    MpmdTrain,
    choose_schedule,
    split_params,
    stage_op_schedule,
)
from dlrover_tpu.parallel.pipeline import bubble_fraction
from tests.test_pipeline import RTOL_CROSS_LAYOUT

CFG = dataclasses.replace(T.CONFIGS["tiny"], n_layers=4, dtype="float32")
SEQ = 32


def _tokens(key, b=16):
    return np.asarray(
        jax.random.randint(jax.random.PRNGKey(key), (1, b, SEQ + 1), 0,
                           CFG.vocab_size)
    )


def _mpmd(optimizer=None, microbatches=4, accum=1, cfg=CFG):
    return MpmdTrain(
        cfg, S.mpmd(pipeline_size=2), optimizer or optax.sgd(1e-2),
        num_stages=2, microbatches=microbatches, seq=SEQ, step_batch=16,
        accum=accum,
    )


@pytest.fixture()
def aot_dir(tmp_path, monkeypatch):
    """Hermetic per-test compile-cache dir (the runtime's programs all
    ride load_or_compile)."""
    monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE_DIR",
                       str(tmp_path / "aot"))
    monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR", str(tmp_path / "jr"))
    return tmp_path


def _stage_compile_events(tmp_path):
    path = tmp_path / "jr" / "events.jsonl"
    if not os.path.exists(path):
        return []
    return [json.loads(line) for line in open(path)
            if json.loads(line)["name"] == "pipeline_stage_compile"]


class TestStageSplit:
    def test_split_covers_every_param(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        stages = split_params(params, 2)
        assert "embed" in stages[0] and "embed" not in stages[1]
        assert "lm_head" in stages[1] and "lm_head" not in stages[0]
        assert "ln_f" in stages[1]
        for tree in stages:
            for leaf in jax.tree_util.tree_leaves(tree["layers"]):
                assert leaf.shape[0] == CFG.n_layers // 2
        # every layer row lands in exactly one stage
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(s["layers"]["wq"])
                            for s in stages]),
            np.asarray(params["layers"]["wq"]),
        )

    def test_indivisible_layers_raise(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="divisible"):
            split_params(params, 3)

    def test_single_stage_rejected(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match=">= 2 stages"):
            split_params(params, 1)

    def test_moe_rejected(self):
        cfg = dataclasses.replace(T.CONFIGS["tiny-moe"], n_layers=4)
        with pytest.raises(NotImplementedError, match="MoE"):
            MpmdTrain(cfg, S.mpmd(2), optax.sgd(1e-2), num_stages=2,
                      microbatches=4, seq=SEQ, step_batch=16)

    def test_interleave_rejected(self):
        strat = S.mpmd(2)
        strat.extra["pipeline_interleave"] = 2
        with pytest.raises(NotImplementedError, match="1F1B"):
            MpmdTrain(CFG, strat, optax.sgd(1e-2), num_stages=2,
                      microbatches=4, seq=SEQ, step_batch=16)


class TestScheduleShape:
    """Pure host properties of the canonical 1F1B order — no jax."""

    @pytest.mark.parametrize("P,M", [(2, 2), (2, 4), (4, 4), (4, 8)])
    def test_op_counts_and_order(self, P, M):
        ops = stage_op_schedule(P, M)
        for s, stage_ops in enumerate(ops):
            assert len(stage_ops) == 2 * M
            fwds = [m for kind, m in stage_ops if kind == "F"]
            bwds = [m for kind, m in stage_ops if kind == "B"]
            assert fwds == list(range(M)) and bwds == list(range(M))
            # 1F1B memory bound: in-flight stashed activations never
            # exceed the warmup depth + 1
            depth = 0
            for kind, _ in stage_ops:
                depth += 1 if kind == "F" else -1
                assert depth <= min(M, P - 1 - s) + 1

    def test_last_stage_strictly_alternates(self):
        ops = stage_op_schedule(4, 8)[-1]
        kinds = [k for k, _ in ops]
        assert kinds == ["F", "B"] * 8


class TestNumerics:
    def test_matches_spmd_pipeline_loss(self, aot_dir):
        """ACCEPTANCE: MPMD loss == the SPMD pipeline's on the same
        seed/geometry, two consecutive steps (the second pins the
        ZeRO-sharded update path too), within RTOL_CROSS_LAYOUT."""
        from dlrover_tpu.trainer import compile_train

        b1, b2 = _tokens(42), _tokens(43)
        mt = _mpmd()
        state = mt.init(jax.random.PRNGKey(0))
        got = []
        for b in (b1, b2):
            batch = {"tokens": jax.device_put(b, mt.batch_sharding)}
            state, m = mt.step(state, batch)
            got.append(float(jax.device_get(m["loss"])))

        strat = S.pipeline(pipeline_size=2, data_size=4)
        mesh = strat.build_mesh()
        ct = compile_train(
            strategy=strat, mesh=mesh,
            loss_fn=T.make_loss_fn(CFG, strat, mesh),
            init_params_fn=lambda rng: T.init_params(CFG, rng),
            logical_params=T.logical_axes(CFG),
            optimizer=optax.sgd(1e-2),
        )
        sd = ct.init(jax.random.PRNGKey(0))
        ref = []
        for b in (b1, b2):
            sd, m = ct.step(sd, jax.device_put({"tokens": b},
                                               ct.batch_sharding))
            ref.append(float(jax.device_get(m["loss"])))
        assert got[0] == pytest.approx(ref[0], rel=RTOL_CROSS_LAYOUT)
        assert got[1] == pytest.approx(ref[1], rel=RTOL_CROSS_LAYOUT)

    def test_trains_and_bubble_at_1f1b_bound(self, aot_dir):
        mt = _mpmd(optax.adamw(1e-2))
        state = mt.init(jax.random.PRNGKey(0))
        losses = []
        for i in range(6):
            batch = {"tokens": jax.device_put(_tokens(i),
                                              mt.batch_sharding)}
            state, m = mt.step(state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        assert losses[-1] < losses[0]
        # the measured schedule bubble sits AT the 1F1B bound — the
        # dependency-driven executor leaves no extra idle ticks
        assert mt.last_bubble_frac == pytest.approx(
            bubble_fraction(2, 4), abs=1e-9)
        assert mt.last_bubble_frac <= mt.bubble_bound + 1e-9
        assert int(state.step) == 6

    def test_accum_rounds_match_single_round(self, aot_dir):
        """[2, 16, S] with accum=2 equals one [1, 32, S] dp-style global
        batch halved — pin the accumulation scale: two rounds of M=4
        average like one round of the doubled batch."""
        tok = _tokens(7, b=32)[0]
        mt = _mpmd(accum=2)
        state = mt.init(jax.random.PRNGKey(0))
        batch = {"tokens": jax.device_put(
            tok.reshape(2, 16, SEQ + 1), mt.batch_sharding)}
        _, m = mt.step(state, batch)
        mt8 = MpmdTrain(
            CFG, S.mpmd(2), optax.sgd(1e-2), num_stages=2,
            microbatches=8, seq=SEQ, step_batch=32, accum=1,
        )
        state8 = mt8.init(jax.random.PRNGKey(0))
        batch8 = {"tokens": jax.device_put(
            tok.reshape(1, 32, SEQ + 1), mt8.batch_sharding)}
        _, m8 = mt8.step(state8, batch8)
        assert float(m["loss"]) == pytest.approx(float(m8["loss"]),
                                                 rel=1e-6)


class TestPerStageCache:
    def test_single_stage_failure_recompiles_only_that_stage(
            self, aot_dir):
        """ACCEPTANCE: evict one stage's artifacts (= its replacement
        host lost them) and rebuild — the journal shows cold
        ``pipeline_stage_compile`` entries for EXACTLY that stage while
        the other P−1 stages hit the cache."""
        from dlrover_tpu.parallel import compile_cache as cc

        _mpmd()  # cold build, publishes all stage programs
        cold = _stage_compile_events(aot_dir)
        assert len(cold) == 5 and all(not e["hit"] for e in cold)
        evicted = glob.glob(
            os.path.join(cc.default_local_dir(), "*pp0of2*"))
        assert len(evicted) == 3  # fwd/bwd/update of stage 0
        for f in evicted:
            os.unlink(f)
        mt = _mpmd()
        events = _stage_compile_events(aot_dir)[len(cold):]
        cold_stages = {e["stage"] for e in events if not e["hit"]}
        warm_stages = {e["stage"] for e in events if e["hit"]}
        assert cold_stages == {0}
        assert warm_stages == {1}
        assert mt.stages[0].cache_misses == 3
        assert mt.stages[1].cache_misses == 0
        # per-stage keys carry stage index + chunk config + phase
        assert any("pp0of2v1fwd" in e["key"] for e in events)

    def test_warm_build_beats_cold_by_stage_count(self, aot_dir):
        """Per-stage warm load ≤ 1/P of the cold compile (acceptance
        bound, generous: measured ~16x on this host)."""
        import time

        t0 = time.monotonic()
        _mpmd()
        cold_s = time.monotonic() - t0
        t0 = time.monotonic()
        mt = _mpmd()
        warm_s = time.monotonic() - t0
        assert mt.cache_hit
        assert warm_s <= cold_s / 2

    def test_rebuild_stage_reloads_from_cache(self, aot_dir):
        mt = _mpmd()
        before = len(_stage_compile_events(aot_dir))
        prog = mt.rebuild_stage(1)
        events = _stage_compile_events(aot_dir)[before:]
        assert {e["stage"] for e in events} == {1}
        assert all(e["hit"] for e in events)
        assert prog.cache_misses == 0


class TestZeroSharding:
    def test_opt_state_shards_over_stage_data_axis(self, aot_dir):
        """ACCEPTANCE: optimizer-state bytes per device ÷data-axis vs
        replicated, with the adamw moments actually laid out
        P('data')."""
        mt = _mpmd(optax.adamw(1e-2))
        state = mt.init(jax.random.PRNGKey(0))
        from jax.sharding import PartitionSpec as P

        sharded_leaves = [
            leaf for leaf in jax.tree_util.tree_leaves(
                state.stages[0]["opt_state"])
            if leaf.sharding.spec == P("data")
        ]
        assert sharded_leaves, "no ZeRO-sharded moment leaves"
        for leaf in sharded_leaves:
            shard = leaf.addressable_shards[0].data
            assert shard.size * mt.data_size == leaf.size
        for s in range(mt.num_stages):
            by = mt.opt_bytes[s]
            # moments dominate: per-device bytes land near 1/data_size
            assert by["sharded"] < by["replicated"] / 2
        # params stay replicated (ZeRO-1: layout of the STATE only)
        for leaf in jax.tree_util.tree_leaves(state.stages[0]["params"]):
            assert leaf.sharding.spec == P()


class TestScheduleGate:
    def test_lm_head_heavy_config_prefers_mpmd(self):
        """Real configs are heterogeneous (stage 0 embeds, the last
        stage pays the LM-head matmul), so the cost-model gate picks
        MPMD over the lockstep roll."""
        kind, ests = choose_schedule(
            T.CONFIGS["gpt2-small"], num_stages=4, step_batch=32,
            seq=512,
        )
        assert kind == "mpmd"
        assert ests["mpmd"] < ests["spmd"]

    def test_deep_interleave_on_uniform_stages_keeps_spmd(self):
        """A deep interleaved roll on a near-uniform stage set beats
        plain-1F1B MPMD — the gate must keep SPMD there."""
        cfg = dataclasses.replace(
            T.CONFIGS["tiny"], n_layers=32, vocab_size=64, d_model=256)
        kind, ests = choose_schedule(
            cfg, num_stages=4, step_batch=8, seq=64, interleave=8,
        )
        assert kind == "spmd"
        assert ests["spmd"] <= ests["mpmd"]
