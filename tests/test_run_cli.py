"""Launcher auto-configuration (run.py) and accelerator sniffing.

Reference analog: ElasticLaunchConfig.auto_configure_params
(dlrover/python/elastic_agent/torch/training.py:143-157) — node count
from env, device count as the nproc-per-node analog, auto network check
at >=4 nodes. TPU twist under test: the device count must come from
kernel device nodes, never from initializing JAX in the launcher/agent
process (libtpu is exclusive-access).
"""

import os

import pytest

from dlrover_tpu.common.accelerator import sniff_accelerator
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.run import auto_configure, parse_args


def _args(*argv):
    return parse_args([*argv, "train.py"])


_KEYS = (EnvKey.NODE_NUM, EnvKey.ACCELERATOR,
         EnvKey.DEVICE_COUNT_OVERRIDE, EnvKey.INIT_TIMEOUT)


@pytest.fixture
def clean_env(monkeypatch):
    for key in _KEYS:
        monkeypatch.delenv(key, raising=False)
    yield monkeypatch
    # auto_configure writes os.environ directly; monkeypatch only
    # restores keys that existed before, so scrub the rest explicitly
    for key in _KEYS:
        os.environ.pop(key, None)


def _pci_dev(root, addr, vendor, pci_class):
    d = root / addr
    d.mkdir(parents=True)
    (d / "vendor").write_text(vendor + "\n")
    (d / "class").write_text(pci_class + "\n")


class TestSniffAccelerator:
    def test_accel_nodes_counted(self, tmp_path):
        for i in range(4):
            (tmp_path / f"accel{i}").touch()
        assert sniff_accelerator(str(tmp_path), str(tmp_path / "pci")) \
            == ("tpu", 4)

    def test_unreadable_sysfs_link_warns(self, tmp_path):
        """A /dev/accel node whose sysfs PCI link is unreadable falls
        back to the megacore default — with a warning naming the escape
        hatch, so a v2/v3 undercount is diagnosable from the log."""
        import logging

        records: list[logging.LogRecord] = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        # the repo's loggers set propagate=False, so capture directly
        logger = logging.getLogger("dlrover_tpu.common.accelerator")
        handler = _Capture(level=logging.WARNING)
        logger.addHandler(handler)
        try:
            (tmp_path / "accel0").touch()
            kind, count = sniff_accelerator(
                str(tmp_path), str(tmp_path / "pci"),
                str(tmp_path / "accel_class"),
            )
            assert (kind, count) == ("tpu", 1)
            assert any("DLROVER_TPU_DEVICE_COUNT" in r.getMessage()
                       for r in records)
            # a READABLE link stays quiet
            records.clear()
            d = tmp_path / "accel_class" / "accel0" / "device"
            d.mkdir(parents=True)
            (d / "device").write_text("0x005e\n")
            assert sniff_accelerator(
                str(tmp_path), str(tmp_path / "pci"),
                str(tmp_path / "accel_class"),
            ) == ("tpu", 1)
            assert not records
        finally:
            logger.removeHandler(handler)

    def test_sysfs_google_accelerators_counted(self, tmp_path):
        pci = tmp_path / "pci"
        _pci_dev(pci, "0000:00:01.0", "0x1ae0", "0x120000")
        _pci_dev(pci, "0000:00:02.0", "0x1ae0", "0x120000")
        # gVNIC shares Google's vendor id but is class 0x0200 (NIC):
        # it must NOT count as a chip
        _pci_dev(pci, "0000:00:03.0", "0x1ae0", "0x020000")
        # someone else's VFIO-bound accelerator must not count either
        _pci_dev(pci, "0000:00:04.0", "0x10de", "0x120000")
        assert sniff_accelerator(str(tmp_path), str(pci)) == ("tpu", 2)

    def test_v3_chips_count_two_cores_each(self, tmp_path):
        """TPU v2/v3 chips (PCI ids 0x0027/0x0037) carry two
        TensorCores — the count must use JAX-device semantics (4 chips
        -> 8 devices on a v3-8 host), matching jax.local_device_count."""
        accel_cls = tmp_path / "accel_class"
        for i in range(4):
            (tmp_path / f"accel{i}").touch()
            d = accel_cls / f"accel{i}" / "device"
            d.mkdir(parents=True)
            (d / "device").write_text("0x0037\n")
        assert sniff_accelerator(
            str(tmp_path), str(tmp_path / "pci"), str(accel_cls)
        ) == ("tpu", 8)

    def test_v4_chips_count_one_device_each(self, tmp_path):
        accel_cls = tmp_path / "accel_class"
        for i in range(4):
            (tmp_path / f"accel{i}").touch()
            d = accel_cls / f"accel{i}" / "device"
            d.mkdir(parents=True)
            (d / "device").write_text("0x005e\n")
        assert sniff_accelerator(
            str(tmp_path), str(tmp_path / "pci"), str(accel_cls)
        ) == ("tpu", 4)

    def test_bare_host_is_cpu(self, tmp_path):
        pci = tmp_path / "pci"
        _pci_dev(pci, "0000:00:03.0", "0x1ae0", "0x020000")  # gVNIC only
        assert sniff_accelerator(str(tmp_path), str(pci)) == ("cpu", 1)


class TestAutoConfigure:
    def test_nnodes_promoted_from_env(self, clean_env, tmp_path):
        clean_env.setenv(EnvKey.NODE_NUM, "4:8")
        args = _args()
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert args.nnodes == "4:8"

    def test_cli_nnodes_wins_over_env(self, clean_env, tmp_path):
        clean_env.setenv(EnvKey.NODE_NUM, "8")
        args = _args("--nnodes", "2")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert args.nnodes == "2"

    def test_device_count_exported_without_jax(self, clean_env, tmp_path):
        (tmp_path / "accel0").touch()
        (tmp_path / "accel1").touch()
        args = _args("--auto-config")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert os.environ[EnvKey.DEVICE_COUNT_OVERRIDE] == "2"
        assert os.environ[EnvKey.ACCELERATOR] == "tpu"

    def test_explicit_device_override_kept(self, clean_env, tmp_path):
        (tmp_path / "accel0").touch()
        clean_env.setenv(EnvKey.DEVICE_COUNT_OVERRIDE, "7")
        args = _args("--auto-config")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert os.environ[EnvKey.DEVICE_COUNT_OVERRIDE] == "7"

    def test_network_check_auto_on_at_4_nodes(self, clean_env, tmp_path):
        args = _args("--auto-config", "--nnodes", "4")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert args.network_check

    def test_network_check_stays_off_small(self, clean_env, tmp_path):
        args = _args("--auto-config", "--nnodes", "2")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert not args.network_check

    def test_init_timeout_scales_with_fleet(self, clean_env, tmp_path):
        args = _args("--auto-config", "--nnodes", "512")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert int(os.environ[EnvKey.INIT_TIMEOUT]) == 300 + (512 - 64)

    def test_gated_off_without_flag(self, clean_env, tmp_path):
        (tmp_path / "accel0").touch()
        args = _args("--nnodes", "8")
        auto_configure(args, dev_root=str(tmp_path), sys_pci_root=str(tmp_path / 'pci'))
        assert EnvKey.DEVICE_COUNT_OVERRIDE not in os.environ
        assert not args.network_check
        assert EnvKey.INIT_TIMEOUT not in os.environ
