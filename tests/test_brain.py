"""Brain service: datastore persistence + optimize algorithms + client.

Reference analog: the Go brain's optalgorithm table tests
(dlrover/go/brain/pkg/optimizer/implementation/optalgorithm/*_test.go).
"""

from __future__ import annotations

import time

import pytest

from dlrover_tpu.brain.service import (
    BrainClient,
    BrainDataStore,
    BrainService,
)
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.messages import BrainJobMetrics
from dlrover_tpu.master.resource_optimizer import (
    LocalResourceOptimizer,
    OptimizerConfig,
)
from dlrover_tpu.master.stats import LocalStatsReporter


@pytest.fixture
def brain():
    service = BrainService()
    service.start()
    client = BrainClient(service.addr)
    yield service, client
    client.close()
    service.stop()


def _job(name, workers, mem, speed, status="succeeded", sig="llama-7b"):
    return BrainJobMetrics(
        job_name=name, signature=sig, workers=workers,
        used_memory_mb=mem, steps_per_s=speed, status=status,
    )


class TestBrainService:
    def test_no_history_not_found(self, brain):
        _, client = brain
        assert not client.optimize("j", "unknown-sig").found

    def test_create_plan_from_history(self, brain):
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=2.0))
        client.report(_job("b", workers=8, mem=10000, speed=6.0))
        client.report(_job("c", workers=8, mem=12000, speed=1.0,
                           status="failed"))
        plan = client.optimize("new", "llama-7b")
        assert plan.found
        # fastest per-worker successful run had 8 workers (6/8 > 2/4)
        assert plan.workers == 8
        # 1.5x median successful memory (median of 8000, 10000)
        assert plan.memory_mb == int(1.5 * 9000)
        assert plan.based_on_jobs == 2

    def test_oom_plan_doubles_peak(self, brain):
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=2.0,
                           status="oom"))
        plan = client.optimize("a", "llama-7b", stage="oom")
        assert plan.found and plan.memory_mb == 16000

    def test_create_oom_plan_starts_above_history(self, brain):
        """create_oom (OptimizeJobWorkerCreateOomResource analog): a
        signature with OOM kills in its history gets a create-stage plan
        at 2x the all-time peak, with the fastest successful run's
        worker count; no OOM history -> found=False (fall back to
        create)."""
        _, client = brain
        client.report(_job("a", workers=4, mem=9000, speed=2.0,
                           status="oom"))
        client.report(_job("b", workers=8, mem=8000, speed=6.0))
        plan = client.optimize("new", "llama-7b", stage="create_oom")
        assert plan.found
        assert plan.memory_mb == 2 * 9000
        assert plan.workers == 8
        # clean-history signature: not this algorithm's business
        client.report(_job("c", workers=4, mem=800, speed=1.0,
                           sig="clean-sig"))
        assert not client.optimize(
            "new2", "clean-sig", stage="create_oom").found

    def test_running_plan_picks_scaling_knee(self, brain):
        """Worker counts past the throughput knee add cost, not speed:
        the running-stage plan picks the smallest count within 90% of
        the best median throughput."""
        _, client = brain
        # 4 workers: 10 steps/s; 8 workers: 19; 16 workers: 19.5
        # (scaling flattens past 8)
        client.report(_job("a", workers=4, mem=8000, speed=10.0))
        client.report(_job("b", workers=8, mem=8000, speed=19.0))
        client.report(_job("c", workers=16, mem=9000, speed=19.5))
        plan = client.optimize("j", "llama-7b", stage="running")
        assert plan.found
        assert plan.workers == 8
        # right-sized memory: 1.2x the peak ever observed
        assert plan.memory_mb == int(1.2 * 9000)
        assert plan.based_on_jobs == 3

    def test_running_plan_without_throughput_not_found(self, brain):
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=0.0))
        assert not client.optimize(
            "j", "llama-7b", stage="running"
        ).found

    def test_latest_record_per_job_wins(self, brain):
        _, client = brain
        client.report(_job("a", workers=2, mem=4000, speed=1.0,
                           status="running"))
        client.report(_job("a", workers=2, mem=6000, speed=1.5))
        plan = client.optimize("new", "llama-7b")
        assert plan.based_on_jobs == 1
        assert plan.memory_mb == int(1.5 * 6000)

    def test_running_knee_ignores_doomed_configs(self, brain):
        """A worker count that only ever reported throughput before
        crashing must not win the knee."""
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=10.0))
        client.report(_job("b", workers=16, mem=8000, speed=20.0,
                           status="oom"))
        plan = client.optimize("j", "llama-7b", stage="running")
        assert plan.found
        assert plan.workers == 4

    def test_sqlite_persistence_across_restart(self, tmp_path):
        db = str(tmp_path / "brain.sqlite")
        s1 = BrainService(BrainDataStore(db))
        s1.start()
        BrainClient(s1.addr).report(
            _job("a", workers=4, mem=8000, speed=2.0)
        )
        s1.stop()
        s2 = BrainService(BrainDataStore(db))
        s2.start()
        try:
            plan = BrainClient(s2.addr).optimize("new", "llama-7b")
            assert plan.found and plan.workers == 4
        finally:
            s2.stop()


class TestOptimizerBrainIntegration:
    def test_initial_plan_applies_oom_history_memory(self, brain):
        """A signature whose ENTIRE history OOM-killed (no successful
        run to vote a worker count) must still launch with the 2x-peak
        memory bump on every planned node — losing the sizing here is
        exactly the OOM->relaunch loop create_oom exists to break."""
        _, client = brain
        client.report(_job("a", workers=4, mem=9000, speed=2.0,
                           status="oom"))
        opt = LocalResourceOptimizer(
            OptimizerConfig(min_workers=1, max_workers=4),
            LocalStatsReporter(), None,
            brain=client, signature="llama-7b",
        )
        plan = opt.initial_plan()
        assert plan.replica_resources == {"worker": 4}
        # seeded up to max_workers so later scale-ups inherit the sizing
        assert plan.memory_mb == {str(i): 18000 for i in range(4)}
        # the grant is also the oom-recovery baseline: a later OOM with
        # low observed usage must RAISE memory from 18000, not shrink it
        recovery = opt.oom_recovery_plan(node_id=1)
        assert recovery.memory_mb["1"] >= 2 * 18000

    def test_create_oom_declines_without_usage_numbers(self, brain):
        """When NO row of the signature recorded usage (all-time peak
        0), create_oom must decline rather than emit an all-zero plan
        that would shadow the create stage's worker vote."""
        _, client = brain
        client.report(_job("a", workers=4, mem=0, speed=1.0,
                           status="oom"))
        client.report(_job("b", workers=8, mem=0, speed=6.0))
        assert not client.optimize(
            "new", "llama-7b", stage="create_oom").found
        opt = LocalResourceOptimizer(
            OptimizerConfig(min_workers=1, max_workers=8),
            LocalStatsReporter(), None,
            brain=client, signature="llama-7b",
        )
        plan = opt.initial_plan()
        # falls through to create: worker vote survives, no memory seed
        assert plan.replica_resources == {"worker": 8}
        assert plan.memory_mb == {}

    def test_create_oom_uses_successful_peak_when_oom_unmetered(self,
                                                                brain):
        """OOM rows without usage numbers still trigger the stage as
        long as SOME row metered usage: 2x the all-time peak beats the
        create stage's 1.5x-median for an OOM-scarred signature."""
        _, client = brain
        client.report(_job("a", workers=4, mem=0, speed=1.0,
                           status="oom"))
        client.report(_job("b", workers=8, mem=8000, speed=6.0))
        plan = client.optimize("new", "llama-7b", stage="create_oom")
        assert plan.found and plan.memory_mb == 16000 and plan.workers == 8

    def test_speed_plan_capped_by_brain_knee(self, brain):
        """The local scale-up heuristic defers to the cross-job scaling
        knee: history says 8 workers is where throughput flattens."""
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=10.0))
        client.report(_job("b", workers=8, mem=8000, speed=19.0))
        client.report(_job("c", workers=16, mem=9000, speed=19.5))

        class Speed:
            def running_speed(self):
                return 5.0  # below target: heuristic alone would grow

        opt = LocalResourceOptimizer(
            OptimizerConfig(min_workers=1, max_workers=32,
                            target_steps_per_s=50.0),
            LocalStatsReporter(), Speed(),
            brain=client, signature="llama-7b",
        )
        plan = opt.speed_plan(current_workers=16)
        assert plan.replica_resources == {"worker": 8}
        assert "knee" in plan.reason

    def test_initial_plan_uses_history_clamped(self, brain):
        _, client = brain
        client.report(_job("a", workers=16, mem=8000, speed=10.0))

        class Speed:
            def running_speed(self):
                return 0.0

        opt = LocalResourceOptimizer(
            OptimizerConfig(min_workers=1, max_workers=8),
            LocalStatsReporter(), Speed(),
            brain=client, signature="llama-7b",
        )
        plan = opt.initial_plan()
        assert plan.replica_resources == {"worker": 8}  # clamped
        assert "brain" in plan.reason

    def test_oom_plan_takes_brain_max(self, brain):
        _, client = brain
        client.report(_job("a", workers=4, mem=50000, speed=2.0,
                           status="oom"))

        class Speed:
            def running_speed(self):
                return 0.0

        opt = LocalResourceOptimizer(
            OptimizerConfig(host_memory_mb=4096, max_workers=4),
            LocalStatsReporter(), Speed(),
            brain=client, signature="llama-7b",
        )
        plan = opt.oom_recovery_plan(0)
        assert plan.memory_mb["0"] == 100000  # brain's 2x peak wins


def _hbm_job(name, hbm, mem=4000, status="running", sig="tpu-sig"):
    return BrainJobMetrics(
        job_name=name, signature=sig, workers=4, used_memory_mb=mem,
        used_hbm_mb=hbm, steps_per_s=1.0, status=status,
    )


class TestColdCreate:
    """Reference: OptimizeJobPSColdCreateResource — a never-seen
    signature gets the cluster-wide prior, not a not-found."""

    def test_empty_store_not_found(self, brain):
        _, client = brain
        assert not client.optimize("j", "new-sig",
                                   stage="cold_create").found

    def test_cluster_prior_from_other_signatures(self, brain):
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=2.0,
                           sig="sig-a"))
        client.report(_job("b", workers=8, mem=10000, speed=3.0,
                           sig="sig-b"))
        client.report(_job("c", workers=16, mem=20000, speed=3.0,
                           sig="sig-c"))
        plan = client.optimize("fresh", "never-seen-sig",
                               stage="cold_create")
        assert plan.found
        assert plan.workers == 8                    # cluster median
        assert plan.memory_mb == int(1.3 * 20000)   # p90 + 30% margin
        assert plan.based_on_jobs == 3

    def test_failed_jobs_do_not_shape_the_prior(self, brain):
        _, client = brain
        client.report(_job("a", workers=4, mem=8000, speed=2.0,
                           sig="sig-a"))
        client.report(_job("bad", workers=64, mem=90000, speed=0.1,
                           status="failed", sig="sig-b"))
        plan = client.optimize("fresh", "never-seen",
                               stage="cold_create")
        assert plan.found
        assert plan.workers == 4
        assert plan.memory_mb == int(1.3 * 8000)


class TestResourceUtil:
    """Reference: OptimizeJobPSResourceUtil — shrink over-provisioned
    jobs; TPU twist: HBM right-sizing rides alongside host memory."""

    CASES = [
        # (peak_used, requested, expect_found, expect_mb)
        pytest.param(4000, 16000, True, int(1.3 * 4000),
                     id="heavily-overprovisioned-shrinks"),
        pytest.param(9900, 16000, False, 0,
                     id="above-60pct-keeps"),
        pytest.param(0, 16000, False, 0, id="no-history-keeps"),
        pytest.param(4000, 0, False, 0, id="no-request-info-keeps"),
    ]

    @pytest.mark.parametrize("peak,requested,found,mb", CASES)
    def test_memory_table(self, brain, peak, requested, found, mb):
        _, client = brain
        if peak:
            client.report(_hbm_job("a", hbm=0, mem=peak, sig="s"))
        from dlrover_tpu.common.messages import BrainOptimizeRequest

        plan = client._client.call(BrainOptimizeRequest(
            job_name="a", signature="s", stage="util",
            requested_memory_mb=requested,
        ))
        assert plan.found is found
        assert plan.memory_mb == mb

    def test_hbm_rightsizing(self, brain):
        _, client = brain
        client.report(_hbm_job("a", hbm=3000, sig="s"))
        client.report(_hbm_job("a", hbm=5000, sig="s"))
        from dlrover_tpu.common.messages import BrainOptimizeRequest

        plan = client._client.call(BrainOptimizeRequest(
            job_name="a", signature="s", stage="util",
            requested_hbm_mb=16000,
        ))
        assert plan.found
        assert plan.hbm_mb == int(1.3 * 5000)   # all-time peak, not last
        assert plan.memory_mb == 0              # memory not requested

    def test_util_never_grows(self, brain):
        _, client = brain
        client.report(_hbm_job("a", hbm=15000, mem=15000, sig="s"))
        from dlrover_tpu.common.messages import BrainOptimizeRequest

        plan = client._client.call(BrainOptimizeRequest(
            job_name="a", signature="s", stage="util",
            requested_memory_mb=16000, requested_hbm_mb=16000,
        ))
        assert not plan.found


class TestInitAdjustStage:
    """OptimizeJobPSInitAdjustResource analog: early self-correction."""

    def _seed(self, svc, mems):
        for i, mem in enumerate(mems):
            svc.store.record(m.BrainJobMetrics(
                job_name="j1", signature="sigA", workers=2,
                used_memory_mb=mem, steps_per_s=1.0, status="running",
                timestamp=100.0 + i,
            ))

    def test_undersized_guess_grows(self):
        svc = BrainService()
        self._seed(svc, [4000, 7000])
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="init_adjust",
            requested_memory_mb=8000))
        assert plan.found and plan.memory_mb == 10500  # 1.5 * own peak

    def test_oversized_guess_shrinks(self):
        svc = BrainService()
        self._seed(svc, [1000, 1100])
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="init_adjust",
            requested_memory_mb=16000))
        assert plan.found and plan.memory_mb == 1650

    def test_close_enough_stays(self):
        svc = BrainService()
        self._seed(svc, [6000])
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="init_adjust",
            requested_memory_mb=9500))  # target 9000, within 20%
        assert not plan.found

    def test_other_jobs_history_not_used(self):
        svc = BrainService()
        svc.store.record(m.BrainJobMetrics(
            job_name="OTHER", signature="sigA", workers=2,
            used_memory_mb=50000, status="running", timestamp=1.0))
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="init_adjust",
            requested_memory_mb=8000))
        assert not plan.found  # j1 itself has no samples yet


class TestHotNodeStage:
    """OptimizeJobHotPSResource analog: per-node grants."""

    def test_hot_node_gets_grant(self):
        svc = BrainService()
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="hot",
            node_memory_mb={"0": 4000, "1": 4100, "2": 4050,
                            "3": 9000}))
        assert plan.found
        assert plan.node_memory_mb == {"3": 13500}

    def test_balanced_job_no_plan(self):
        svc = BrainService()
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="hot",
            node_memory_mb={"0": 4000, "1": 4200, "2": 4100}))
        assert not plan.found

    def test_too_few_nodes_no_plan(self):
        svc = BrainService()
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigA", stage="hot",
            node_memory_mb={"0": 1000, "1": 9000}))
        assert not plan.found


class TestNewStagesOverRpc:
    """init_adjust/hot must be reachable through the CLIENT API (the
    path the master actually uses), not just direct service calls."""

    def test_round_trip(self):
        from dlrover_tpu.brain.service import BrainClient

        svc = BrainService()
        svc.start()
        try:
            client = BrainClient(svc.addr)
            client.report(m.BrainJobMetrics(
                job_name="j9", signature="sigR", workers=2,
                used_memory_mb=7000, status="running", timestamp=1.0))
            adj = client.optimize("j9", "sigR", "init_adjust",
                                  requested_memory_mb=4000)
            assert adj.found and adj.memory_mb == 10500
            hot = client.optimize("j9", "sigR", "hot", node_memory_mb={
                "0": 4000, "1": 4100, "2": 4050, "3": 9000})
            assert hot.found and hot.node_memory_mb == {"3": 13500}
            client.close()
        finally:
            svc.stop()


class TestInitAdjustHbm:
    def test_hbm_adjusts_independently(self):
        svc = BrainService()
        svc.store.record(m.BrainJobMetrics(
            job_name="j1", signature="sigH", workers=2,
            used_memory_mb=0, used_hbm_mb=9000, status="running",
            timestamp=1.0))
        plan = svc.optimize(m.BrainOptimizeRequest(
            job_name="j1", signature="sigH", stage="init_adjust",
            requested_hbm_mb=8000))
        assert plan.found and plan.hbm_mb == 13500
        assert plan.memory_mb == 0


class TestTuningPlanIntegration:
    """The master path for the new stages: optimizer.tuning_plan()
    consults the Brain with real inputs and emits per-node memory."""

    class _Stats:
        def __init__(self, usage):
            self._usage = usage

        def latest(self):
            import types

            return {
                nid: types.SimpleNamespace(used_memory_mb=mem)
                for nid, mem in self._usage.items()
            }

    def test_init_adjust_and_hot_reach_brain(self):
        from dlrover_tpu.brain.service import BrainClient
        from dlrover_tpu.master.resource_optimizer import (
            LocalResourceOptimizer,
            OptimizerConfig,
        )

        svc = BrainService()
        svc.start()
        try:
            client = BrainClient(svc.addr)
            client.report(m.BrainJobMetrics(
                job_name="jT", signature="sigT", workers=4,
                used_memory_mb=7000, status="running", timestamp=1.0))
            opt = LocalResourceOptimizer(
                OptimizerConfig(min_workers=1, max_workers=4,
                                host_memory_mb=4000),
                self._Stats({0: 4000, 1: 4100, 2: 4050, 3: 9000}),
                speed_monitor=None, brain=client,
                signature="sigT", job_name="jT",
            )
            plan = opt.tuning_plan()
            # init_adjust: 1.5 * 7000 = 10500 for every node...
            assert plan.memory_mb["0"] == 10500
            # ...except the hot node, whose grant wins
            assert plan.memory_mb["3"] == 13500
            client.close()
        finally:
            svc.stop()

    def test_no_brain_empty_plan(self):
        from dlrover_tpu.master.resource_optimizer import (
            LocalResourceOptimizer,
            OptimizerConfig,
        )

        opt = LocalResourceOptimizer(
            OptimizerConfig(host_memory_mb=4000),
            self._Stats({0: 1000}), speed_monitor=None,
        )
        assert opt.tuning_plan().is_empty()


class TestClusterMonitor:
    """Brain's own k8s observation (brain/cluster_monitor.py) — the
    go/brain platform-watcher + k8smonitor analog, driven against the
    real HTTP envtest apiserver."""

    def test_watch_ingests_lifecycle_and_oom(self):
        from dlrover_tpu.brain.cluster_monitor import ClusterMonitor
        from dlrover_tpu.brain.service import BrainDataStore
        from dlrover_tpu.cluster.envtest import FakeKubeApiServer
        from dlrover_tpu.cluster.kube_client import KubernetesClient

        srv = FakeKubeApiServer().start()
        client = KubernetesClient(srv.url, watch_timeout_s=2.0)
        store = BrainDataStore()
        monitor = ClusterMonitor(client, store,
                                 resync_interval_s=0.5).start()
        try:
            client.create_pod("default", {
                "metadata": {"name": "job1-worker-0",
                             "labels": {"app": "dlrover-tpu",
                                        "job": "job1",
                                        "group": "worker"}},
                "spec": {},
            })
            deadline = time.time() + 15
            while time.time() < deadline:
                if store.cluster_job_pods("job1"):
                    break
                time.sleep(0.2)
            pods = store.cluster_job_pods("job1")
            assert pods and pods[0][0] == "job1-worker-0"

            # kubelet-style status patch: OOMKilled must be ingested
            client._request(
                "PATCH", "/api/v1/namespaces/default/pods/job1-worker-0",
                body={"status": {"phase": "Failed",
                                 "reason": "OOMKilled"}},
            )
            deadline = time.time() + 15
            while time.time() < deadline:
                if store.cluster_oom_count("job1"):
                    break
                time.sleep(0.2)
            assert store.cluster_oom_count("job1") == 1
        finally:
            monitor.stop()
            client.close()
            srv.stop()
            store.close()

    def test_cluster_oom_feeds_create_oom_stage(self):
        """A job whose master never self-reported OOM still drives the
        create_oom sizing when the cluster watched its pod die."""
        from dlrover_tpu.brain.service import BrainDataStore, BrainService

        store = BrainDataStore()
        service = BrainService(store=store)
        # the job reported ordinary usage rows (status running), never oom
        store.record(m.BrainJobMetrics(
            job_name="j-oom", signature="sig-c", workers=4,
            used_memory_mb=9000, status="running",
        ))
        store.record_cluster_event(
            job_name="j-oom", pod="j-oom-worker-1", group="worker",
            event="MODIFIED", phase="Failed", oom=True,
        )
        plan = service.optimize(m.BrainOptimizeRequest(
            job_name="new", signature="sig-c", stage="create_oom",
        ))
        assert plan.found
        assert plan.memory_mb == 2 * 9000

    def test_no_oom_evidence_declines(self):
        from dlrover_tpu.brain.service import BrainDataStore, BrainService

        store = BrainDataStore()
        service = BrainService(store=store)
        store.record(m.BrainJobMetrics(
            job_name="j-ok", signature="sig-d", workers=4,
            used_memory_mb=9000, status="running",
        ))
        plan = service.optimize(m.BrainOptimizeRequest(
            job_name="new", signature="sig-d", stage="create_oom",
        ))
        assert not plan.found
