"""Sharded embedding service (embedding/service.py) — the elastic-PS
analog: key-space partition, trainer fan-out, elastic re-shard with row
migration, sharded delta checkpoints.

Reference: dlrover elastic_ps.py:82 (version-bumped PS cluster),
tfplus hybrid_embedding/table_manager.h (sharded sparse storage).
"""

import threading
import time

import numpy as np
import pytest

from dlrover_tpu.embedding.kv_table import KvEmbeddingTable
from dlrover_tpu.embedding.service import (
    EmbeddingCoordinator,
    EmbeddingShardServer,
    ShardedKvClient,
    decode_msg,
    encode_msg,
    shard_owner,
)

DIM = 8


@pytest.fixture
def cluster(tmp_path):
    """Two shard servers + coordinator + client; yields a dict so tests
    can grow/shrink the ring."""
    servers = [
        EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
            index=i, num_shards=2, ckpt_dir=str(tmp_path / "ckpt"),
        ).start()
        for i in range(2)
    ]
    addrs = [f"127.0.0.1:{s.port}" for s in servers]
    coord = EmbeddingCoordinator(addrs, host="127.0.0.1").start()
    client = ShardedKvClient(
        coordinator_addr=f"127.0.0.1:{coord.port}", dim=DIM
    )
    state = {"servers": servers, "coord": coord, "client": client,
             "tmp_path": tmp_path}
    yield state
    client.close()
    coord.stop()
    for s in state["servers"]:
        s.stop()


def _seed_rows(servers, keys, values):
    """Place known rows on their owner shards (slots zeroed)."""
    n = len(servers)
    owners = shard_owner(keys, n)
    for i, srv in enumerate(servers):
        sel = owners == i
        if sel.any():
            srv.table.import_({
                "keys": keys[sel],
                "values": values[sel],
                "slots": np.zeros(
                    (int(sel.sum()), 2 * DIM), np.float32
                ),
                "freq": np.ones(int(sel.sum()), np.uint32),
            })


class TestProtocol:
    def test_msg_roundtrip(self):
        arrays = {
            "ids": np.arange(5, dtype=np.int64),
            "vals": np.random.default_rng(0).standard_normal(
                (5, 3)).astype(np.float32),
        }
        op, meta, out = decode_msg(
            encode_msg("lookup", {"v": 3}, arrays)
        )
        assert op == "lookup" and meta == {"v": 3}
        np.testing.assert_array_equal(out["ids"], arrays["ids"])
        np.testing.assert_array_equal(out["vals"], arrays["vals"])

    def test_shard_owner_stable_and_balanced(self):
        ids = np.arange(100_000, dtype=np.int64)
        o3 = shard_owner(ids, 3)
        # deterministic
        np.testing.assert_array_equal(o3, shard_owner(ids, 3))
        # contiguous id ranges spread across shards (mixing works)
        counts = np.bincount(o3, minlength=3)
        assert counts.min() > 25_000
        # hot contiguous block does not land on one shard
        assert len(np.unique(shard_owner(ids[:100], 3))) == 3


class TestShardedOps:
    def test_lookup_matches_seeded_rows(self, cluster):
        keys = np.arange(0, 500, dtype=np.int64)
        vals = np.random.default_rng(1).standard_normal(
            (keys.size, DIM)).astype(np.float32)
        _seed_rows(cluster["servers"], keys, vals)
        got = cluster["client"].lookup(keys, init_missing=False)
        np.testing.assert_allclose(got, vals, rtol=0, atol=0)

    def test_apply_matches_local_table(self, cluster):
        """Sharded Adam == single-table Adam on identical rows."""
        keys = np.arange(100, dtype=np.int64)
        vals = np.random.default_rng(2).standard_normal(
            (keys.size, DIM)).astype(np.float32)
        _seed_rows(cluster["servers"], keys, vals)
        local = KvEmbeddingTable(dim=DIM, num_slots=2, seed=99)
        local.import_({
            "keys": keys, "values": vals,
            "slots": np.zeros((keys.size, 2 * DIM), np.float32),
            "freq": np.ones(keys.size, np.uint32),
        })
        rng = np.random.default_rng(3)
        for step in range(1, 4):
            grads = rng.standard_normal(
                (keys.size, DIM)).astype(np.float32)
            cluster["client"].apply("adam", keys, grads, lr=1e-2,
                                    step=step)
            local.apply_adam(keys, grads, lr=1e-2, step=step)
        got = cluster["client"].lookup(keys, init_missing=False)
        np.testing.assert_allclose(
            got, local.lookup(keys, init_missing=False),
            rtol=1e-6, atol=1e-7,
        )

    def test_batched_shapes(self, cluster):
        ids = np.arange(24, dtype=np.int64).reshape(4, 6)
        out = cluster["client"].lookup(ids)
        assert out.shape == (4, 6, DIM)


class TestElasticReshard:
    def _snapshot(self, client):
        snap = client.export_all()
        order = np.argsort(snap["keys"])
        return {k: v[order] for k, v in snap.items()}

    def test_scale_up_preserves_rows_and_values(self, cluster):
        keys = np.arange(2000, dtype=np.int64)
        vals = np.random.default_rng(4).standard_normal(
            (keys.size, DIM)).astype(np.float32)
        _seed_rows(cluster["servers"], keys, vals)
        # give rows nonzero optimizer slots so slot migration is tested
        g = np.ones((keys.size, DIM), np.float32)
        cluster["client"].apply("adam", keys, g, lr=1e-3, step=1)
        before = self._snapshot(cluster["client"])

        new_srv = EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1", index=2,
            num_shards=3,
            ckpt_dir=str(cluster["tmp_path"] / "ckpt"),
        ).start()
        cluster["servers"].append(new_srv)
        addrs = [f"127.0.0.1:{s.port}" for s in cluster["servers"]]
        cluster["coord"].scale(addrs)

        # every shard now holds exactly its hash partition
        for i, srv in enumerate(cluster["servers"]):
            srv_keys = srv.table.export()["keys"]
            if srv_keys.size:
                assert (shard_owner(srv_keys, 3) == i).all()
        assert new_srv.table.export()["keys"].size > 0  # rows moved

        cluster["client"].refresh_route()
        after = self._snapshot(cluster["client"])
        np.testing.assert_array_equal(before["keys"], after["keys"])
        np.testing.assert_allclose(before["values"], after["values"],
                                   rtol=0, atol=0)
        np.testing.assert_allclose(before["slots"], after["slots"],
                                   rtol=0, atol=0)
        # training continues post-reshard
        cluster["client"].apply("adam", keys, g, lr=1e-3, step=2)
        assert cluster["client"].row_count() == keys.size

    def test_scale_down_drains_departing_server(self, cluster):
        # grow to 3 first, then shrink back to 2
        keys = np.arange(1500, dtype=np.int64)
        vals = np.random.default_rng(5).standard_normal(
            (keys.size, DIM)).astype(np.float32)
        _seed_rows(cluster["servers"], keys, vals)
        third = EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
        ).start()
        cluster["servers"].append(third)
        all_addrs = [f"127.0.0.1:{s.port}" for s in cluster["servers"]]
        cluster["coord"].scale(all_addrs)
        cluster["client"].refresh_route()
        before = self._snapshot(cluster["client"])
        assert len(third.table) > 0

        cluster["coord"].scale(all_addrs[:2])
        assert len(third.table) == 0  # fully drained
        cluster["client"].refresh_route()
        after = self._snapshot(cluster["client"])
        np.testing.assert_array_equal(before["keys"], after["keys"])
        np.testing.assert_allclose(before["values"], after["values"],
                                   rtol=0, atol=0)

    def test_stale_client_rerouted_mid_training(self, cluster):
        """A client that raced the scale keeps training: version errors
        trigger a route refresh + retry, no updates lost."""
        keys = np.arange(800, dtype=np.int64)
        _seed_rows(cluster["servers"], keys,
                   np.zeros((keys.size, DIM), np.float32))
        stop = threading.Event()
        applied = []
        errors = []

        def trainer():
            step = 0
            while not stop.is_set():
                step += 1
                try:
                    cluster["client"].apply(
                        "adam", keys,
                        np.ones((keys.size, DIM), np.float32),
                        lr=1e-3, step=step,
                    )
                    applied.append(step)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return
                time.sleep(0.01)

        t = threading.Thread(target=trainer, daemon=True)
        t.start()
        time.sleep(0.3)
        new_srv = EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
        ).start()
        cluster["servers"].append(new_srv)
        addrs = [f"127.0.0.1:{s.port}" for s in cluster["servers"]]
        cluster["coord"].scale(addrs)
        time.sleep(0.5)
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive()
        assert not errors, errors[:1]
        n_before = len(applied)
        assert n_before >= 2
        assert cluster["client"].row_count() == keys.size


class TestFailureAtomicScale:
    """Two-phase scale (r04 verdict ask 3 / advisor findings): a
    destination dying mid-scale must lose zero rows, never resurrect
    fresh rows over trained values, and a retried scale must converge."""

    def _snapshot(self, client):
        snap = client.export_all()
        order = np.argsort(snap["keys"])
        return {k: v[order] for k, v in snap.items()}

    def _train_rows(self, cluster, n=1200):
        keys = np.arange(n, dtype=np.int64)
        _seed_rows(cluster["servers"], keys,
                   np.random.default_rng(11).standard_normal(
                       (n, DIM)).astype(np.float32))
        cluster["client"].apply(
            "adam", keys, np.ones((n, DIM), np.float32),
            lr=1e-3, step=1)
        return keys

    @pytest.mark.timeout(120)
    def test_dead_destination_aborts_with_zero_loss(self, cluster):
        keys = self._train_rows(cluster)
        before = self._snapshot(cluster["client"])

        # destination is dead before the copy phase even starts
        dead = EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1").start()
        dead_addr = f"127.0.0.1:{dead.port}"
        dead.stop()
        addrs = [f"127.0.0.1:{s.port}" for s in cluster["servers"]]
        with pytest.raises(Exception):
            cluster["coord"].scale(addrs + [dead_addr],
                                   migrate_retries=2,
                                   retry_backoff_s=0.05)
        # route unchanged, servers re-opened at the old epoch
        assert cluster["coord"].version == 0
        assert cluster["coord"].addrs == addrs
        for srv in cluster["servers"]:
            assert not srv._migrating

        # zero loss AND no resurrection: lookups with init_missing=True
        # must return the TRAINED values, not fresh inits
        got = cluster["client"].lookup(keys, init_missing=True)
        np.testing.assert_allclose(got, before["values"], atol=0)
        after = self._snapshot(cluster["client"])
        np.testing.assert_array_equal(before["keys"], after["keys"])
        np.testing.assert_allclose(before["slots"], after["slots"],
                                   atol=0)

        # a retried scale with a LIVE replacement converges exactly
        live = EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1").start()
        cluster["servers"].append(live)
        cluster["coord"].scale(addrs + [f"127.0.0.1:{live.port}"])
        assert cluster["coord"].version == 1
        assert len(live.table) > 0
        cluster["client"].refresh_route()
        final = self._snapshot(cluster["client"])
        np.testing.assert_array_equal(before["keys"], final["keys"])
        np.testing.assert_allclose(before["values"], final["values"],
                                   atol=0)
        np.testing.assert_allclose(before["slots"], final["slots"],
                                   atol=0)

    @pytest.mark.timeout(120)
    def test_mid_copy_failure_then_retry_no_stale_overwrite(
            self, cluster):
        """Destination fails AFTER receiving part of the copy; the
        retried scale must overwrite those partial (now stale) copies
        with the authoritative rows — including rows the trainer
        updated between the failure and the retry."""
        keys = self._train_rows(cluster)

        flaky_fail = {"n": 2}  # fail the first two import pushes
        real_handle = EmbeddingShardServer._handle

        class FlakyServer(EmbeddingShardServer):
            def _handle(self, op, meta, arrays):
                if op == "import_rows" and flaky_fail["n"] > 0:
                    flaky_fail["n"] -= 1
                    # accept the rows, THEN fail: the pusher sees an
                    # error for rows the table already holds — the
                    # worst case for stale-copy correctness
                    real_handle(self, op, meta, arrays)
                    raise ConnectionError("dest died mid-import")
                return real_handle(self, op, meta, arrays)

        flaky = FlakyServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1").start()
        cluster["servers"].append(flaky)
        addrs = [f"127.0.0.1:{s.port}" for s in cluster["servers"]]
        with pytest.raises(Exception):
            cluster["coord"].scale(addrs, migrate_retries=1,
                                   retry_backoff_s=0.05)
        assert cluster["coord"].version == 0
        # flaky now holds PARTIAL stale copies; train more so the
        # authoritative rows diverge from those copies
        cluster["client"].apply(
            "adam", keys, np.full((keys.size, DIM), 2.0, np.float32),
            lr=1e-3, step=2)
        before = self._snapshot(cluster["client"])

        cluster["coord"].scale(addrs)  # retry converges
        assert cluster["coord"].version == 1
        cluster["client"].refresh_route()
        after = self._snapshot(cluster["client"])
        np.testing.assert_array_equal(before["keys"], after["keys"])
        np.testing.assert_allclose(before["values"], after["values"],
                                   atol=0)
        np.testing.assert_allclose(before["slots"], after["slots"],
                                   atol=0)
        # every shard holds exactly its partition (stale copies pruned)
        for i, srv in enumerate(cluster["servers"]):
            srv_keys = srv.table.export()["keys"]
            if srv_keys.size:
                assert (shard_owner(srv_keys, len(addrs)) == i).all()

    @pytest.mark.timeout(120)
    def test_route_served_during_scale(self, cluster):
        """`route` must answer from the short-hold snapshot lock while a
        scale is mid-flight (advisor: the scale-spanning lock starved
        route requests past the client timeout)."""
        self._train_rows(cluster, n=400)
        release = threading.Event()
        real_migrate = EmbeddingShardServer.migrate_to

        def slow_migrate(srv, *a, **kw):
            release.wait(timeout=30)
            return real_migrate(srv, *a, **kw)

        cluster["servers"][0].migrate_to = (
            lambda *a, **kw: slow_migrate(cluster["servers"][0],
                                          *a, **kw))
        new_srv = EmbeddingShardServer(
            dim=DIM, num_slots=2, seed=7, host="127.0.0.1").start()
        cluster["servers"].append(new_srv)
        addrs = [f"127.0.0.1:{s.port}" for s in cluster["servers"]]
        t = threading.Thread(
            target=cluster["coord"].scale, args=(addrs,), daemon=True)
        t.start()
        time.sleep(0.2)  # scale is now blocked inside migrate
        t0 = time.monotonic()
        cluster["client"].refresh_route()  # must NOT block on the scale
        assert time.monotonic() - t0 < 5.0
        assert cluster["client"].version == 0  # pre-flip route
        release.set()
        t.join(timeout=30)
        assert not t.is_alive()
        assert cluster["coord"].version == 1


class TestShardedCheckpoint:
    def test_sharded_delta_ckpt_roundtrip(self, cluster, tmp_path):
        keys = np.arange(600, dtype=np.int64)
        vals = np.random.default_rng(6).standard_normal(
            (keys.size, DIM)).astype(np.float32)
        _seed_rows(cluster["servers"], keys, vals)
        client = cluster["client"]
        client.ckpt_save()  # base
        g = np.ones((keys.size, DIM), np.float32)
        client.apply("adam", keys, g, lr=1e-2, step=1)
        paths = client.ckpt_save()  # delta (only changed rows)
        assert any("delta-" in p for p in paths)
        expect = self._sorted(client.export_all())

        # fresh servers restore base + delta at the same shard layout
        restored = [
            EmbeddingShardServer(
                dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
                index=i, num_shards=2,
                ckpt_dir=str(cluster["tmp_path"] / "ckpt"),
            ).start()
            for i in range(2)
        ]
        try:
            coord2 = EmbeddingCoordinator(
                [f"127.0.0.1:{s.port}" for s in restored],
                host="127.0.0.1",
            ).start()
            c2 = ShardedKvClient(
                coordinator_addr=f"127.0.0.1:{coord2.port}", dim=DIM
            )
            c2.ckpt_restore()
            got = self._sorted(c2.export_all())
            np.testing.assert_array_equal(expect["keys"], got["keys"])
            np.testing.assert_allclose(expect["values"], got["values"],
                                       rtol=0, atol=0)
            np.testing.assert_allclose(expect["slots"], got["slots"],
                                       rtol=0, atol=0)
            c2.close()
            coord2.stop()
        finally:
            for s in restored:
                s.stop()

    @staticmethod
    def _sorted(snap):
        order = np.argsort(snap["keys"])
        return {k: v[order] for k, v in snap.items()}


class TestAutoScaledTableTier:
    """The PSTrainingAutoScaler analog: a ScalePlan resizes the table
    tier through the master's auto-scaler machinery (reference
    job_auto_scaler.py:98)."""

    def test_scale_plan_grows_and_shrinks_tier(self, tmp_path):
        from dlrover_tpu.cluster.crd import ScalePlan
        from dlrover_tpu.embedding.service import EmbeddingServerScaler

        # in-process spawn (subprocess servers are exercised by the
        # recsys e2e; here the invariants are the point)
        servers = []

        def spawn(index):
            srv = EmbeddingShardServer(
                dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
            ).start()
            servers.append(srv)
            return f"127.0.0.1:{srv.port}", srv

        first = [EmbeddingShardServer(dim=DIM, num_slots=2, seed=7,
                                      host="127.0.0.1", index=i,
                                      num_shards=2).start()
                 for i in range(2)]
        servers.extend(first)
        coord = EmbeddingCoordinator(
            [f"127.0.0.1:{s.port}" for s in first], host="127.0.0.1"
        ).start()
        scaler = EmbeddingServerScaler(DIM, coordinator=coord,
                                       spawn=spawn)
        client = ShardedKvClient(
            coordinator_addr=f"127.0.0.1:{coord.port}", dim=DIM
        )
        try:
            keys = np.arange(1200, dtype=np.int64)
            client.lookup(keys)  # materialize rows
            client.apply("adam", keys,
                         np.ones((keys.size, DIM), np.float32),
                         lr=1e-2, step=1)
            before = client.export_all()

            scaler.scale(ScalePlan(
                replica_resources={"table_server": 3},
                reason="speed plan",
            ))
            assert coord.version == 1 and len(coord.addrs) == 3
            client.refresh_route()
            assert client.row_count() == keys.size

            scaler.scale(ScalePlan(
                replica_resources={"table_server": 2},
                reason="shrink",
            ))
            assert coord.version == 2 and len(coord.addrs) == 2
            client.refresh_route()
            after = client.export_all()
            oa, ob = (np.argsort(after["keys"]),
                      np.argsort(before["keys"]))
            np.testing.assert_array_equal(after["keys"][oa],
                                          before["keys"][ob])
            np.testing.assert_allclose(after["values"][oa],
                                       before["values"][ob],
                                       rtol=0, atol=0)
            # a plan without the group is a no-op for this scaler
            scaler.scale(ScalePlan(replica_resources={"worker": 9}))
            assert coord.version == 2
        finally:
            client.close()
            coord.stop()
            for s in servers:
                s.stop()

    def test_plugs_into_job_auto_scaler(self):
        """JobAutoScaler.execute drives the tier like any other scaler."""
        from dlrover_tpu.cluster.crd import ScalePlan
        from dlrover_tpu.embedding.service import EmbeddingServerScaler
        from dlrover_tpu.master.auto_scaler import JobAutoScaler

        servers = [EmbeddingShardServer(dim=DIM, num_slots=2, seed=7,
                                        host="127.0.0.1", index=i,
                                        num_shards=2).start()
                   for i in range(2)]

        def spawn(index):
            srv = EmbeddingShardServer(dim=DIM, num_slots=2, seed=7,
                                       host="127.0.0.1").start()
            servers.append(srv)
            return f"127.0.0.1:{srv.port}", srv

        coord = EmbeddingCoordinator(
            [f"127.0.0.1:{s.port}" for s in servers], host="127.0.0.1"
        ).start()
        scaler = EmbeddingServerScaler(DIM, coordinator=coord,
                                       spawn=spawn)

        class _Opt:  # minimal optimizer stub for the ctor
            def initial_plan(self):
                return ScalePlan()

        auto = JobAutoScaler(_Opt(), scaler, node_manager=None)
        try:
            auto.execute(ScalePlan(
                replica_resources={"table_server": 3},
                reason="auto-scale tick",
            ))
            assert len(coord.addrs) == 3 and coord.version == 1
        finally:
            coord.stop()
            for s in servers:
                s.stop()

    def test_scale_to_zero_rejected(self):
        from dlrover_tpu.cluster.crd import ScalePlan
        from dlrover_tpu.embedding.service import EmbeddingServerScaler

        srv = EmbeddingShardServer(dim=DIM, num_slots=2, seed=7,
                                   host="127.0.0.1").start()
        coord = EmbeddingCoordinator(
            [f"127.0.0.1:{srv.port}"], host="127.0.0.1").start()
        scaler = EmbeddingServerScaler(DIM, coordinator=coord)
        try:
            with pytest.raises(ValueError, match="below 1"):
                scaler.scale(ScalePlan(
                    replica_resources={"table_server": 0}))
            assert coord.version == 0  # untouched
        finally:
            coord.stop()
            srv.stop()

    def test_default_spawn_carries_tier_config(self):
        """Autoscale-spawned subprocess servers must inherit the tier's
        num_slots/seed — a mismatched server rejects migrated rows
        (review finding)."""
        from dlrover_tpu.cluster.crd import ScalePlan
        from dlrover_tpu.embedding.service import EmbeddingServerScaler

        servers = [EmbeddingShardServer(dim=DIM, num_slots=1, seed=3,
                                        host="127.0.0.1", index=i,
                                        num_shards=2).start()
                   for i in range(2)]
        coord = EmbeddingCoordinator(
            [f"127.0.0.1:{s.port}" for s in servers], host="127.0.0.1"
        ).start()
        scaler = EmbeddingServerScaler(
            DIM, coordinator=coord, num_slots=1, seed=3
        )
        client = ShardedKvClient(
            coordinator_addr=f"127.0.0.1:{coord.port}", dim=DIM
        )
        try:
            keys = np.arange(600, dtype=np.int64)
            client.lookup(keys)
            client.apply("adagrad", keys,
                         np.ones((keys.size, DIM), np.float32), lr=0.1)
            before = client.export_all()
            # grows via the REAL subprocess spawn path
            scaler.scale(ScalePlan(
                replica_resources={"table_server": 3}))
            client.refresh_route()
            after = client.export_all()
            oa, ob = (np.argsort(after["keys"]),
                      np.argsort(before["keys"]))
            np.testing.assert_array_equal(after["keys"][oa],
                                          before["keys"][ob])
            np.testing.assert_allclose(after["values"][oa],
                                       before["values"][ob],
                                       rtol=0, atol=0)
        finally:
            client.close()
            scaler.stop_all()
            coord.stop()
            for s in servers:
                s.stop()

    def test_failed_migration_cleans_up_spawned_servers(self):
        """coord.scale raising mid-grow must terminate the servers just
        spawned for it — a retried tick would otherwise leak one server
        per failure (review finding)."""
        from dlrover_tpu.cluster.crd import ScalePlan
        from dlrover_tpu.embedding.service import EmbeddingServerScaler

        srv = EmbeddingShardServer(dim=DIM, num_slots=2, seed=7,
                                   host="127.0.0.1", index=0,
                                   num_shards=1).start()
        coord = EmbeddingCoordinator(
            [f"127.0.0.1:{srv.port}"], host="127.0.0.1").start()

        stopped = []

        class _FakeProc:
            def __init__(self, i):
                self.i = i

            def stop(self):
                stopped.append(self.i)

        def spawn(index):
            return f"127.0.0.1:{59000 + index}", _FakeProc(index)

        scaler = EmbeddingServerScaler(DIM, coordinator=coord,
                                       spawn=spawn)

        def boom(addrs):
            raise ConnectionError("shard died mid-migrate")

        coord.scale = boom
        try:
            with pytest.raises(ConnectionError):
                scaler.scale(ScalePlan(
                    replica_resources={"table_server": 3}))
            assert sorted(stopped) == [1, 2]  # both spawns reaped
            assert not scaler._procs
        finally:
            coord.stop()
            srv.stop()

    def test_failed_spawn_cleans_up_earlier_spawns(self):
        """A readiness failure on spawn #2 must reap spawn #1 (review
        finding: the leak pattern also exists at the spawn leg)."""
        from dlrover_tpu.cluster.crd import ScalePlan
        from dlrover_tpu.embedding.service import EmbeddingServerScaler

        srv = EmbeddingShardServer(dim=DIM, num_slots=2, seed=7,
                                   host="127.0.0.1", index=0,
                                   num_shards=1).start()
        coord = EmbeddingCoordinator(
            [f"127.0.0.1:{srv.port}"], host="127.0.0.1").start()
        stopped = []

        class _P:
            def __init__(self, i): self.i = i
            def stop(self): stopped.append(self.i)

        calls = []

        def spawn(index):
            calls.append(index)
            if len(calls) == 2:
                raise RuntimeError("server not ready")
            return f"127.0.0.1:{58000 + index}", _P(index)

        scaler = EmbeddingServerScaler(DIM, coordinator=coord,
                                       spawn=spawn)
        try:
            with pytest.raises(RuntimeError, match="not ready"):
                scaler.scale(ScalePlan(
                    replica_resources={"table_server": 3}))
            assert stopped == [1]
            assert not scaler._procs
            assert coord.version == 0  # route untouched
            # shutdown refuses further scaling
            scaler.stop_all()
            with pytest.raises(RuntimeError, match="shut down"):
                scaler.scale(ScalePlan(
                    replica_resources={"table_server": 2}))
        finally:
            coord.stop()
            srv.stop()
