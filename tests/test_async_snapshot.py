"""Zero-stall async shm snapshots (CheckpointEngine.save_to_memory_async).

The goodput-critical path: the sync snapshot charges the training loop for
a device sync + arena write every cadence (measured 5-8% of steady step
time in the goodput bench); the async path must cost the loop nothing,
survive the train step's buffer donation, and keep only the newest
pending snapshot.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.checkpoint.engine import CheckpointEngine


@pytest.fixture()
def engine(tmp_ipc_dir, tmp_path):
    eng = CheckpointEngine(str(tmp_path / "ckpt"), node_id=3)
    yield eng
    eng.close()


def _state(v: float):
    return {"w": jnp.full((64, 64), v), "step": jnp.asarray(int(v))}


@pytest.mark.timeout(60)
def test_async_snapshot_lands_and_matches(engine):
    engine.save_to_memory_async(7, _state(7.0))
    assert engine.flush_async(timeout=30)
    loaded = engine.load(_state(0.0))
    assert loaded is not None
    step, state = loaded
    assert step == 7
    np.testing.assert_array_equal(np.asarray(state["w"]), 7.0)


@pytest.mark.timeout(60)
def test_supersede_keeps_newest(engine):
    for v in (1, 2, 3):
        engine.save_to_memory_async(v, _state(float(v)))
    assert engine.flush_async(timeout=30)
    step, state = engine.load(_state(0.0))
    assert step == 3
    np.testing.assert_array_equal(np.asarray(state["w"]), 3.0)


@pytest.mark.timeout(120)
def test_survives_buffer_donation(engine):
    """The snapshot must capture the value at save time even though the
    very next train step donates (and deletes) those buffers."""
    step_fn = jax.jit(
        lambda s: {"w": s["w"] * 2, "step": s["step"] + 1},
        donate_argnums=0,
    )
    state = _state(5.0)
    engine.save_to_memory_async(5, state)
    state = step_fn(state)  # donates the snapshotted buffers
    state = step_fn(state)
    assert engine.flush_async(timeout=60)
    step, snap = engine.load(_state(0.0))
    assert step == 5
    np.testing.assert_array_equal(np.asarray(snap["w"]), 5.0)  # not 20
    # training state itself advanced independently
    np.testing.assert_array_equal(np.asarray(state["w"]), 20.0)
