"""Sparse serving path: train_recsys checkpoint -> serve_recsys scoring.

Reference analog: tfplus serving restores the KvVariable table from a TF
checkpoint; here the C++ table + dense tower round-trip through the flash
checkpoint and the restored model must still KNOW the synthetic signal it
memorized (accuracy well above chance), not merely reload row counts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.timeout(300)
def test_train_then_serve_roundtrip(tmp_ipc_dir, tmp_path):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "DLROVER_TPU_IPC_DIR": str(tmp_path / "ipc"),
    })
    train = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "train_recsys.py"),
         "--steps", "200", "--batch", "128", "--id-space", "20000",
         "--ckpt-dir", str(tmp_path / "ckpt"),
         "--result-file", str(tmp_path / "train.json")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert train.returncode == 0, train.stderr[-2000:]
    rows = json.load(open(tmp_path / "train.json"))["table_rows"]

    env["DLROVER_TPU_IPC_DIR"] = str(tmp_path / "ipc2")
    serve = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "serve_recsys.py"),
         "--ckpt-dir", str(tmp_path / "ckpt"), "--id-space", "20000",
         "--requests", "512", "--batch", "128",
         "--result-file", str(tmp_path / "serve.json")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert serve.returncode == 0, serve.stderr[-2000:]
    out = json.load(open(tmp_path / "serve.json"))
    assert out["table_rows"] == rows          # every row restored
    assert out["restored_step"] == 200
    # the parity signal memorized in the embeddings survived the
    # round-trip; chance is 0.5
    assert out["accuracy"] > 0.8, out


@pytest.mark.timeout(300)
def test_train_sharded_table_e2e(tmp_path):
    """BASELINE config 5 shape: the same training loop over a 2-shard
    embedding service (spawned server processes), learning the signal
    and checkpointing across shards."""
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "DLROVER_TPU_IPC_DIR": str(tmp_path / "ipc"),
        "JAX_PLATFORMS": "cpu",
        "DLROVER_TPU_PLATFORM": "cpu",
    })
    train = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "train_recsys.py"),
         "--steps", "150", "--batch", "128", "--id-space", "20000",
         "--table-shards", "2",
         "--ckpt-dir", str(tmp_path / "ckpt"), "--incremental-ckpt",
         # first_loss is the loss at the FIRST log point: at interval 50
         # the model has already converged by then (≈0.077) and the
         # decreasing-loss assertion compares converged noise against
         # converged noise. Interval 25 samples genuinely-early training
         # (≈0.195 on this seed), giving the assertion a real margin.
         "--log-interval", "25",
         "--result-file", str(tmp_path / "train.json")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert train.returncode == 0, train.stderr[-2000:]
    out = json.load(open(tmp_path / "train.json"))
    assert out["table_rows"] > 1000
    assert out["last_loss"] < out["first_loss"]
    # sharded incremental checkpoints landed (one chain per shard)
    shard_dirs = os.listdir(tmp_path / "ckpt" / "embedding-shards")
    assert sorted(shard_dirs) == ["n2-s0", "n2-s1"]
