"""Remote data workers (trainer/data_service.py) — the coworker analog."""

import socket
import threading

import numpy as np
import pytest

from dlrover_tpu.common.rpc import recv_frame, send_frame
from dlrover_tpu.trainer.data_service import (
    DataServiceServer,
    RemoteBatchLoader,
    decode_batch,
    encode_batch,
)


def _batches(n, base=0):
    def produce():
        for i in range(n):
            yield {
                "tokens": np.full((2, 8), base + i, dtype=np.int32),
                "weight": np.asarray([base + i], dtype=np.float32),
            }
    return produce


class TestWireFormat:
    def test_roundtrip_dtypes_shapes(self):
        batch = {
            "a": np.arange(12, dtype=np.int32).reshape(3, 4),
            "b": np.random.default_rng(0).normal(size=(2, 2)).astype(
                np.float64),
            "c": np.asarray(7, dtype=np.uint8),  # 0-d
        }
        out = decode_batch(encode_batch(batch))
        assert set(out) == set(batch)
        for k in batch:
            assert out[k].dtype == batch[k].dtype
            np.testing.assert_array_equal(out[k], batch[k])

    def test_end_marker(self):
        assert decode_batch(b"E") is None

    def test_zero_size_array_roundtrip(self):
        batch = {"empty": np.zeros((0, 5), np.float32),
                 "x": np.arange(3, dtype=np.int64)}
        out = decode_batch(encode_batch(batch))
        assert out["empty"].shape == (0, 5)
        np.testing.assert_array_equal(out["x"], batch["x"])

    def test_bad_tag(self):
        with pytest.raises(ValueError):
            decode_batch(b"X123")


class TestService:
    def test_single_worker_all_batches(self):
        srv = DataServiceServer(_batches(5), host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"])
            got = sorted(int(b["weight"][0]) for b in loader)
            assert got == [0, 1, 2, 3, 4]
        finally:
            srv.stop()

    def test_two_clients_partition(self):
        """Each batch goes to exactly one client (sharding semantics)."""
        srv = DataServiceServer(_batches(20), host="127.0.0.1").start()
        try:
            results: list[list[int]] = [[], []]

            def drain(idx):
                loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"])
                results[idx] = [int(b["weight"][0]) for b in loader]

            ts = [threading.Thread(target=drain, args=(i,)) for i in (0, 1)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            allv = results[0] + results[1]
            assert sorted(allv) == list(range(20))  # no dup, no loss
        finally:
            srv.stop()

    def test_fan_in_two_workers(self):
        s1 = DataServiceServer(_batches(3, base=0), host="127.0.0.1").start()
        s2 = DataServiceServer(_batches(3, base=100),
                               host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader(
                [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"]
            )
            got = sorted(int(b["weight"][0]) for b in loader)
            assert got == [0, 1, 2, 100, 101, 102]
        finally:
            s1.stop()
            s2.stop()

    def test_unreachable_worker_does_not_hang(self):
        # grab a port with no listener
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv = DataServiceServer(_batches(2), host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader(
                [f"127.0.0.1:{srv.port}", f"127.0.0.1:{port}"],
                connect_timeout=2.0,
            )
            got = sorted(int(b["weight"][0]) for b in loader)
            assert got == [0, 1]  # live worker drained, dead one skipped
        finally:
            srv.stop()

    @staticmethod
    def _pullers():
        return [t for t in threading.enumerate()
                if t.name.startswith("data-pull")]

    def _wait_no_pullers(self, seconds=5.0):
        import time

        deadline = time.monotonic() + seconds
        while self._pullers() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not self._pullers(), self._pullers()

    def test_early_close_unblocks_pullers(self):
        """Abandoning iteration + close() must not leave puller threads
        parked on the full prefetch queue forever (incl. the final None
        sentinel put)."""
        srv = DataServiceServer(_batches(50), host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"],
                                       prefetch=1)
            it = iter(loader)
            next(it)  # threads running, queue full behind us
            loader.close()
            self._wait_no_pullers()
            with pytest.raises(RuntimeError):
                next(iter(loader))  # closed loader refuses a new epoch
        finally:
            srv.stop()

    def test_reiteration_retires_previous_generation(self):
        """Breaking out of epoch 1 and starting epoch 2 must retire the
        old pullers and never replay epoch-1 queue leftovers."""
        srv = DataServiceServer(_batches(40), host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"],
                                       prefetch=2)
            it = iter(loader)
            seen1 = [int(next(it)["weight"][0]) for _ in range(3)]
            seen2 = [int(b["weight"][0]) for b in loader]  # epoch 2
            assert not set(seen1) & set(seen2)  # no replays
            # nothing lost except what epoch-1 pullers had in flight:
            # the union is a prefix-free subset of range(40) of size >= 35
            assert len(seen1) + len(seen2) >= 35
            self._wait_no_pullers()
        finally:
            srv.stop()

    def test_malformed_address_does_not_hang(self):
        srv = DataServiceServer(_batches(2), host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader(
                [f"127.0.0.1:{srv.port}", "localhost", "10.0.0.5:abc"],
                connect_timeout=2.0,
            )
            got = sorted(int(b["weight"][0]) for b in loader)
            assert got == [0, 1]
        finally:
            srv.stop()

    def test_broken_producer_fails_loudly_not_short_epoch(self):
        """A produce() iterator that raises mid-stream must read as a
        worker failure (connection drop), not as clean end-of-data."""
        def produce():
            yield {"weight": np.asarray([0], np.float32)}
            raise RuntimeError("corrupt shard")

        srv = DataServiceServer(produce, host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"])
            got = [int(b["weight"][0]) for b in loader]  # must terminate
            assert got == [0]
            # the failure is programmatically visible, not just a log
            # line — trainers can tell a truncated epoch from a drained
            # one (round-3 advisor finding)
            assert loader.failed_workers == [f"127.0.0.1:{srv.port}"]
        finally:
            srv.stop()

    def test_strict_loader_raises_on_truncated_epoch(self):
        def produce():
            yield {"weight": np.asarray([0], np.float32)}
            raise RuntimeError("corrupt shard")

        srv = DataServiceServer(produce, host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"],
                                       strict=True)
            with pytest.raises(RuntimeError, match="truncated"):
                list(loader)
        finally:
            srv.stop()

    def test_strict_loader_clean_drain_does_not_raise(self):
        srv = DataServiceServer(_batches(3), host="127.0.0.1").start()
        try:
            loader = RemoteBatchLoader([f"127.0.0.1:{srv.port}"],
                                       strict=True)
            assert len(list(loader)) == 3
            assert loader.failed_workers == []
        finally:
            srv.stop()

    def test_protocol_rejects_unknown_kind(self):
        # an unknown kind answers an ERROR frame, not end-of-data — a
        # version-skewed client must raise, not read a completed epoch
        srv = DataServiceServer(_batches(2), host="127.0.0.1").start()
        try:
            conn = socket.create_connection(("127.0.0.1", srv.port))
            send_frame(conn, b'{"kind": "bogus"}')
            frame = recv_frame(conn)
            assert frame[:1] == b"X"
            with pytest.raises(ValueError):
                decode_batch(frame)
            conn.close()
        finally:
            srv.stop()
