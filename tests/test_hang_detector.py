"""Agent-side hang detection: unit logic + wedged-trainer e2e.

Reference analog: atorch/atorch/fault_tolerance/hanging_detector.py:86
(progress-timeout relaunch) — unit-tested with an injected clock, then
driven end-to-end: a trainer that wedges mid-run is killed by the agent
and the job completes on the restart.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu.agent.hang_detector import HangDetector, ProgressReporter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")


class TestHangDetector:
    def test_startup_grace_then_hang(self, tmp_ipc_dir):
        d = HangDetector(node_id=5, timeout_s=10, startup_grace_s=30)
        d.reset()
        t0 = time.monotonic()
        assert not d.check(now=t0 + 29)       # still in grace
        assert d.check(now=t0 + 31)           # no report ever -> hung

    def test_progress_then_stall(self, tmp_ipc_dir):
        rep = ProgressReporter(node_id=6, min_interval_s=0)
        d = HangDetector(node_id=6, timeout_s=10, startup_grace_s=30)
        d.reset()
        t0 = time.monotonic()
        rep.report(3)
        assert not d.check(now=t0 + 100)      # fresh progress resets
        assert d.last_step() == 3
        # same step rewritten: NOT progress
        rep.report(3)
        assert not d.check(now=t0 + 105)      # within timeout of advance
        assert d.check(now=t0 + 111)          # stalled past timeout
        # step advances again: recovers
        rep.report(4)
        assert not d.check(now=t0 + 200)

    def test_reset_clears_stale_file(self, tmp_ipc_dir):
        rep = ProgressReporter(node_id=7, min_interval_s=0)
        rep.report(42)
        d = HangDetector(node_id=7, timeout_s=5, startup_grace_s=30)
        d.reset()  # a new incarnation must not credit the old file's step
        assert not os.path.exists(
            __import__(
                "dlrover_tpu.agent.hang_detector",
                fromlist=["progress_path"],
            ).progress_path(7)
        )

    def test_reporter_rate_limit(self, tmp_ipc_dir):
        from dlrover_tpu.agent.hang_detector import progress_path

        rep = ProgressReporter(node_id=8, min_interval_s=3600)
        rep.report(1)
        rep.report(2)  # dropped by the rate limit
        data = json.load(open(progress_path(8)))
        assert data["step"] == 1


@pytest.mark.timeout(300)
# slow tier (tier-1 envelope): among the heaviest bodies in this
# file on XLA:CPU; core behavior stays covered by the lighter
# tests in-tier. `pytest tests/` still runs it.
@pytest.mark.slow
def test_wedged_trainer_restarted_by_agent(tmp_path):
    """e2e: trainer wedges at step 8; the agent's detector kills it; the
    restart resumes from the shm snapshot and completes the run."""
    result_file = str(tmp_path / "result.json")
    env = dict(os.environ)
    env.update({
        "DLROVER_TPU_PLATFORM": "cpu",
        "DLROVER_TPU_DEVICE_COUNT": "1",
        "DLROVER_TPU_IPC_DIR": str(tmp_path / "ipc"),
        "PYTHONPATH": REPO,
    })
    cmd = [
        sys.executable, "-m", "dlrover_tpu.run", "--standalone",
        "--monitor-interval", "0.3", "--max-restarts", "2",
        "--hang-timeout", "4", "--hang-startup-grace", "120",
        EXAMPLE, "--",
        "--model", "tiny", "--global-batch", "8", "--seq", "128",
        "--log-interval", "5", "--ckpt-dir", str(tmp_path / "ckpt"),
        "--result-file", result_file,
        "--max-steps", "20", "--hang-at-step", "8",
    ]
    proc = subprocess.run(
        cmd, env=env, cwd=REPO, timeout=280,
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.load(open(result_file))
    assert result["final_step"] == 20
    assert result["restart_count"] == 1
    # the detector reported the wedge before killing
    assert "hang detected" in proc.stderr or "wedged" in proc.stderr, \
        proc.stderr[-2000:]
