"""MoE gating/dispatch + expert-parallel transformer.

Reference analog: atorch/atorch/modules/moe tests (gating math, layer
behavior) translated to the einsum-dispatch design.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.ops.moe import (
    MoeConfig,
    _dispatch_tensors,
    init_moe_params,
    moe_ffn,
)


class TestDispatch:
    def test_topk_gates_and_capacity(self):
        cfg = MoeConfig(n_experts=2, top_k=1, capacity_factor=1.0)
        # 4 tokens all prefer expert 0; capacity 2 -> two overflow dropped
        gates = jnp.asarray(
            [[0.9, 0.1]] * 4, jnp.float32
        )
        combine, dispatch = _dispatch_tensors(gates, cfg, capacity=2)
        assert dispatch.sum() == 2  # only 2 tokens placed
        # the placed tokens carry their gate weight
        np.testing.assert_allclose(
            np.asarray(combine.sum(axis=(1, 2))[:2]), [0.9, 0.9]
        )
        np.testing.assert_allclose(
            np.asarray(combine.sum(axis=(1, 2))[2:]), [0.0, 0.0]
        )

    def test_top2_routes_two_experts(self):
        cfg = MoeConfig(n_experts=4, top_k=2)
        gates = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(0), (8, 4)), -1
        )
        combine, dispatch = _dispatch_tensors(gates, cfg, capacity=8)
        per_token = np.asarray(dispatch.sum(axis=(1, 2)))
        np.testing.assert_array_equal(per_token, np.full(8, 2))

    def test_no_slot_collisions(self):
        cfg = MoeConfig(n_experts=2, top_k=2)
        gates = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(1), (16, 2)), -1
        )
        combine, dispatch = _dispatch_tensors(gates, cfg, capacity=16)
        # each (expert, slot) pair holds at most one token
        assert float(dispatch.sum(axis=0).max()) <= 1.0

    def test_no_slot_collisions_bf16_long_sequence(self):
        """Positions must survive bf16 gates past 256 tokens: a bf16
        cumsum cannot represent integers > 256 (slot collisions)."""
        cfg = MoeConfig(n_experts=2, top_k=1)
        gates = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(2), (1024, 2)), -1
        ).astype(jnp.bfloat16)
        combine, dispatch = _dispatch_tensors(gates, cfg, capacity=1024)
        assert float(dispatch.sum(axis=0).max()) <= 1.0
        assert float(dispatch.sum()) == 1024  # every token placed

    def test_masked_tokens_claim_no_capacity(self):
        """Pad tokens must not route or evict real tokens."""
        cfg = MoeConfig(n_experts=2, top_k=1, capacity_factor=1.0)
        params = init_moe_params(jax.random.PRNGKey(0), 8, 16, cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 8))
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.float32)
        y, aux = moe_ffn(params, x, cfg, token_mask=mask)
        # masked positions produce zero output (routed nowhere)
        np.testing.assert_allclose(
            np.asarray(y[0, 4:]), np.zeros((4, 8)), atol=1e-6
        )
        # real positions produce nonzero output (never evicted by pads)
        assert float(jnp.abs(y[0, :4]).sum()) > 0


class TestMoeFfn:
    def test_output_shape_and_aux(self):
        cfg = MoeConfig(n_experts=4, top_k=2)
        params = init_moe_params(jax.random.PRNGKey(0), 32, 64, cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))
        y, aux = jax.jit(partial(moe_ffn, cfg=cfg))(params, x)
        assert y.shape == x.shape
        # aux is >= 1 by Cauchy-Schwarz (perfect balance == 1)
        assert float(aux) >= 0.99

    def test_single_expert_equals_dense_ffn(self):
        """E=1 with ample capacity routes every token through the one
        expert with gate 1.0 — identical to a plain ReLU FFN."""
        cfg = MoeConfig(n_experts=1, top_k=1, capacity_factor=2.0)
        params = init_moe_params(jax.random.PRNGKey(0), 16, 32, cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 16))
        y, _ = moe_ffn(params, x, cfg)
        dense = jax.nn.relu(
            x @ params["w_in"][0]
        ) @ params["w_out"][0]
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(dense), atol=1e-5, rtol=1e-5
        )


class TestMoeTransformer:
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_trains_and_loss_decreases(self):
        cfg = tfm.CONFIGS["tiny-moe"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        assert "w_router" in params["layers"]
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 65), 0, cfg.vocab_size
        )
        opt = optax.adam(1e-2)
        state = opt.init(params)
        loss_fn = partial(tfm.loss_fn, cfg=cfg)

        @jax.jit
        def step(params, state):
            loss, g = jax.value_and_grad(loss_fn)(
                params, {"tokens": tokens}
            )
            updates, state = opt.update(g, state)
            return optax.apply_updates(params, updates), state, loss

        losses = []
        for _ in range(10):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow

    def test_expert_parallel_sharding_on_mesh(self):
        """moe strategy: expert weights shard over the expert axis and a
        full train step runs on the 8-device mesh."""
        from dlrover_tpu.parallel.strategy import moe as moe_strategy
        from dlrover_tpu.trainer.train_step import compile_train

        cfg = tfm.CONFIGS["tiny-moe"]
        strat = moe_strategy(expert_size=4, data_size=2)
        mesh = strat.build_mesh()
        compiled = compile_train(
            strategy=strat, mesh=mesh,
            loss_fn=tfm.make_loss_fn(cfg, strat, mesh),
            init_params_fn=lambda rng: tfm.init_params(cfg, rng),
            logical_params=tfm.logical_axes(cfg),
            optimizer=optax.adamw(1e-3),
        )
        state = compiled.init(jax.random.PRNGKey(0))
        w_in = state.params["layers"]["w_in"]
        spec = w_in.sharding.spec
        assert "expert" in str(spec), spec
        tokens = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (1, 4, 129), dtype=np.int32
        )
        batch = jax.device_put(
            {"tokens": tokens}, compiled.batch_sharding
        )
        state, metrics = compiled.step(state, batch)
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
