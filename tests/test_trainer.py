"""High-level Trainer: loops, logging, eval, save policies, resume, best.

Reference analog: the AtorchTrainer surface
(atorch/atorch/trainer/atorch_trainer.py:129 — train/evaluate/save with
save_total_limit rotation, metric_for_best_model + load_best_model_at_end,
resume_from_checkpoint) exercised the way the reference's trainer tests do:
tiny model, synthetic data, assertions on host-side state.
"""

from __future__ import annotations

import json
import os

import numpy as np
import optax
import pytest

import jax

from dlrover_tpu.agent.ckpt_saver import read_tracker
from dlrover_tpu.common.storage import PosixDiskStorage
from dlrover_tpu.models import mlp
from dlrover_tpu.trainer.trainer import (
    EarlyStoppingCallback,
    Trainer,
    TrainerCallback,
    TrainingArguments,
)

SIZES = (8, 16, 4)


def _dataset(n=64, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, SIZES[0])).astype(np.float32)
    # learnable rule: class = argmax of 4 fixed random projections
    w = np.asarray(jax.random.normal(jax.random.PRNGKey(9), (SIZES[0], 4)))
    ys = np.argmax(xs @ w, axis=-1).astype(np.int32)
    return [{"x": xs[i], "y": ys[i]} for i in range(n)]


def _trainer(tmp_path, train_n=64, callbacks=None, **arg_overrides):
    args = TrainingArguments(
        output_dir=str(tmp_path / "out"),
        global_batch_size=16,
        micro_batch_size=2,
        logging_steps=5,
        **arg_overrides,
    )
    return Trainer(
        args=args,
        optimizer=optax.adam(1e-2),
        init_params_fn=lambda rng: mlp.init_params(rng, SIZES),
        logical_params=mlp.logical_axes(SIZES),
        loss_fn=mlp.loss_fn,
        train_dataset=_dataset(train_n),
        eval_dataset=_dataset(32, seed=1),
        callbacks=callbacks,
        lr_schedule=lambda step: 1e-2,
    )


@pytest.mark.timeout(120)
def test_train_logs_and_loss_decreases(tmp_ipc_dir, tmp_path):
    t = _trainer(tmp_path, max_steps=30)
    try:
        state = t.train()
        assert state.global_step == 30
        losses = [e["loss"] for e in state.log_history if "loss" in e]
        assert len(losses) >= 3
        assert losses[-1] < losses[0]
        tail = [e for e in state.log_history if "steps_per_sec" in e]
        assert tail and tail[-1]["learning_rate"] == pytest.approx(1e-2)
        # the default LoggingCallback mirrored history to a JSONL file
        log_file = os.path.join(t.args.output_dir, "log_history.jsonl")
        lines = [json.loads(x) for x in open(log_file)]
        assert lines and lines[0]["step"] == 1  # logging_first_step
    finally:
        t.close()


@pytest.mark.timeout(120)
def test_epoch_semantics_and_epoch_eval(tmp_ipc_dir, tmp_path):
    # 64 samples / global 16 = 4 steps per epoch; 2 epochs = 8 steps
    t = _trainer(tmp_path, num_train_epochs=2.0, eval_strategy="epoch")
    try:
        state = t.train()
        assert state.global_step == 8
        assert state.epoch == pytest.approx(2.0)
        evals = [e for e in state.log_history if "eval_loss" in e]
        assert len(evals) == 2  # one per epoch
    finally:
        t.close()


@pytest.mark.timeout(120)
def test_early_stopping_and_control_flow(tmp_ipc_dir, tmp_path):
    # threshold so high no improvement ever counts: first eval sets best,
    # second eval trips patience=1 -> stop at step 10
    cb = EarlyStoppingCallback(patience=1, threshold=1e9)
    t = _trainer(
        tmp_path, max_steps=100, eval_strategy="steps", eval_steps=5,
        metric_for_best_model="eval_loss", callbacks=[cb],
    )
    try:
        state = t.train()
        assert state.global_step == 10
    finally:
        t.close()


@pytest.mark.timeout(120)
def test_callback_can_stop_training(tmp_ipc_dir, tmp_path):
    class StopAt(TrainerCallback):
        def on_step_end(self, args, state, control, **kw):
            if state.global_step >= 7:
                control.should_training_stop = True

    t = _trainer(tmp_path, max_steps=50, callbacks=[StopAt()])
    try:
        assert t.train().global_step == 7
    finally:
        t.close()


@pytest.mark.timeout(180)
# slow tier (tier-1 envelope): among the heaviest bodies in this
# file on XLA:CPU; core behavior stays covered by the lighter
# tests in-tier. `pytest tests/` still runs it.
@pytest.mark.slow
def test_save_rotation_resume(tmp_ipc_dir, tmp_path):
    t = _trainer(
        tmp_path, max_steps=20, save_strategy="steps", save_steps=5,
        save_total_limit=2,
    )
    ckpt_dir = t.ckpt_dir
    try:
        t.train()
        assert t.engine.wait_for_persist(20)
        storage = PosixDiskStorage()
        committed = read_tracker(storage, ckpt_dir)
        assert committed is not None and committed[0] == 20
        kept = sorted(
            int(d.split("-")[1])
            for d in storage.listdir(ckpt_dir) if d.startswith("step-")
        )
        assert 20 in kept
        assert len(kept) <= 2
        assert 5 not in kept  # oldest rotated out
    finally:
        t.close()

    # resume: fresh Trainer on the same output_dir continues at step 20
    t2 = _trainer(tmp_path, max_steps=24, save_strategy="steps", save_steps=5)
    try:
        state = t2.train()
        assert state.global_step == 24
        # resumed history from trainer_state.json was preserved
        assert any(e["step"] <= 20 for e in state.log_history)
        assert int(t2._train_state.step) == 24
    finally:
        t2.close()


@pytest.mark.timeout(180)
def test_load_best_model_at_end(tmp_ipc_dir, tmp_path):
    # greater_is_better on eval_loss makes the FIRST eval (highest loss,
    # least-trained params) the "best" — so the reload at the end must
    # restore early-step weights, observable via a re-evaluation.
    t = _trainer(
        tmp_path, max_steps=20, eval_strategy="steps", eval_steps=5,
        save_strategy="steps", save_steps=5,
        metric_for_best_model="eval_loss", greater_is_better=True,
        load_best_model_at_end=True,
    )
    try:
        state = t.train()
        assert state.best_step == 5
        final = t.evaluate(params=t._train_state.params)
        assert final["eval_loss"] == pytest.approx(
            state.best_metric, rel=1e-4
        )
        # sanity: training really did reduce the loss past the "best"
        evals = [e["eval_loss"] for e in state.log_history
                 if "eval_loss" in e]
        assert min(evals) < state.best_metric
    finally:
        t.close()


def test_training_arguments_validation_and_roundtrip(tmp_path):
    with pytest.raises(ValueError):
        TrainingArguments(eval_strategy="steps")
    with pytest.raises(ValueError):
        TrainingArguments(save_strategy="steps")
    args = TrainingArguments(
        output_dir=str(tmp_path), save_strategy="steps", save_steps=3,
        load_best_model_at_end=True,
    )
    assert args.metric_for_best_model == "eval_loss"
    clone = TrainingArguments.from_json(args.to_json())
    assert clone == args


@pytest.mark.timeout(120)
def test_goodput_callback_writes_log(tmp_ipc_dir, tmp_path):
    from dlrover_tpu.trainer.trainer import GoodputCallback
    from dlrover_tpu.utils.goodput import compute_goodput

    log = str(tmp_path / "gp.jsonl")
    t = _trainer(tmp_path, max_steps=12,
                 callbacks=[GoodputCallback(log)])
    try:
        t.train()
    finally:
        t.close()
    report = compute_goodput(log)
    assert report.n_steps == 12
    assert report.n_incarnations == 1
    assert report.goodput > 0.5


@pytest.mark.timeout(570)
def test_strategy_auto_with_cache(tmp_ipc_dir, tmp_path):
    """strategy='auto': the Trainer runs the cached search (the
    load_strategy analog) and trains; a second Trainer on the same
    output_dir reuses the pick without re-searching."""
    import json
    import time

    def make(out):
        args = TrainingArguments(
            output_dir=str(out), global_batch_size=16,
            micro_batch_size=2, max_steps=3,
        )
        return Trainer(
            args=args,
            optimizer=optax.adam(1e-2),
            init_params_fn=lambda rng: mlp.init_params(rng, SIZES),
            logical_params=mlp.logical_axes(SIZES),
            loss_fn=mlp.loss_fn,  # plain form: auto wraps it itself
            train_dataset=_dataset(48),
            strategy="auto",
            # per-sample shapes; the Trainer derives [1, global, ...]
            example_batch={
                "x": np.zeros((SIZES[0],), np.float32),
                "y": np.zeros((), np.int32),
            },
        )

    out = tmp_path / "auto_out"
    t1 = make(out)
    t1.train()
    cache = json.load(open(out / "strategy.json"))
    assert cache["strategy"]["name"]
    t0 = time.monotonic()
    t2 = make(out)  # second construction must reload, not re-search
    assert time.monotonic() - t0 < 30, "auto search re-ran despite cache"
    assert t2.strategy.name == t1.strategy.name

    # missing example_batch is an error, not a silent dp fallback
    with pytest.raises(ValueError, match="example_batch"):
        Trainer(
            args=TrainingArguments(output_dir=str(tmp_path / "x"),
                                   global_batch_size=16, max_steps=1),
            optimizer=optax.adam(1e-2),
            init_params_fn=lambda rng: mlp.init_params(rng, SIZES),
            logical_params=mlp.logical_axes(SIZES),
            loss_fn=mlp.loss_fn,
            strategy="auto",
        )
