"""Cluster layer: CRDs, operator reconcile, scalers, auto-scaler.

Reference analog: the Go controller tests
(dlrover/go/operator/pkg/controllers/training/task_test.go) and the
mock_k8s_client pattern (SURVEY.md §4) — a fake client records verbs so the
control loop runs hermetically.
"""

from __future__ import annotations

import threading
import time

import pytest

from dlrover_tpu.cluster.crd import (
    ElasticJob,
    ElasticJobSpec,
    OptimizeMode,
    ReplicaSpec,
    ScalePlan,
)
from dlrover_tpu.cluster.operator import ElasticJobOperator
from dlrover_tpu.cluster.scaler import (
    KubeClient,
    PodScaler,
    master_pod_manifest,
    worker_pod_manifest,
)
from dlrover_tpu.common.constants import EnvKey, NodeExitReason
from dlrover_tpu.master.resource_optimizer import (
    LocalResourceOptimizer,
    OptimizerConfig,
)
from dlrover_tpu.master.stats import LocalStatsReporter


class FakeKube(KubeClient):
    def __init__(self):
        self.pods: dict[str, dict] = {}
        self.lock = threading.Lock()
        self.created: list[str] = []
        self.deleted: list[str] = []

    def create_pod(self, namespace, manifest):
        with self.lock:
            name = manifest["metadata"]["name"]
            self.pods[name] = manifest
            self.created.append(name)

    def delete_pod(self, namespace, name):
        with self.lock:
            self.pods.pop(name, None)
            self.deleted.append(name)

    def list_pods(self, namespace, label_selector):
        want = dict(
            kv.split("=", 1) for kv in label_selector.split(",") if kv
        )
        with self.lock:
            return [
                p for p in self.pods.values()
                if all(
                    p["metadata"].get("labels", {}).get(k) == v
                    for k, v in want.items()
                )
            ]


def _job(workers=3, **replica_kw) -> ElasticJob:
    return ElasticJob(
        name="train1",
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=workers, tpu_type="v5p",
                    tpu_topology="2x2x1", memory_mb=8192, **replica_kw,
                )
            },
        ),
    )


class TestCrd:
    def test_manifest_roundtrip(self):
        job = _job(workers=4)
        job.spec.optimize_mode = OptimizeMode.CLUSTER
        back = ElasticJob.from_manifest(job.to_manifest())
        assert back.spec.optimize_mode == OptimizeMode.CLUSTER
        assert back.spec.replica_specs["worker"].replicas == 4
        assert back.spec.replica_specs["worker"].tpu_topology == "2x2x1"

    def test_worker_manifest_env_contract_and_tpu_selectors(self):
        pod = worker_pod_manifest(_job(), "worker", 7, "10.0.0.2:5001")
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env[EnvKey.NODE_ID] == "7"
        assert env[EnvKey.MASTER_ADDR] == "10.0.0.2:5001"
        sel = pod["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-accelerator"] == "v5p"
        assert sel["cloud.google.com/gke-tpu-topology"] == "2x2x1"

    def test_master_manifest(self):
        pod = master_pod_manifest(_job())
        cmd = pod["spec"]["containers"][0]["command"]
        assert "dlrover_tpu.master.job_master" in cmd


class TestOperator:
    def test_reconcile_creates_master_and_workers(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=3))
        assert "train1-master" in kube.pods
        workers = [n for n in kube.pods if "worker" in n]
        assert len(workers) == 3

    def test_scale_plan_resizes(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=3))
        op.apply_scale_plan(ScalePlan(
            job_name="train1", replica_resources={"worker": 1},
        ))
        assert len([n for n in kube.pods if "worker" in n]) == 1
        op.apply_scale_plan(ScalePlan(
            job_name="train1", replica_resources={"worker": 4},
        ))
        assert len([n for n in kube.pods if "worker" in n]) == 4

    def test_relaunch_recreates_same_node_id(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=2))
        op.apply_scale_plan(ScalePlan(
            job_name="train1", relaunch_nodes=[0],
        ))
        assert "train1-worker-0" in kube.deleted
        assert kube.created.count("train1-worker-0") == 2
        assert len([n for n in kube.pods if "worker" in n]) == 2

    def test_oom_memory_bump_reaches_relaunched_pod(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=2))
        op.apply_scale_plan(ScalePlan(
            job_name="train1", memory_mb={"0": 16384},
            relaunch_nodes=[0], reason="oom-recovery",
        ))
        pod = kube.pods["train1-worker-0"]
        res = pod["spec"]["containers"][0]["resources"]["requests"]
        assert res["memory"] == "16384Mi"
        # the bump persists across a later relaunch of the same node, and
        # a combined relaunch+target plan does not over-provision
        op.apply_scale_plan(ScalePlan(
            job_name="train1", relaunch_nodes=[0],
            replica_resources={"worker": 2},
        ))
        pod = kube.pods["train1-worker-0"]
        assert pod["spec"]["containers"][0]["resources"]["requests"][
            "memory"
        ] == "16384Mi"
        # combined relaunch + target never over-provisions
        assert len([n for n in kube.pods if "worker" in n]) == 2

    def test_resubmitted_spec_reaches_scaler(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=1))
        updated = _job(workers=1, image="new-image:2")
        op.apply_job(updated)
        op.apply_scale_plan(ScalePlan(
            job_name="train1", relaunch_nodes=[0],
        ))
        pod = kube.pods["train1-worker-0"]
        assert pod["spec"]["containers"][0]["image"] == "new-image:2"

    def test_delete_job_removes_pods(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=2))
        op.delete_job("train1")
        assert not kube.pods

    def test_reconcile_replaces_missing_workers(self):
        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=3))
        # a pod vanishes out-of-band (preemption)
        kube.delete_pod("default", "train1-worker-1")
        op.reconcile("train1")
        assert len([n for n in kube.pods if "worker" in n]) == 3


class TestOptimizer:
    def _opt(self, **cfg):
        stats = LocalStatsReporter()

        class Speed:
            rate = 0.0

            def running_speed(self):
                return self.rate

        speed = Speed()
        opt = LocalResourceOptimizer(
            OptimizerConfig(**cfg), stats, speed
        )
        return opt, stats, speed

    def test_oom_doubles_memory(self):
        opt, stats, _ = self._opt(host_memory_mb=4096, max_workers=2)
        plan = opt.oom_recovery_plan(3)
        assert plan.memory_mb == {"3": 8192}
        assert plan.relaunch_nodes == [3]
        # a second OOM doubles again
        assert opt.oom_recovery_plan(3).memory_mb == {"3": 16384}

    def test_oom_uses_observed_usage_when_higher(self):
        opt, stats, _ = self._opt(host_memory_mb=1024, max_workers=2)
        stats.record(0, used_memory_mb=6000)
        assert opt.oom_recovery_plan(0).memory_mb == {"0": 12000}

    def test_speed_plan_scales_up_within_bounds(self):
        opt, _, speed = self._opt(
            max_workers=8, target_steps_per_s=10.0,
        )
        speed.rate = 4.0
        plan = opt.speed_plan(current_workers=4)
        assert plan.replica_resources == {"worker": 6}
        speed.rate = 12.0
        assert opt.speed_plan(current_workers=6).is_empty()

    def test_failure_plans(self):
        opt, _, _ = self._opt(max_workers=2)
        assert opt.plan_for_failure(
            1, NodeExitReason.HARDWARE_ERROR
        ).relaunch_nodes == [1]
        assert opt.plan_for_failure(
            1, NodeExitReason.FATAL_ERROR
        ).is_empty()
        assert opt.plan_for_failure(
            1, NodeExitReason.OOM
        ).memory_mb


class TestPodWatcher:
    def test_diff_events_and_node_failure_wiring(self):
        from dlrover_tpu.cluster.watcher import (
            PodEvent,
            PodWatcher,
            wire_to_node_manager,
        )
        from dlrover_tpu.common.constants import NodeStatus
        from dlrover_tpu.master.node_manager import NodeManager

        kube = FakeKube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=2))
        nm = NodeManager()
        nm.ensure_node(0)
        nm.ensure_node(1)
        events: list = []

        handler = wire_to_node_manager(nm)
        watcher = PodWatcher(
            kube, "default", "train1",
            on_event=lambda e: (events.append(e), handler(e)),
        )
        added = watcher.poll_once()
        assert {e.kind for e in added} == {PodEvent.ADDED}
        # a worker pod vanishes out-of-band (preemption)
        kube.delete_pod("default", "train1-worker-1")
        deleted = watcher.poll_once()
        assert [e.kind for e in deleted] == [PodEvent.DELETED]
        assert deleted[0].node_id == 1
        # the node failed immediately — no dead-window wait
        nodes = {n.node_id: n for n in nm.all_nodes()}
        assert nodes[1].status == NodeStatus.FAILED
        assert nodes[0].status == NodeStatus.RUNNING


class TestStreamingWatcher:
    def _streaming_kube(self):
        import queue

        class StreamingKube(FakeKube):
            """FakeKube + the k8s-style blocking watch iterator."""

            def __init__(self):
                super().__init__()
                self.stream: queue.Queue = queue.Queue()

            def watch_pods(self, namespace, label_selector):
                while True:
                    ev = self.stream.get()
                    if ev is None:  # stream expiry
                        return
                    yield ev

            def close_watch(self):
                self.stream.put(None)

        return StreamingKube()

    def test_stream_events_delivered_without_polling(self):
        from dlrover_tpu.cluster.watcher import PodEvent, PodWatcher

        kube = self._streaming_kube()
        events: list = []
        got = threading.Event()
        watcher = PodWatcher(
            kube, "default", "train1",
            on_event=lambda e: (events.append(e), got.set()),
            interval_s=3600.0,  # polling would never fire in this test
        )
        watcher.start()
        try:
            pod = {"metadata": {"name": "train1-worker-0",
                                "labels": {"node-id": "0"}}}
            kube.stream.put({"type": "ADDED", "object": pod})
            assert got.wait(5.0)
            assert events[0].kind == PodEvent.ADDED
            got.clear()
            kube.stream.put({"type": "DELETED", "object": pod})
            assert got.wait(5.0)
            assert events[1].kind == PodEvent.DELETED
            assert events[1].node_id == 0
        finally:
            watcher.stop()

    def test_replacement_pod_same_node_id(self):
        """ADDED(new pod) then DELETED(old pod) for one node-id — the
        relaunch ordering — must not fail the live replacement node."""
        from dlrover_tpu.cluster.watcher import PodEvent, PodWatcher

        kube = self._streaming_kube()
        events: list = []
        watcher = PodWatcher(
            kube, "default", "train1",
            on_event=events.append, interval_s=3600.0,
        )
        old = {"metadata": {"name": "w0-old",
                            "labels": {"node-id": "0"}}}
        new = {"metadata": {"name": "w0-new",
                            "labels": {"node-id": "0"}}}
        bad = {"metadata": {"name": "weird",
                            "labels": {"node-id": "nope"}}}
        watcher._handle_stream_event({"type": "ADDED", "object": old})
        watcher._handle_stream_event({"type": "ADDED", "object": new})
        watcher._handle_stream_event({"type": "ADDED", "object": bad})
        watcher._handle_stream_event({"type": "DELETED", "object": old})
        assert [e.kind for e in events] == [PodEvent.ADDED]
        # deleting the replacement itself IS a failure
        watcher._handle_stream_event({"type": "DELETED", "object": new})
        assert [e.kind for e in events] == [
            PodEvent.ADDED, PodEvent.DELETED,
        ]

    def test_resync_does_not_override_in_flight_stream_events(self):
        """A stream ADDED landing while the resync's list RPC is in
        flight must not be reverted into a false DELETED by the stale
        snapshot (and vice versa for a streamed DELETED)."""
        from dlrover_tpu.cluster.watcher import PodEvent, PodWatcher

        kube = self._streaming_kube()
        events: list = []
        watcher = PodWatcher(
            kube, "default", "train1",
            on_event=events.append, interval_s=3600.0,
        )
        new_pod = {"metadata": {"name": "w3",
                                "labels": {"node-id": "3"}}}

        real_list = kube.list_pods

        def racing_list(namespace, selector):
            pods = real_list(namespace, selector)  # stale: no w3 yet
            # the stream delivers ADDED(w3) before the diff runs
            watcher._handle_stream_event(
                {"type": "ADDED", "object": new_pod}
            )
            return pods

        kube.list_pods = racing_list
        polled = watcher.poll_once()
        # no false DELETED for node 3; the stream's view survives
        assert polled == []
        assert [e.kind for e in events] == [PodEvent.ADDED]
        assert watcher._known.get(3) == "w3"

        # mirror race: streamed DELETED during a list that still has w3
        kube.list_pods = real_list
        kube.pods["w3"] = {"metadata": {"name": "w3", "labels": {
            "node-id": "3", "job": "train1", "group": "worker"}}}

        def racing_list2(namespace, selector):
            pods = real_list(namespace, selector)  # stale: w3 present
            watcher._handle_stream_event(
                {"type": "DELETED", "object": new_pod}
            )
            return pods

        kube.list_pods = racing_list2
        watcher.poll_once()
        # the dead pod is not resurrected into _known
        assert 3 not in watcher._known

    def test_stream_break_resyncs_by_list(self):
        """A deletion missed while the stream was down surfaces via the
        re-list diff on re-subscribe."""
        from dlrover_tpu.cluster.watcher import PodEvent, PodWatcher

        kube = self._streaming_kube()
        op = ElasticJobOperator(kube)
        op.apply_job(_job(workers=2))
        events: list = []
        two = threading.Event()

        def on_event(e):
            events.append(e)
            if len([x for x in events
                    if x.kind == PodEvent.DELETED]) >= 1:
                two.set()

        watcher = PodWatcher(
            kube, "default", "train1", on_event=on_event,
            interval_s=0.1,
        )
        watcher.start()
        try:
            # initial resync list sees both workers
            deadline = time.time() + 5
            while time.time() < deadline and len(events) < 2:
                time.sleep(0.02)
            assert {e.kind for e in events} == {PodEvent.ADDED}
            # pod vanishes while no stream event is sent; then the
            # stream expires -> watcher re-lists and catches it
            kube.delete_pod("default", "train1-worker-1")
            kube.stream.put(None)
            assert two.wait(5.0)
            deleted = [e for e in events if e.kind == PodEvent.DELETED]
            assert deleted[0].node_id == 1
        finally:
            watcher.stop()


class TestWatcherScalerCoordination:
    def test_intentional_scale_down_is_not_a_failure(self):
        from dlrover_tpu.cluster.watcher import (
            PodWatcher,
            wire_to_node_manager,
        )
        from dlrover_tpu.common.constants import NodeStatus
        from dlrover_tpu.master.node_manager import NodeManager

        kube = FakeKube()
        job = _job(workers=2)
        scaler = PodScaler(job, kube, "m:5001")
        scaler.scale(ScalePlan(replica_resources={"worker": 2}))
        nm = NodeManager()
        relaunched = []
        nm._relaunch_hook = relaunched.append
        nm.ensure_node(0)
        nm.ensure_node(1)
        watcher = PodWatcher(
            kube, "default", "train1",
            on_event=wire_to_node_manager(
                nm, was_intentional=scaler.consume_intentional_removal
            ),
        )
        watcher.poll_once()  # learn the 2 pods
        # deliberate scale-down to 1
        scaler.scale(ScalePlan(replica_resources={"worker": 1}))
        watcher.poll_once()
        nodes = {n.node_id: n for n in nm.all_nodes()}
        assert nodes[1].status == NodeStatus.DELETED
        assert relaunched == [], "scaler and watcher fought"
        # a LATER out-of-band vanish of the surviving pod is a failure
        kube.delete_pod("default", "train1-worker-0")
        watcher.poll_once()
        nodes = {n.node_id: n for n in nm.all_nodes()}
        assert nodes[0].status == NodeStatus.FAILED


class TestHyperparams:
    def test_suggestion_shape(self):
        from dlrover_tpu.master.hyperparams import suggest_initial

        s = suggest_initial(
            n_params=7_000_000_000, d_model=4096, n_layers=32,
            seq_len=4096, num_devices=128,
        )
        assert s.micro_batch_size >= 1
        assert s.global_batch_size == (
            s.micro_batch_size * 128 * s.grad_accum_steps
        )
        assert s.learning_rate > 0

    def test_lr_sqrt_scaling(self):
        from dlrover_tpu.master.hyperparams import suggest_initial

        small = suggest_initial(
            n_params=100e6, d_model=768, n_layers=12, seq_len=1024,
            num_devices=8, target_global_batch=256,
        )
        big = suggest_initial(
            n_params=100e6, d_model=768, n_layers=12, seq_len=1024,
            num_devices=8, target_global_batch=1024,
        )
        ratio = big.learning_rate / small.learning_rate
        expected = (big.global_batch_size / small.global_batch_size) ** 0.5
        assert ratio == pytest.approx(expected, rel=0.05)

    def test_tiny_hbm_still_trains(self):
        from dlrover_tpu.master.hyperparams import suggest_initial

        s = suggest_initial(
            n_params=1_000_000_000, d_model=2048, n_layers=24,
            seq_len=8192, num_devices=1,
            hbm_bytes_per_device=16 * (1 << 30),
        )
        assert s.micro_batch_size >= 1


class TestAutoScaler:
    def test_initial_scale_and_failure_replan(self):
        from dlrover_tpu.master.auto_scaler import JobAutoScaler

        kube = FakeKube()
        job = _job(workers=2)
        scaler = PodScaler(job, kube, "m:5001")
        stats = LocalStatsReporter()

        class Speed:
            def running_speed(self):
                return 0.0

        class NM:
            def running_nodes(self):
                return []

        opt = LocalResourceOptimizer(
            OptimizerConfig(min_workers=1, max_workers=2), stats, Speed()
        )
        auto = JobAutoScaler(opt, scaler, NM(), interval_s=3600)
        auto.start(initial_scale=True)
        try:
            assert len(kube.pods) == 2
            auto.on_node_failure(0, NodeExitReason.OOM)
            assert kube.created.count("train1-worker-0") == 2
        finally:
            auto.stop()
