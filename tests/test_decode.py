"""KV-cached decode equivalence with the training forward."""

from __future__ import annotations

import dataclasses
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.models.decode import forward_cached, generate, init_cache


def _f32(cfg):
    return dataclasses.replace(cfg, dtype="float32")


class TestCachedForwardEquivalence:
    @pytest.mark.parametrize("name", ["tiny", "gpt2-small"])
    def test_prefill_matches_forward(self, name):
        cfg = _f32(
            dataclasses.replace(
                tfm.CONFIGS[name], n_layers=2, max_seq_len=64
            )
        )
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size
        )
        ref = tfm.forward(params, tokens, cfg)
        cache = init_cache(cfg, 2, 32)
        out, cache = forward_cached(params, tokens, cache, cfg)
        assert int(cache["pos"]) == 16
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
        )

    @pytest.mark.parametrize("name", [
        "tiny",
        # slow tier (tier-1 envelope): the gpt2-small variant compiles
        # +decodes ~21s on XLA:CPU; tiny covers the equivalence in-tier
        pytest.param("gpt2-small", marks=pytest.mark.slow),
    ])
    def test_incremental_matches_forward(self, name):
        """Prefill then one-token steps (pos > 0 — the path PPO decode
        actually runs, incl. gpt2's pos_embed dynamic slice) reproduce
        the full forward."""
        cfg = _f32(
            dataclasses.replace(
                tfm.CONFIGS[name], n_layers=2, max_seq_len=64
            )
        )
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size
        )
        ref = tfm.forward(params, tokens, cfg)

        cache = init_cache(cfg, 2, 16)
        out_p, cache = forward_cached(params, tokens[:, :4], cache, cfg)
        outs = [out_p]
        step = jax.jit(
            lambda t, c: forward_cached(params, t, c, cfg)
        )
        for i in range(4, 12):
            out_i, cache = step(tokens[:, i:i + 1], cache)
            outs.append(out_i)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=3e-4, rtol=3e-4
        )


class TestSlidingWindowDecode:
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_windowed_decode_matches_windowed_forward(self):
        """A model trained with sliding-window attention must decode
        with the same mask — prefill+steps reproduce the windowed
        training forward, not the full-causal one."""
        cfg = _f32(
            dataclasses.replace(
                tfm.CONFIGS["tiny"], n_layers=2, max_seq_len=64,
                attention="splash", attention_window=4,
            )
        )
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size
        )
        from dlrover_tpu.ops.splash_attention import make_splash_attention

        ref = tfm.forward(
            params, tokens, cfg,
            attention_fn=make_splash_attention(cfg.attention_window),
        )
        cache = init_cache(cfg, 2, 16)
        out_p, cache = forward_cached(params, tokens[:, :4], cache, cfg)
        outs = [out_p]
        for i in range(4, 12):
            out_i, cache = forward_cached(
                params, tokens[:, i:i + 1], cache, cfg
            )
            outs.append(out_i)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=3e-4, rtol=3e-4
        )
        # and it differs from the full-causal forward (the mask matters)
        full = tfm.forward(
            params, tokens, dataclasses.replace(cfg, attention="dense",
                                                attention_window=0)
        )
        assert not np.allclose(np.asarray(got), np.asarray(full),
                               atol=1e-3)

    def test_resolve_config_carries_strategy_window(self):
        """The sliding_window preset sets the window in strategy.extra;
        resolve_config must surface it so decode masks match training."""
        from dlrover_tpu.parallel import strategy as S

        cfg = tfm.CONFIGS["tiny"]
        assert cfg.attention_window == 0
        resolved = tfm.resolve_config(cfg, S.sliding_window(window=16))
        assert resolved.attention == "splash"
        assert resolved.attention_window == 16
        # and pipeline extras merge the same way
        resolved_pp = tfm.resolve_config(cfg, S.pipeline(pipeline_size=2))
        assert resolved_pp.pipeline_stages == 2


class TestMoeDecode:
    def _cfg(self):
        # generous capacity: drop patterns differ between full-sequence
        # routing (training) and per-step routing (decode), so exact
        # equivalence is only defined in the no-drop regime
        return _f32(
            dataclasses.replace(
                tfm.CONFIGS["tiny-moe"], max_seq_len=64,
                moe_capacity_factor=float(tfm.CONFIGS["tiny-moe"].moe_experts),
            )
        )

    def test_incremental_matches_forward(self):
        cfg = self._cfg()
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size
        )
        ref = tfm.forward(params, tokens, cfg)
        cache = init_cache(cfg, 2, 16)
        out_p, cache = forward_cached(params, tokens[:, :4], cache, cfg)
        outs = [out_p]
        step = jax.jit(lambda t, c: forward_cached(params, t, c, cfg))
        for i in range(4, 12):
            out_i, cache = step(tokens[:, i:i + 1], cache)
            outs.append(out_i)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=3e-4, rtol=3e-4
        )

    def test_generate_runs(self):
        cfg = tfm.CONFIGS["tiny-moe"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        prompts = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        out = generate(params, prompts, cfg, gen_len=4,
                       key=jax.random.PRNGKey(7))
        assert out.shape == (2, 7)
        assert (np.asarray(out[:, :3]) == np.asarray(prompts)).all()


class TestGenerate:
    def test_shapes_and_determinism(self):
        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        prompts = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        out = generate(params, prompts, cfg, gen_len=5,
                       key=jax.random.PRNGKey(7))
        assert out.shape == (2, 8)
        np.testing.assert_array_equal(np.asarray(out[:, :3]),
                                      np.asarray(prompts))
        out2 = generate(params, prompts, cfg, gen_len=5,
                        key=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_greedy_matches_uncached_argmax(self):
        """temperature=0 cached decode equals argmax over the full
        uncached forward at every step."""
        cfg = _f32(tfm.CONFIGS["tiny"])
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        prompts = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
        out = generate(params, prompts, cfg, gen_len=6,
                       key=jax.random.PRNGKey(0), temperature=0.0)
        # uncached greedy reference
        toks = prompts
        for _ in range(6):
            logits = tfm.forward(params, toks, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
            toks = jnp.concatenate([toks, nxt.astype(jnp.int32)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(toks))

    def test_cached_is_faster_for_long_generation(self):
        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        prompts = jnp.zeros((4, 8), jnp.int32)
        gen = jax.jit(
            lambda p, k: generate(params, p, cfg, gen_len=48, key=k)
        )
        gen(prompts, jax.random.PRNGKey(0))  # compile

        from dlrover_tpu.rl.ppo import PPOConfig, sample

        ppo = PPOConfig(gen_len=48)
        ac = {"model": params, "value_head": jnp.zeros(cfg.d_model)}
        samp = jax.jit(lambda p, k: sample(ac, p, cfg, ppo, k))
        samp(prompts, jax.random.PRNGKey(0))

        def best_of(fn, n=3):
            times = []
            for i in range(n):
                t0 = time.monotonic()
                fn(prompts, jax.random.PRNGKey(i)).block_until_ready()
                times.append(time.monotonic() - t0)
            return min(times)

        cached_s = best_of(gen)
        uncached_s = best_of(samp)
        assert cached_s < uncached_s, (cached_s, uncached_s)


class TestSampling:
    """Serving-side sampler surface: top-k, nucleus, eos padding."""

    def test_top_k_one_equals_greedy(self):
        from dlrover_tpu.models.decode import sample_logits

        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
        greedy = jnp.argmax(logits, axis=-1)
        sampled = sample_logits(logits, jax.random.PRNGKey(1),
                                temperature=1.0, top_k=1)
        np.testing.assert_array_equal(np.asarray(sampled),
                                      np.asarray(greedy))

    def test_top_p_masks_tail(self):
        from dlrover_tpu.models.decode import sample_logits

        # one dominant token (p ~ 0.97): tiny nucleus keeps only it
        logits = jnp.zeros((2, 8)).at[:, 3].set(5.0)
        for seed in range(5):
            out = sample_logits(logits, jax.random.PRNGKey(seed),
                                temperature=1.0, top_p=0.5)
            np.testing.assert_array_equal(np.asarray(out), 3)

    def test_temperature_zero_is_argmax(self):
        from dlrover_tpu.models.decode import sample_logits

        logits = jax.random.normal(jax.random.PRNGKey(2), (3, 16))
        out = sample_logits(logits, jax.random.PRNGKey(3), temperature=0)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, axis=-1)))

    def test_generate_eos_pads_finished_rows(self):
        from dlrover_tpu.models.decode import generate

        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        prompts = jnp.ones((2, 4), jnp.int32)
        out = generate(params, prompts, cfg, gen_len=12,
                       key=jax.random.PRNGKey(1), temperature=1.0,
                       eos_id=7)
        gen = np.asarray(out[:, 4:])
        for row in gen:
            hits = np.where(row == 7)[0]
            if hits.size:  # everything after the first eos is eos
                assert np.all(row[hits[0]:] == 7)

    def test_generate_top_kp_runs_under_jit(self):
        from functools import partial

        from dlrover_tpu.models.decode import generate

        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        fn = jax.jit(partial(generate, cfg=cfg, gen_len=6,
                             temperature=0.8, top_k=16, top_p=0.9))
        out = fn(params, jnp.ones((2, 3), jnp.int32),
                 key=jax.random.PRNGKey(4))
        assert out.shape == (2, 9)
        assert np.all(np.asarray(out) >= 0)
        assert np.all(np.asarray(out) < cfg.vocab_size)
