"""Buddy-host shm checkpoint replication (checkpoint/buddy.py).

Round-2 verdict Missing #4 / Next #5: shm snapshots only survived
*process* death; TPU preemption kills the host VM. Every agent now
streams new snapshots to a master-assigned ring buddy and a relaunched
node pulls its snapshot back BEFORE spawning the trainer. The e2e here
SIGKILLs an entire node (launcher + agent + trainer), lets the master
relaunch it, and asserts the job resumed from the replicated in-memory
snapshot with no committed storage checkpoint to fall back on.

Reference analog: extends dlrover/python/elastic_agent/torch/
ckpt_saver.py:313 restart-in-place beyond single-host survival
(SURVEY §7 hard-parts).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.checkpoint.buddy import (
    BuddyReplicator,
    BuddyServer,
    fetch_snapshot,
    push_snapshot,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")


def _trainer_pids(node_id: int) -> list[int]:
    """Find trainer processes of one node by their agent-set env (the
    trainer runs in its own session, so killing the launcher's process
    group alone leaves it computing as an orphan)."""
    needle = f"DLROVER_TPU_NODE_ID={node_id}".encode()
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read()
            if b"train_transformer" not in cmd:
                continue
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read()
        except OSError:
            continue
        if needle + b"\x00" in env:
            pids.append(int(pid))
    return pids


@pytest.fixture
def server():
    s = BuddyServer().start()
    yield s
    s.stop()


class TestBuddyProtocol:
    def test_push_get_roundtrip(self, server):
        header = {"step": 7, "total_size": 1 << 20, "metas": {"w": {}}}
        payload = os.urandom(1 << 20)
        assert push_snapshot(server.addr, source=3, header=header,
                             payload=payload)
        got = fetch_snapshot(server.addr, source=3)
        assert got is not None
        got_header, got_payload = got
        assert got_header["step"] == 7
        assert got_payload == payload
        assert server.holds(3) == 7

    def test_get_missing_returns_none(self, server):
        assert fetch_snapshot(server.addr, source=99) is None
        assert server.holds(99) is None

    def test_latest_push_wins(self, server):
        push_snapshot(server.addr, 1, {"step": 1}, b"a")
        push_snapshot(server.addr, 1, {"step": 2}, b"bb")
        _, payload = fetch_snapshot(server.addr, 1)
        assert payload == b"bb"
        server.drop(1)
        assert fetch_snapshot(server.addr, 1) is None

    def test_push_to_dead_addr_is_false(self):
        assert not push_snapshot("127.0.0.1:1", 0, {"step": 1}, b"x",
                                 timeout_s=2.0)


class TestShmRawRoundTrip:
    def test_write_raw_restores_arrays(self, tmp_ipc_dir):
        from dlrover_tpu.checkpoint.shm_handler import (
            SharedMemoryHandler,
        )

        src = SharedMemoryHandler(70, owner=True)
        try:
            tree = {"w": np.arange(128, dtype=np.float32),
                    "b": np.ones(3, dtype=np.int32)}
            src.save_state_dict(11, tree)
            header, buf = src.read_raw()
            payload = bytes(buf[: int(header["total_size"])])
        finally:
            src.close(unlink=True)

        dst = SharedMemoryHandler(71, owner=True)
        try:
            assert dst.header() is None
            dst.write_raw(header, payload)
            step, arrays = dst.load_arrays()
            assert step == 11
            np.testing.assert_array_equal(
                arrays["w"], np.arange(128, dtype=np.float32))
            np.testing.assert_array_equal(
                arrays["b"], np.ones(3, dtype=np.int32))
        finally:
            dst.close(unlink=True)

    def test_write_raw_rejects_short_payload(self, tmp_ipc_dir):
        from dlrover_tpu.checkpoint.shm_handler import (
            SharedMemoryHandler,
        )

        h = SharedMemoryHandler(72, owner=True)
        try:
            with pytest.raises(ValueError, match="payload"):
                h.write_raw({"total_size": 128, "step": 1, "metas": {}},
                            b"short")
        finally:
            h.close(unlink=True)


class _FakeBuddyClient:
    def __init__(self, addr):
        self._addr = addr

    def query_buddy(self):
        from dlrover_tpu.common.messages import BuddyQueryResponse

        return BuddyQueryResponse(found=True, buddy_node_id=9,
                                  addr=self._addr)


class TestReplicator:
    def test_replicates_new_snapshots_once(self, tmp_ipc_dir, server):
        from dlrover_tpu.checkpoint.shm_handler import (
            SharedMemoryHandler,
        )

        h = SharedMemoryHandler(73, owner=True)
        try:
            rep = BuddyReplicator(h, _FakeBuddyClient(server.addr))
            assert not rep.replicate_once()  # nothing snapshotted yet
            h.save_state_dict(5, {"w": np.zeros(16, np.float32)})
            assert rep.replicate_once()
            assert server.holds(73) == 5
            assert not rep.replicate_once()  # same step: no re-push
            h.save_state_dict(6, {"w": np.ones(16, np.float32)})
            assert rep.replicate_once()
            header, payload = fetch_snapshot(server.addr, 73)
            assert header["step"] == 6
            view = np.frombuffer(
                payload[: 16 * 4], dtype=np.float32)
            np.testing.assert_array_equal(view, np.ones(16, np.float32))
        finally:
            h.close(unlink=True)


class TestMasterRingAssignment:
    def test_ring_over_registered_endpoints(self, tmp_ipc_dir):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=3)
        master.prepare()
        try:
            clients = {
                nid: MasterClient(master.addr, node_id=nid)
                for nid in (0, 1, 2)
            }
            assert not clients[0].query_buddy().found  # nobody registered
            for nid, c in clients.items():
                c.report_buddy_endpoint(f"127.0.0.1:{9000 + nid}")
            assert clients[0].query_buddy().buddy_node_id == 1
            assert clients[1].query_buddy().buddy_node_id == 2
            assert clients[2].query_buddy().buddy_node_id == 0  # wrap
            # a node alone in the ring has no buddy
            solo = JobMaster(min_nodes=1, max_nodes=1)
            solo.prepare()
            try:
                c = MasterClient(solo.addr, node_id=0)
                c.report_buddy_endpoint("127.0.0.1:9999")
                assert not c.query_buddy().found
            finally:
                solo.stop()
        finally:
            master.stop()


# slow tier: a REAL 2-node job — jax's CPU backend in this container
# cannot run multiprocess collectives ("Multiprocess computations aren't
# implemented on the CPU backend"), so every trainer spawn dies at state
# init and the test burns its whole 500s budget failing. Same
# disposition as tests/test_multinode_e2e.py; a plain `pytest tests/`
# (or any multi-host-capable backend) still runs it.
@pytest.mark.slow
@pytest.mark.timeout(500)
def test_sigkilled_node_restores_from_buddy(tmp_path, monkeypatch):
    """Kill node 1 wholesale (launcher+agent+trainer: its shm header dies
    with the agent); the master relaunches it; the replacement restores
    the replicated snapshot from node 0 and the 2-node job finishes.

    Determinism: FSDP strategy so each node owns real shard pieces
    (under pure dp, replica-0 dedup gives node 1 an empty shard set and
    nothing to replicate); ONE snapshot point (step 12 of 20, ~5s of
    0.4s steps away from the next) so survivors' local shm and the buddy
    copy can only ever hold step 12; the kill fires once BOTH buddies
    hold it. Storage never commits (ckpt-interval huge; the 2-shard
    commit can't complete with one shard missing), so resumed_from==12
    proves the restore came through the buddy path within the recovery
    window."""
    from dlrover_tpu.cluster.crd import ScalePlan
    from dlrover_tpu.cluster.scaler import LocalProcessScaler
    from dlrover_tpu.master.job_master import JobMaster

    monkeypatch.setenv("DLROVER_TPU_PLATFORM", "cpu")
    monkeypatch.setenv("DLROVER_TPU_DEVICE_COUNT", "4")
    monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
    monkeypatch.setenv("PYTHONPATH", REPO)
    monkeypatch.setenv("DLROVER_TPU_BUDDY_INTERVAL", "0.1")
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=4"
    )

    master = JobMaster(min_nodes=2, max_nodes=2, rdzv_timeout=8.0,
                       heartbeat_dead_window_s=4.0)
    result_file = str(tmp_path / "result.json")
    scaler = LocalProcessScaler(
        master_addr="",
        entrypoint=[
            "--monitor-interval", "0.3", "--max-restarts", "2",
            "--nnodes", "2", "--heartbeat-interval", "1",
            EXAMPLE, "--",
            "--model", "tiny", "--seq", "128", "--global-batch", "64",
            "--strategy", "fsdp",
            "--max-steps", "20", "--step-delay", "0.5",
            "--mem-ckpt-interval", "12",
            "--ckpt-interval", "1000000",
            "--ckpt-dir", str(tmp_path / "ckpt"),
            "--result-file", result_file,
            # frequent loss syncs keep host dispatch from running ahead
            # of the device past the next snapshot point
            "--log-interval", "2",
        ],
    )
    master.node_manager._relaunch_hook = scaler.relaunch_node
    master.prepare()
    scaler._master_addr = master.addr
    done = {}

    def run_master():
        done["ok"] = master.run(poll_interval_s=0.2,
                                all_exited_grace_s=5.0)

    t = threading.Thread(target=run_master, daemon=True)
    try:
        scaler.scale(ScalePlan(replica_resources={"worker": 2}))
        t.start()

        # wait until BOTH buddies hold the step-12 snapshot
        deadline = time.time() + 240
        ready = False
        while time.time() < deadline and not ready:
            eps = dict(master.servicer._buddy_endpoints)
            if len(eps) == 2:
                held = {}
                for nid, other in ((0, 1), (1, 0)):
                    got = fetch_snapshot(eps[nid], source=other,
                                         timeout_s=5.0)
                    held[other] = got[0]["step"] if got else None
                ready = held.get(0) == 12 and held.get(1) == 12
            if not ready:
                time.sleep(0.3)
        assert ready, "buddies never both held the step-12 snapshot"
        assert not os.path.exists(tmp_path / "ckpt" / "latest"), \
            "storage committed a checkpoint; test premise broken"

        kill_t = time.monotonic()
        # the ENTIRE node dies at once: launcher+agent group AND the
        # trainer's own session (simulates host preemption)
        trainers = _trainer_pids(1)
        os.killpg(scaler._procs[1].pid, signal.SIGKILL)
        for pid in trainers:
            try:
                os.killpg(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        assert trainers, "node 1 trainer not found to kill"

        t.join(timeout=400)
        assert not t.is_alive(), "job never finished after node kill"
        assert done.get("ok"), "job did not finish successfully"
        recover_s = time.monotonic() - kill_t
        result = json.load(open(result_file))
        assert result["final_step"] == 20
        assert result["num_nodes"] == 2
        # restored from the replicated in-memory snapshot — storage had
        # no committed step to offer
        assert result["resumed_from"] == 12
        nodes = {n.node_id: n for n in master.node_manager.all_nodes()}
        assert nodes[1].relaunch_count == 1
        print(f"\nbuddy recovery wall time: {recover_s:.1f}s "
              "(includes dead-window + respawn + restore)")
    finally:
        scaler.stop_all()
        master.stop()


class _SwitchableBuddyClient:
    def __init__(self):
        self.addr = ""
        self.buddy_id = 0

    def query_buddy(self):
        from dlrover_tpu.common.messages import BuddyQueryResponse

        return BuddyQueryResponse(found=True, buddy_node_id=self.buddy_id,
                                  addr=self.addr)


class TestReplicatorReassignment:
    def test_repushes_current_snapshot_to_new_buddy(self, tmp_ipc_dir):
        """Ring reassignment (old buddy died) must re-push the CURRENT
        snapshot to the new buddy, or the node is unprotected until the
        next snapshot (review finding)."""
        from dlrover_tpu.checkpoint.shm_handler import (
            SharedMemoryHandler,
        )

        a, b = BuddyServer().start(), BuddyServer().start()
        h = SharedMemoryHandler(74, owner=True)
        try:
            client = _SwitchableBuddyClient()
            client.addr, client.buddy_id = a.addr, 1
            rep = BuddyReplicator(h, client)
            h.save_state_dict(9, {"w": np.zeros(8, np.float32)})
            assert rep.replicate_once()
            assert a.holds(74) == 9
            # buddy reassigned: same step must go to the NEW server
            client.addr, client.buddy_id = b.addr, 2
            assert rep.replicate_once()
            assert b.holds(74) == 9
            assert not rep.replicate_once()  # now settled
            # SAME buddy id relaunches with a fresh empty server (new
            # port): suppression must key on the address, not the id
            b.drop(74)
            c = BuddyServer().start()
            try:
                client.addr, client.buddy_id = c.addr, 2
                assert rep.replicate_once()
                assert c.holds(74) == 9
            finally:
                c.stop()
        finally:
            h.close(unlink=True)
            a.stop()
            b.stop()


class TestServerBounds:
    def test_oversized_push_rejected(self, server, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_BUDDY_MAX_BYTES", "1024")
        assert not push_snapshot(server.addr, 1, {"step": 1},
                                 b"x" * 2048)
        assert server.holds(1) is None

    def test_store_evicts_beyond_max_sources(self):
        s = BuddyServer(max_sources=2).start()
        try:
            for src in (1, 2, 3):
                push_snapshot(s.addr, src, {"step": src}, b"p")
            assert s.holds(1) is None      # oldest evicted
            assert s.holds(2) == 2
            assert s.holds(3) == 3
        finally:
            s.stop()
