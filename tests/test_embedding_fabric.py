"""Elastic KV embedding fabric (DESIGN.md §25) acceptance suite.

Covers the §25 pillars: consistent-hash ownership (scale moves ~1/N of
rows), async gradient streaming (drain barrier, staleness back-pressure),
verified shard checkpoints on the §20 machinery (N→M→N row-exact, twin
rollback of a bit-flipped shard, persist-ack ledger namespacing), the
train+serve-one-table gateway route under a live scale event, the
kill-mid-migration chaos scenario's replay-identical trail, and the two
satellite regressions (stale-socket eviction in the PS tier,
merge_deltas deleted-row resurrection).
"""

import json
import os
import threading
import time
import urllib.request
import zlib

import numpy as np
import pytest

from dlrover_tpu.common.hashring import HashRing, id_points
from dlrover_tpu.embedding.fabric import (
    FabricClient,
    FabricShardServer,
    RingRoute,
    start_local_fabric,
)

DIM = 8


def _counter_value(name: str) -> float:
    from dlrover_tpu.telemetry.metrics import registry

    for fam in registry().snapshot():
        if fam["name"] == name:
            for s in fam["samples"]:
                return float(s.get("value", 0.0))
    return 0.0


def _sorted_export(client_or_dict) -> dict:
    snap = (client_or_dict if isinstance(client_or_dict, dict)
            else client_or_dict.export())
    order = np.argsort(snap["keys"], kind="stable")
    return {k: np.asarray(v)[order] for k, v in snap.items()}


@pytest.fixture
def ring(tmp_path):
    coord, servers = start_local_fabric(
        3, dim=DIM, seed=7, ckpt_dir=str(tmp_path / "ckpt"),
    )
    client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                          async_apply=False, retry_window_s=20.0)
    state = {"coord": coord, "servers": servers, "client": client,
             "tmp_path": tmp_path}
    yield state
    client.close()
    coord.stop()
    for s in state["servers"]:
        s.stop()


def _populate(client, n=256, seed=3):
    rng = np.random.default_rng(seed)
    ids = rng.choice(1 << 20, size=n, replace=False).astype(np.int64)
    client.lookup(ids)
    for _ in range(3):
        client.apply("adam", ids,
                     rng.standard_normal((n, DIM)).astype(np.float32),
                     lr=1e-2)
    return ids


# ------------------------------------------------------------ shared ring


class TestHashRing:
    def test_vectorized_matches_scalar(self):
        members = [f"m-{i}" for i in range(5)]
        r = HashRing(members)
        ids = np.random.default_rng(0).integers(
            0, 1 << 62, size=512).astype(np.int64)
        points, owners = r.snapshot(members)
        got = HashRing.owner_indices(points, owners, id_points(ids))
        for i, pos in zip(got, id_points(ids)):
            assert members[int(i)] == r.owner_of_point(int(pos))

    def test_membership_change_moves_a_bounded_slice(self):
        members = [f"m-{i}" for i in range(4)]
        ids = np.random.default_rng(1).integers(
            0, 1 << 62, size=20_000).astype(np.int64)
        pos = id_points(ids)
        r = HashRing(members)
        before = HashRing.owner_indices(*r.snapshot(members), pos)
        r.add("m-4")
        grown = members + ["m-4"]
        after = HashRing.owner_indices(*r.snapshot(grown), pos)
        changed = before != after
        # every change lands on the new member, and the moved slice is
        # ~1/N of the keyspace (vnode variance bounded)
        assert set(after[changed].tolist()) == {4}
        assert 0.05 < changed.mean() < 1.6 / 5

    def test_ring_route_owner_indices(self):
        route = RingRoute(version=0, members=["a", "b"],
                          addrs={"a": "x", "b": "y"})
        ids = np.arange(100, dtype=np.int64)
        idx = route.owner_indices(ids)
        assert idx.shape == (100,) and set(idx.tolist()) <= {0, 1}
        # stable: the same ids always route to the same member
        assert np.array_equal(idx, route.owner_indices(ids))


# ------------------------------------------------------------- ring scale


class TestRingScale:
    def test_grow_moves_about_one_over_n(self, ring, monkeypatch,
                                         tmp_path):
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR",
                           str(tmp_path / "journal"))
        coord, client = ring["coord"], ring["client"]
        ids = _populate(client, n=512)
        before = _sorted_export(client)
        total = client.row_count()
        extra = FabricShardServer(dim=DIM, num_slots=2, member="emb-3",
                                  seed=7, host="127.0.0.1").start()
        ring["servers"].append(extra)
        members = {s.member: s.addr for s in ring["servers"]}
        coord.scale(members)
        client.refresh_route()
        assert client.row_count() == total
        after = _sorted_export(client)
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])
        events = [
            json.loads(line) for line in
            open(tmp_path / "journal" / "events.jsonl")
        ]
        scales = [e for e in events if e["name"] == "embedding_scale"
                  and e.get("ok")]
        assert scales and scales[-1]["from_n"] == 3 \
            and scales[-1]["to_n"] == 4
        moved = scales[-1]["moved"]
        assert 0 < moved <= 1.6 / 4 * total, (
            f"3->4 moved {moved}/{total} rows; ring bound is ~1/N"
        )
        # the new member actually owns rows now
        assert len(extra.table) > 0
        # lookups on the new route still resolve every id
        np.testing.assert_array_equal(
            _sorted_export({"keys": ids,
                            "values": client.lookup(np.sort(ids))}
                           )["keys"],
            np.sort(ids),
        )

    def test_shrink_keeps_every_row(self, ring):
        coord, client = ring["coord"], ring["client"]
        _populate(client, n=256)
        before = _sorted_export(client)
        keep = {s.member: s.addr for s in ring["servers"][:2]}
        coord.scale(keep)
        client.refresh_route()
        assert len(client.route.members) == 2
        after = _sorted_export(client)
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])
        # the departed member pruned everything
        assert len(ring["servers"][2].table) == 0

    def test_repair_refills_only_the_dead_shard(self, ring):
        coord, client = ring["coord"], ring["client"]
        ids = _populate(client, n=512)
        client.persist(10)
        at_ckpt = _sorted_export(client)
        # live state moves past the checkpoint
        rng = np.random.default_rng(9)
        client.apply("adam", ids,
                     rng.standard_normal((ids.size, DIM)).astype(
                         np.float32), lr=1e-2)
        live = _sorted_export(client)
        assert not np.array_equal(at_ckpt["values"], live["values"])
        victim = ring["servers"][1]
        victim.stop()
        fresh = FabricShardServer(dim=DIM, num_slots=2,
                                  member=victim.member, seed=7,
                                  host="127.0.0.1").start()
        ring["servers"][1] = fresh
        info = coord.repair(victim.member, fresh.addr)
        assert info["rows"] == len(fresh.table) > 0
        client.refresh_route()
        assert client.row_count() == ids.size
        got = _sorted_export(client)
        route = client.route
        owners = route.owner_indices(got["keys"])
        dead = route.members.index(victim.member)
        # the dead shard's rows come from the checkpoint; everyone
        # else's kept their newer live values
        np.testing.assert_array_equal(
            got["values"][owners == dead],
            at_ckpt["values"][owners == dead],
        )
        np.testing.assert_array_equal(
            got["values"][owners != dead],
            live["values"][owners != dead],
        )


# -------------------------------------------------------- async streaming


class TestAsyncStreaming:
    def _slow_flusher(self, client, delay=0.02):
        inner = client._flush_item

        def slowed(item):
            time.sleep(delay)
            inner(item)

        client._flush_item = slowed

    def test_drain_barrier_makes_checkpoints_update_complete(
            self, ring, tmp_path):
        coord = ring["coord"]
        client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                              max_staleness=64, queue_batches=64)
        try:
            self._slow_flusher(client)
            rng = np.random.default_rng(5)
            ids = rng.choice(1 << 20, size=128, replace=False).astype(
                np.int64)
            client.lookup(ids)
            client.drain()
            for _ in range(8):
                client.apply("adam", ids,
                             rng.standard_normal((128, DIM)).astype(
                                 np.float32), lr=1e-2)
            # the queue is genuinely behind when the snapshot is asked
            # for: without the drain barrier these updates would be
            # missing from the saved state
            assert client.staleness() > 0
            info = client.persist(7)
            assert info["applied_version"] == 8
            live = _sorted_export(client)
        finally:
            client.close()
        # a fresh ring restores the persisted state: byte-equal to the
        # post-drain live table, update-complete
        coord2, servers2 = start_local_fabric(
            3, dim=DIM, seed=7, ckpt_dir=str(tmp_path / "ckpt"),
        )
        c2 = FabricClient(coordinator_addr=coord2.addr, dim=DIM,
                          async_apply=False)
        try:
            restored = coord2.restore()
            assert restored["step"] == 7
            assert restored["applied_version"] == 8
            got = _sorted_export(c2)
            for k in ("keys", "values", "slots", "freq"):
                np.testing.assert_array_equal(live[k], got[k])
        finally:
            c2.close()
            coord2.stop()
            for s in servers2:
                s.stop()

    def test_staleness_backpressure_engages_at_the_bound(self, ring):
        coord = ring["coord"]
        client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                              max_staleness=2, queue_batches=64)
        try:
            self._slow_flusher(client, delay=0.03)
            rng = np.random.default_rng(6)
            ids = np.arange(64, dtype=np.int64)
            client.lookup(ids)
            client.drain()
            before = _counter_value(
                "dlrover_tpu_embedding_backpressure_total")
            worst = 0
            for _ in range(8):
                client.apply("adam", ids,
                             rng.standard_normal((64, DIM)).astype(
                                 np.float32), lr=1e-2)
                worst = max(worst, client.staleness())
            after = _counter_value(
                "dlrover_tpu_embedding_backpressure_total")
            # the bound held: apply() blocked instead of running ahead
            assert worst <= 2
            assert after > before
            assert client.drain(timeout=20.0)
        finally:
            client.close()

    def test_env_default_staleness_bound(self, ring, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_EMBEDDING_MAX_STALENESS", "5")
        client = FabricClient(coordinator_addr=ring["coord"].addr,
                              dim=DIM)
        try:
            assert client.max_staleness == 5
        finally:
            client.close()

    def test_dead_ring_surfaces_flusher_error(self, tmp_path):
        coord, servers = start_local_fabric(2, dim=DIM, seed=7)
        client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                              retry_window_s=0.6)
        ids = np.arange(16, dtype=np.int64)
        client.lookup(ids)
        coord.stop()
        for s in servers:
            s.stop()
        try:
            client.apply("adam", ids, np.ones((16, DIM), np.float32),
                         lr=1e-2)
            with pytest.raises(RuntimeError, match="flusher died"):
                # the flusher exhausts its retry window against the
                # dead ring; the barrier must NOT report success
                client.drain(timeout=20.0)
        finally:
            client.close()

    def test_serve_mode_is_read_only(self, ring):
        _populate(ring["client"], n=64)
        serve = FabricClient(coordinator_addr=ring["coord"].addr,
                             dim=DIM, mode="serve")
        try:
            rows_before = serve.row_count()
            values, info = serve.lookup_with_info(
                np.asarray([1, 2, 999_999_937], dtype=np.int64))
            # no row materialized for the unseen id, freshness stamped
            assert serve.row_count() == rows_before
            assert values.shape == (3, DIM)
            assert info["version"] == serve.version
            assert info["applied_version"] >= 0
            with pytest.raises(RuntimeError, match="read-only"):
                serve.apply("adam", np.asarray([1], np.int64),
                            np.ones((1, DIM), np.float32), lr=1e-2)
        finally:
            serve.close()


# ------------------------------------------------- verified checkpoints


class TestVerifiedCheckpoints:
    def test_n_to_m_to_n_row_exact_with_slots(self, ring, tmp_path):
        client = ring["client"]
        _populate(client, n=384)
        reference = _sorted_export(client)
        assert reference["slots"].any()      # adam state is real
        info = client.persist(10)
        assert info["num_shards"] == 3

        def fresh_ring(n):
            coord, servers = start_local_fabric(
                n, dim=DIM, seed=7, ckpt_dir=str(tmp_path / "ckpt"),
            )
            c = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                             async_apply=False)
            return coord, servers, c

        # N=3 -> M=2
        coord2, servers2, c2 = fresh_ring(2)
        try:
            restored = coord2.restore()
            assert restored["step"] == 10 and restored["rows"] == 384
            got = _sorted_export(c2)
            for k in ("keys", "values", "slots", "freq"):
                np.testing.assert_array_equal(reference[k], got[k])
            c2.persist(20)
        finally:
            c2.close()
            coord2.stop()
            for s in servers2:
                s.stop()
        # M=2 -> N=3 again, through the 2-shard save
        coord3, servers3, c3 = fresh_ring(3)
        try:
            restored = coord3.restore()
            assert restored["step"] == 20
            assert restored["num_shards"] == 2
            got = _sorted_export(c3)
            for k in ("keys", "values", "slots", "freq"):
                np.testing.assert_array_equal(reference[k], got[k])
        finally:
            c3.close()
            coord3.stop()
            for s in servers3:
                s.stop()

    def test_manifest_carries_hash_shard_identity(self, ring, tmp_path):
        client = ring["client"]
        _populate(client, n=64)
        client.persist(4)
        manifest = json.loads(
            (tmp_path / "ckpt" / "step-4" / "commit_w3").read_text()
        )
        assert manifest["kind"] == "embedding"
        assert manifest["members"] == ["emb-0", "emb-1", "emb-2"]
        assert manifest["dim"] == DIM and manifest["num_slots"] == 2
        assert manifest["applied_version"] == 3
        for member, entry in manifest["shards"].items():
            piece = entry["pieces"][f"emb/{member}"]
            assert piece["replica"] == 0 and piece["crc32"]

    def test_bit_flipped_shard_rolls_back_to_its_twin(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR",
                           str(tmp_path / "journal"))
        coord, servers = start_local_fabric(
            3, dim=DIM, seed=7, replicas=2,
            ckpt_dir=str(tmp_path / "ckpt"),
        )
        client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                              async_apply=False)
        try:
            _populate(client, n=256)
            reference = _sorted_export(client)
            client.persist(10)
            # the medium rots: one bit of emb-0's shard file flips
            path = tmp_path / "ckpt" / "step-10" / "node_emb-0.bin"
            blob = bytearray(path.read_bytes())
            blob[len(blob) // 2] ^= 0x10
            path.write_bytes(bytes(blob))
            # wipe the live tables so only a true restore can match
            for s in servers:
                keys = s.table.export(with_slots=False)["keys"]
                if keys.size:
                    s.table.remove(keys)
            restored = coord.restore()
            # the step SURVIVES: emb-0's block verifies in its ring
            # successor's file (replicas=2), so restore rolls the one
            # shard back to the twin instead of losing step 10
            assert restored["step"] == 10
            got = _sorted_export(client)
            for k in ("keys", "values", "slots", "freq"):
                np.testing.assert_array_equal(reference[k], got[k])
            events = [
                json.loads(line) for line in
                open(tmp_path / "journal" / "events.jsonl")
            ]
            rb = [e for e in events
                  if e["name"] == "ckpt_shard_rollback"]
            assert rb and rb[0]["writer"] == "emb-0"
        finally:
            client.close()
            coord.stop()
            for s in servers:
                s.stop()

    def test_without_replicas_a_flip_condemns_the_step(self, tmp_path):
        coord, servers = start_local_fabric(
            3, dim=DIM, seed=7, replicas=1,
            ckpt_dir=str(tmp_path / "ckpt"),
        )
        client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                              async_apply=False)
        try:
            ids = _populate(client, n=128)
            client.persist(5)
            at5 = _sorted_export(client)
            client.apply("adam", ids, np.ones((128, DIM), np.float32),
                         lr=1e-2)
            client.persist(9)
            path = tmp_path / "ckpt" / "step-9" / "node_emb-1.bin"
            blob = bytearray(path.read_bytes())
            blob[len(blob) // 2] ^= 0x04
            path.write_bytes(bytes(blob))
            restored = coord.restore()
            # no twin to roll back to: quorum rejects step 9 wholesale
            # and lands on the previous verified step
            assert restored["step"] == 5
            got = _sorted_export(client)
            np.testing.assert_array_equal(at5["values"], got["values"])
        finally:
            client.close()
            coord.stop()
            for s in servers:
                s.stop()

    def test_persist_acks_land_in_the_embedding_ledger_group(self):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(port=0, rdzv_timeout=2.0)
        master.prepare()
        try:
            mc = MasterClient(master.addr, 0)
            entry = {"crc32": 1, "bytes": 2, "pieces": {}}
            for member in ("emb-0", "emb-1"):
                mc.report_persist_ack(4, 2, entry, writer_id=member,
                                      group="embedding")
            st = mc.persist_status(4, 2, group="embedding")
            assert st.complete
            assert set(st.shards) == {"emb-0", "emb-1"}
            # the fabric's acks can never complete a DENSE commit of
            # the same (step, world) — the ledger key is namespaced
            assert not mc.persist_status(4, 2).complete
            mc.close()
        finally:
            master.stop()

    def test_coordinator_commits_through_master_ledger(self, tmp_path):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(port=0, rdzv_timeout=2.0)
        master.prepare()
        coord = None
        servers = []
        client = None
        try:
            mc = MasterClient(master.addr, 0)
            coord, servers = start_local_fabric(
                2, dim=DIM, seed=7, ckpt_dir=str(tmp_path / "ckpt"),
                master_client=mc,
            )
            client = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                                  async_apply=False)
            _populate(client, n=64)
            info = client.persist(6)
            assert info["num_shards"] == 2
            st = mc.persist_status(6, 2, group="embedding")
            assert st.complete
            manifest = json.loads(
                (tmp_path / "ckpt" / "step-6" / "commit_w2").read_text()
            )
            # the manifest was assembled from the ledger's acks
            assert set(manifest["shards"]) == {"emb-0", "emb-1"}
            mc.close()
        finally:
            if client is not None:
                client.close()
            if coord is not None:
                coord.stop()
            for s in servers:
                s.stop()
            master.stop()


# ------------------------------------------------------- chaos scenario


class TestChaosScenario:
    @pytest.mark.parametrize("seed", [4242])
    def test_kill_mid_migration_replay_identical(self, tmp_path, seed):
        from dlrover_tpu.chaos.scenario import run_embedding_scenario

        r1 = run_embedding_scenario(str(tmp_path / "a"), seed=seed)
        r1.assert_invariants()
        r2 = run_embedding_scenario(str(tmp_path / "b"), seed=seed)
        r2.assert_invariants()
        assert r1.trail == r2.trail
        # the trail shows the injected kill and both scale outcomes
        assert ["embedding_msg", "reset", 0] in r1.trail["faults"]
        assert ["storage_write", "bit_flip", 0] in r1.trail["faults"]
        scales = [e for e in r1.trail["recovery"]
                  if e[0] == "embedding_scale"]
        assert [3, 4, False] == [scales[0][1], scales[0][2],
                                 scales[0][4]]
        assert any(e[4] for e in scales)       # the re-scale committed
        assert any(e[0] == "embedding_restore" and e[1] == 8
                   for e in r1.trail["recovery"])


# -------------------------------------------------- gateway live lookups


class TestGatewayLiveLookup:
    def _post(self, port, ids, timeout=10.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/embedding/lookup",
            data=json.dumps({"ids": ids}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_serves_version_pinned_rows_during_a_scale(self, ring):
        from dlrover_tpu.gateway.server import GatewayHTTPServer

        coord, client = ring["coord"], ring["client"]
        ids = _populate(client, n=256)
        expected = client.lookup(ids[:8])
        serve = FabricClient(coordinator_addr=coord.addr, dim=DIM,
                             mode="serve")
        http = GatewayHTTPServer(None, host="127.0.0.1", port=0,
                                 embedding_client=serve).start()
        extra = FabricShardServer(dim=DIM, num_slots=2, member="emb-3",
                                  seed=7, host="127.0.0.1").start()
        ring["servers"].append(extra)
        try:
            code, body = self._post(http.port, ids[:8].tolist())
            assert code == 200 and body["version"] == 0
            members = {s.member: s.addr for s in ring["servers"]}
            t = threading.Thread(target=coord.scale, args=(members,),
                                 daemon=True)
            t.start()
            # lookups issued THROUGH the scale event keep answering:
            # version errors / migrating gates re-route internally
            seen_versions = set()
            while t.is_alive():
                code, body = self._post(http.port, ids[:8].tolist())
                assert code == 200
                seen_versions.add(body["version"])
                np.testing.assert_allclose(
                    np.asarray(body["values"], np.float32), expected,
                    rtol=1e-6,
                )
            t.join()
            code, body = self._post(http.port, ids[:8].tolist())
            assert code == 200 and body["version"] == 1
            assert body["applied_version"] == 3
            assert body["staleness"] == 0
            assert seen_versions <= {0, 1}
        finally:
            http.stop()
            serve.close()

    def test_embedding_route_error_codes(self):
        from dlrover_tpu.gateway.server import GatewayHTTPServer

        http = GatewayHTTPServer(None, host="127.0.0.1", port=0,
                                 embedding_client=None).start()
        try:
            code, body = self._post(http.port, [[1, 2]])
            assert code == 503 and "error" in body
            hz = urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/healthz"
            )
        except urllib.error.HTTPError as e:
            assert e.code == 503
        finally:
            http.stop()

    def test_embedding_route_rejects_bad_request(self, ring):
        from dlrover_tpu.gateway.server import GatewayHTTPServer

        serve = FabricClient(coordinator_addr=ring["coord"].addr,
                             dim=DIM, mode="serve")
        http = GatewayHTTPServer(None, host="127.0.0.1", port=0,
                                 embedding_client=serve).start()
        try:
            code, body = self._post(http.port, [])
            assert code == 400 and "error" in body
        finally:
            http.stop()
            serve.close()


# ----------------------------------------------------- satellite: PS tier


class TestStaleSocketEviction:
    def test_killed_server_is_redialed_not_reused(self, tmp_path):
        from dlrover_tpu.embedding.service import (
            EmbeddingCoordinator,
            EmbeddingShardServer,
            ShardedKvClient,
        )

        servers = [
            EmbeddingShardServer(
                dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
                index=i, num_shards=2,
            ).start()
            for i in range(2)
        ]
        addrs = [f"127.0.0.1:{s.port}" for s in servers]
        coord = EmbeddingCoordinator(addrs, host="127.0.0.1").start()
        client = ShardedKvClient(
            coordinator_addr=f"127.0.0.1:{coord.port}", dim=DIM,
        )
        try:
            ids = np.arange(64, dtype=np.int64)
            v1 = client.lookup(ids)          # sockets now cached
            port = servers[1].port
            addr = f"127.0.0.1:{port}"
            stale = client._socks.get(addr)
            assert stale is not None
            rows = servers[1].table.export()
            servers[1].stop()                # server dies between calls
            # a respawn takes the same address (same shard identity);
            # the old listener may take a moment to release the port
            revived = None
            for _ in range(40):
                try:
                    revived = EmbeddingShardServer(
                        dim=DIM, num_slots=2, seed=7, host="127.0.0.1",
                        index=1, num_shards=2, port=port,
                    ).start()
                    break
                except OSError:
                    time.sleep(0.05)
            assert revived is not None, "could not rebind the port"
            revived.table.import_(rows)
            servers[1] = revived
            # the cached socket is stale; the client must evict it and
            # re-dial instead of failing the fanout
            v2 = client.lookup(ids)
            np.testing.assert_array_equal(v1, v2)
            # evicted means CLOSED, not just popped (the r05 fd leak)
            assert stale.fileno() == -1
            assert client._socks.get(addr) is not stale
        finally:
            client.close()
            coord.stop()
            for s in servers:
                s.stop()


# ---------------------------------------- satellite: deleted-row deltas


class TestMergeDeltasDeletedRow:
    def test_merge_drops_rows_removed_by_the_newer_delta(self):
        from dlrover_tpu.embedding.kv_table import (
            KvEmbeddingTable,
            merge_deltas,
        )

        older = {
            "keys": np.asarray([5, 6], np.int64),
            "values": np.ones((2, DIM), np.float32),
            "freq": np.asarray([1, 1], np.int64),
            "removed": np.asarray([], np.int64),
        }
        newer = {
            "keys": np.asarray([7], np.int64),
            "values": np.full((1, DIM), 2.0, np.float32),
            "freq": np.asarray([1], np.int64),
            "removed": np.asarray([5], np.int64),
        }
        merged = merge_deltas(older, newer)
        # the upsert of key 5 is gone — keeping it would resurrect the
        # row on replay (removals run before upserts)
        assert 5 not in merged["keys"].tolist()
        assert set(merged["keys"].tolist()) == {6, 7}
        assert 5 in merged["removed"].tolist()
        table = KvEmbeddingTable(dim=DIM, num_slots=2, seed=0)
        table.lookup(np.asarray([5], np.int64))     # 5 exists pre-replay
        table.apply_delta(merged)
        got = table.export(with_slots=False)["keys"].tolist()
        assert 5 not in got and {6, 7} <= set(got)

    def test_incremental_manager_keeps_deleted_row_dead(self, tmp_path):
        from dlrover_tpu.embedding.kv_table import (
            IncrementalCheckpointManager,
            KvEmbeddingTable,
        )

        table = KvEmbeddingTable(dim=DIM, num_slots=2, seed=1)
        mgr = IncrementalCheckpointManager(table, str(tmp_path / "inc"))
        base_ids = np.asarray([1, 2], np.int64)
        table.lookup(base_ids)
        mgr.save()                                   # base-1
        doomed = np.asarray([3], np.int64)
        table.lookup(doomed)                         # row 3 upserted
        real_write = mgr._write

        def failing_write(path, snap):
            raise OSError("disk hiccup")

        mgr._write = failing_write
        with pytest.raises(OSError):
            mgr.save()          # delta parked in _pending (holds row 3)
        mgr._write = real_write
        table.remove(doomed)    # newer change: row 3 deleted
        mgr.save()              # delta-2 = merge(pending, {removed: 3})
        fresh = KvEmbeddingTable(dim=DIM, num_slots=2, seed=1)
        mgr2 = IncrementalCheckpointManager(fresh, str(tmp_path / "inc"))
        assert mgr2.restore() == 2
        keys = fresh.export(with_slots=False)["keys"].tolist()
        # the deleted row stays dead; the base rows survive
        assert 3 not in keys
        assert {1, 2} <= set(keys)
