"""Chaos harness: deterministic fault injection + recovery invariants.

Reference analog: the chaosblade fault-tolerance experiments
(docs/tech_report/fault_tolerance_exps.md), made hermetic and
replayable: seeded count-matched fault plans (dlrover_tpu/chaos/)
injected at the RPC / storage / process-management trust boundaries,
with the acceptance scenario (trainer killed mid-save, newest shard
bit-flipped, master RPC flaking) driven end to end twice and its
fault/recovery journal trail compared across runs.
"""

from __future__ import annotations

import importlib.util
import json
import os
import time

import pytest

from dlrover_tpu import chaos
from dlrover_tpu.common import rpc, serde, storage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    chaos.uninstall()


# ----------------------------------------------------------------- gating


def test_disabled_is_a_hard_noop(monkeypatch, tmp_path):
    """With no plan installed, no chaos code runs on any hot path: the
    sites gate on ``chaos.ENABLED`` before calling ``fire`` at all."""
    assert chaos.ENABLED is False

    def _boom(*a, **k):  # noqa: ARG001
        raise AssertionError("chaos.fire called with chaos disabled")

    monkeypatch.setattr(chaos, "fire", _boom)

    @serde.register_message
    class ChaosPingA:
        x: int = 0

    server = rpc.RpcServer(lambda m: ChaosPingA(x=m.x + 1), host="127.0.0.1")
    server.start()
    try:
        client = rpc.RpcClient(f"127.0.0.1:{server.port}")
        assert client.call(ChaosPingA(x=1)).x == 2
        client.close()
    finally:
        server.stop()
    storage.atomic_write_file(b"clean", str(tmp_path / "f.bin"))
    assert open(tmp_path / "f.bin", "rb").read() == b"clean"


def test_malformed_plan_disables_chaos(monkeypatch):
    from dlrover_tpu.chaos.injector import controller_from_environ

    monkeypatch.setenv("DLROVER_TPU_CHAOS", "{not json")
    assert controller_from_environ() is None
    monkeypatch.setenv("DLROVER_TPU_CHAOS", "/nonexistent/plan.json")
    assert controller_from_environ() is None


# ----------------------------------------------------------- rule matching


def test_rule_matching_and_occurrence_window():
    ctl = chaos.ChaosController.from_spec({"seed": 3, "faults": [
        {"point": "p", "action": "a",
         "match": {"step_gte": 5, "path_suffix": ".bin"},
         "after": 1, "times": 2},
    ]})
    # context misses: wrong suffix, low step, missing key
    assert ctl.fire("p", step=9, path="x.meta") is None
    assert ctl.fire("p", step=2, path="x.bin") is None
    assert ctl.fire("p", step=9) is None
    # first real match skipped (after=1), next two fire, then exhausted
    assert ctl.fire("p", step=5, path="a.bin") is None
    assert ctl.fire("p", step=5, path="a.bin") is not None
    assert ctl.fire("p", step=9, path="b.bin") is not None
    assert ctl.fire("p", step=9, path="b.bin") is None


def test_seeded_firing_is_deterministic():
    spec = {"seed": 11, "faults": [
        {"point": "p", "action": "a", "prob": 0.4, "times": 0},
        {"point": "q", "action": "b", "prob": 0.7, "times": 0},
    ]}
    runs = []
    for _ in range(2):
        ctl = chaos.ChaosController.from_spec(spec)
        pattern = []
        for i in range(60):
            point = "p" if i % 2 else "q"
            pattern.append(ctl.fire(point) is not None)
        runs.append(pattern)
    assert runs[0] == runs[1]
    assert any(runs[0]) and not all(runs[0])
    # a different seed gives a different pattern (overwhelmingly)
    ctl = chaos.ChaosController.from_spec({**spec, "seed": 12})
    other = [ctl.fire("p" if i % 2 else "q") is not None
             for i in range(60)]
    assert other != runs[0]


def test_every_fault_leaves_a_journal_line(monkeypatch, tmp_path):
    monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR", str(tmp_path))
    ctl = chaos.install({"seed": 1, "faults": [
        {"point": "p", "action": "a", "times": 3},
    ]})
    for _ in range(5):
        ctl.fire("p", step=4)
    events = [
        json.loads(line)
        for line in open(tmp_path / "events.jsonl", encoding="utf-8")
    ]
    faults = [e for e in events if e["name"] == "chaos_fault"]
    assert [f["seq"] for f in faults] == [0, 1, 2]
    assert all(f["point"] == "p" and f["action"] == "a" and f["step"] == 4
               for f in faults)


# --------------------------------------------------------------- rpc faults


@serde.register_message
class ChaosPingB:
    x: int = 0


def _echo_server():
    server = rpc.RpcServer(lambda m: ChaosPingB(x=m.x + 1), host="127.0.0.1")
    server.start()
    return server, ChaosPingB


def test_rpc_drop_and_reset_retry_with_backoff_and_counts():
    server, Ping = _echo_server()
    before = rpc._retry_total.labels().value
    chaos.install({"seed": 1, "faults": [
        {"point": "rpc_call", "action": "drop", "times": 2},
    ]})
    try:
        client = rpc.RpcClient(f"127.0.0.1:{server.port}",
                               backoff_base_s=0.01)
        assert client.call(Ping(x=1)).x == 2  # drop, drop, ok
        chaos.install({"seed": 1, "faults": [
            {"point": "rpc_call", "action": "reset", "times": 1},
        ]})
        assert client.call(Ping(x=5)).x == 6  # reset, ok
        assert rpc._retry_total.labels().value - before >= 3
        client.close()
    finally:
        server.stop()


def test_rpc_garbled_frame_survived_by_server_and_client():
    server, Ping = _echo_server()
    chaos.install({"seed": 1, "faults": [
        {"point": "rpc_call", "action": "garble", "times": 1},
    ]})
    try:
        client = rpc.RpcClient(f"127.0.0.1:{server.port}",
                               backoff_base_s=0.01)
        assert client.call(Ping(x=3)).x == 4   # garbled then retried
        assert client.call(Ping(x=7)).x == 8   # server still healthy
        client.close()
    finally:
        server.stop()


def test_rpc_per_call_deadline_exceeded():
    before = rpc._deadline_total.labels().value
    client = rpc.RpcClient("127.0.0.1:1", retries=10_000,
                           backoff_base_s=0.02, backoff_max_s=0.05,
                           deadline_s=0.3)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="deadline"):
        client.call(rpc.RpcError(error=""))
    assert time.monotonic() - t0 < 5.0
    assert rpc._deadline_total.labels().value == before + 1


def test_rpc_delay_fault_only_slows_the_call():
    server, Ping = _echo_server()
    chaos.install({"seed": 1, "faults": [
        {"point": "rpc_call", "action": "delay", "args": {"s": 0.2},
         "times": 1},
    ]})
    try:
        client = rpc.RpcClient(f"127.0.0.1:{server.port}")
        t0 = time.monotonic()
        assert client.call(Ping(x=1)).x == 2
        assert time.monotonic() - t0 >= 0.2
        client.close()
    finally:
        server.stop()


# ------------------------------------------------------------ storage faults


def test_storage_bit_flip_is_deterministic(tmp_path):
    blobs = []
    for _ in range(2):
        chaos.install({"seed": 9, "faults": [
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_suffix": ".bin"}, "times": 1},
        ]})
        path = str(tmp_path / f"f{len(blobs)}.bin")
        storage.atomic_write_file(b"\x00" * 256, path)
        blobs.append(open(path, "rb").read())
        chaos.uninstall()
    assert blobs[0] == blobs[1] != b"\x00" * 256
    assert len(blobs[0]) == 256


def test_storage_enospc_and_torn(tmp_path):
    chaos.install({"seed": 2, "faults": [
        {"point": "storage_write", "action": "enospc",
         "match": {"path_suffix": ".a"}, "times": 1},
        {"point": "storage_write", "action": "torn",
         "args": {"frac": 0.25}, "match": {"path_suffix": ".b"},
         "times": 1},
    ]})
    with pytest.raises(OSError, match="space"):
        storage.atomic_write_file(b"x" * 10, str(tmp_path / "f.a"))
    assert not os.path.exists(tmp_path / "f.a")
    with pytest.raises(OSError, match="torn"):
        storage.atomic_write_file(b"y" * 100, str(tmp_path / "f.b"))
    # the torn write left a PARTIAL file at the final path
    assert os.path.getsize(tmp_path / "f.b") == 25


def test_storage_slow_fsync_delays_but_completes(tmp_path):
    chaos.install({"seed": 2, "faults": [
        {"point": "storage_write", "action": "slow_fsync",
         "args": {"s": 0.2}, "times": 1},
    ]})
    t0 = time.monotonic()
    storage.atomic_write_file(b"z" * 8, str(tmp_path / "s.bin"))
    assert time.monotonic() - t0 >= 0.2
    assert open(tmp_path / "s.bin", "rb").read() == b"z" * 8


# ------------------------------------------------------------------- lint


def test_fault_point_lint_passes_and_catches_undocumented(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "check_metric_names",
        os.path.join(REPO, "native", "check_metric_names.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    names, problems = mod.scan_fault_points()
    assert problems == []
    assert {"rpc_call", "storage_write", "agent_kill_trainer"} <= set(names)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'chaos.fire("totally_undocumented_point", x=1)\n'
    )
    _, problems = mod.scan_fault_points(str(pkg))
    assert any("totally_undocumented_point" in p for p in problems)
    (pkg / "mod.py").write_text("chaos.fire(f\"dyn_{x}\")\n")
    _, problems = mod.scan_fault_points(str(pkg))
    assert any("non-literal" in p for p in problems)


# -------------------------------------------------- gateway degraded mode


class _FakeMasterClient:
    def __init__(self):
        self.down = False
        self.kv: bytes | None = None

    def report_metrics(self, samples, role="agent"):  # noqa: ARG002
        if self.down:
            raise ConnectionError("master unreachable")

    def kv_get(self, key):  # noqa: ARG002
        if self.down:
            raise ConnectionError("master unreachable")
        return self.kv


class _RecordingScaler:
    def __init__(self):
        self.plans = []

    def scale(self, plan):
        self.plans.append(plan)


def test_gateway_degraded_mode(monkeypatch, tmp_path):
    import types

    monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR", str(tmp_path))
    from dlrover_tpu.gateway.control import MasterLink, _degraded_gauge

    client = _FakeMasterClient()
    scaler = _RecordingScaler()
    gw = types.SimpleNamespace(master_link=None)
    link = MasterLink(gw, client, scaler=scaler, interval_s=60)
    assert gw.master_link is link

    client.kv = b"3"
    link.tick()
    assert not link.degraded
    assert len(scaler.plans) == 1
    assert scaler.plans[0].replica_resources == {"serving": 3}

    # master goes away: degraded entered ONCE, no exception escapes,
    # no further control actions
    client.down = True
    link.tick()
    link.tick()
    assert link.degraded
    assert _degraded_gauge.labels().value == 1
    assert len(scaler.plans) == 1

    # master returns: degraded exits, control resumes
    client.down = False
    client.kv = b"2"
    link.tick()
    assert not link.degraded
    assert _degraded_gauge.labels().value == 0
    assert scaler.plans[-1].replica_resources == {"serving": 2}

    events = [
        json.loads(line)
        for line in open(tmp_path / "events.jsonl", encoding="utf-8")
    ]
    modes = [e["state"] for e in events if e["name"] == "degraded_mode"]
    assert modes == ["enter", "exit"]


def test_gateway_keeps_serving_while_degraded(monkeypatch):
    """Control-plane loss must not fail data-plane submits: a Gateway
    with an unreachable master still serves from its replica pool."""
    from dlrover_tpu.gateway.control import MasterLink
    from dlrover_tpu.gateway.server import Gateway

    class _Engine:
        slots = 4

        def submit(self, prompt, params, on_token=None):  # noqa: ARG002
            self._last = (len(prompt), params)
            return 1

        def step(self):
            pass

        def poll_results(self):
            import types as t

            if getattr(self, "_last", None) is None:
                return []
            self._last = None
            return [t.SimpleNamespace(id=1, tokens=[7, 8],
                                      finish_reason="stop")]

    gw = Gateway(lambda: _Engine(), replicas=1)
    try:
        client = _FakeMasterClient()
        client.down = True
        link = MasterLink(gw, client, interval_s=60)
        link.tick()
        assert link.degraded and gw.stats()["degraded"]
        result = gw.generate([1, 2, 3], timeout=30)
        assert result.tokens == [7, 8]
    finally:
        gw.stop()


# ------------------------------------------------- the acceptance scenario


def _scenario_env(tmp_path) -> dict:
    return {
        "DLROVER_TPU_PLATFORM": "cpu",
        "DLROVER_TPU_DEVICE_COUNT": "1",
        # warm recovery is a recovery path: the acceptance scenario must
        # stay deterministic WITH standby promotion in the loop (pinned
        # explicitly, independent of the feature's default)
        "DLROVER_TPU_STANDBY": "1",
    }


@pytest.mark.timeout(560)
def test_seeded_scenario_recovers_and_replays_identically(tmp_path):
    """The acceptance run: trainer SIGKILLed mid-save, newest shard
    bit-flipped, master RPC dropped on the re-join — completes with
    zero lost shards, restores from the newest VERIFIED step, and two
    runs with the same seed leave an identical fault/recovery trail."""
    from dlrover_tpu.chaos.scenario import canned_scenario, run_scenario

    results = []
    for run in ("run_a", "run_b"):
        res = run_scenario(
            canned_scenario(seed=20260804),
            str(tmp_path / run),
            env_extra=_scenario_env(tmp_path),
            deadline_s=250,
        )
        res.assert_invariants()
        results.append(res)

    for res in results:
        leg1, leg2 = res.legs
        # leg 1: killed once mid-save, recovered in place, completed
        assert leg1.result["restart_count"] == 1
        assert leg1.result["final_step"] == 14
        # leg 2 (fresh process tree): the newest step (14) was
        # bit-flipped on disk, so restore must roll back to the newest
        # verified step (12) — never the corrupt one, never step 0
        assert leg2.result["resumed_from"] == 12
        assert leg2.result["final_step"] == 20
        assert res.verified_step == 20
        # every planned fault fired exactly once and was journaled
        assert sorted(f[:2] for f in res.trail["faults"]) == sorted([
            ["agent_kill_trainer", "kill"],
            ["rpc_call", "drop"],
            ["storage_write", "bit_flip"],
            ["storage_write", "slow_fsync"],
        ])
        recovery_names = {r[0] for r in res.trail["recovery"]}
        assert {"node_restart", "ckpt_verify_failed",
                "ckpt_rollback"} <= recovery_names
        assert ["ckpt_rollback", 14, 12] in res.trail["recovery"]
        assert res.recovery_seconds is not None

    # determinism: identical fault/recovery journal trail across runs
    assert results[0].trail == results[1].trail

    # §27: the kill's incident trace assembles across the agent and the
    # respawned trainer, its category breakdown reconciles with the
    # report vocabulary, and the seeded span-id discipline makes the
    # incident trees byte-identical across the two runs
    from dlrover_tpu.telemetry import trace as trace_mod

    skeletons = []
    for run, res in zip(("run_a", "run_b"), results):
        jdir = str(tmp_path / run / "journal")
        roots = trace_mod.build_forest(trace_mod.load_spans([jdir]))
        incidents = [r for r in trace_mod.find_incident_roots(roots)
                     if r.span.fields.get("kind") == "failure"]
        assert incidents, "no failure incident tree assembled"
        inc = incidents[0]
        names = {n.span.name for n in inc.walk()}
        # the recovery phases attached under the incident root: the
        # agent's rendezvous and (cross-process, via SPAN_CTX) the
        # respawned trainer's restore
        assert "rendezvous_wait" in names
        assert "ckpt_restore" in names
        assert inc.n_procs() >= 2
        cats = trace_mod.incident_breakdown(inc)
        assert cats.get("restore", 0) > 0
        assert cats.get("rendezvous", 0) > 0
        # kill -> restore read off the TREE agrees with the journal-
        # timestamp recovery number (same bound bench.py asserts)
        from dlrover_tpu.chaos.scenario import _read_journal
        t_kill = next(e["t"] for e in _read_journal(jdir)
                      if e.get("name") == "chaos_fault"
                      and e.get("point") == "agent_kill_trainer")
        restore_end = min(n.end for n in inc.walk()
                          if n.span.name == "ckpt_restore")
        assert restore_end - t_kill == pytest.approx(
            res.recovery_seconds, rel=0.10)
        assert trace_mod.critical_path(inc)[-1].get("name") in names

        # byte-identical modulo the save-before-restart persist: that
        # span is opportunistic BY DESIGN (it fires only if a fresher
        # shm snapshot won the race with the kill signal), so its
        # presence is the one legitimately timing-dependent bit of an
        # otherwise deterministic incident tree
        def prune(sk):
            sk["children"] = [
                prune(c) for c in sk["children"]
                if c["name"] not in ("ckpt_persist", "ckpt_persist_shard")
            ]
            return sk

        skeletons.append(json.dumps(
            [prune(trace_mod.tree_skeleton(i)) for i in incidents],
            sort_keys=True))
    assert skeletons[0] == skeletons[1]


@pytest.mark.timeout(300)
def test_standby_promotion_is_deterministic_under_kill_chaos(tmp_path):
    """Warm-standby promotion IS the recovery path when the chaos
    harness kills the trainer: the respawn must be served by promoting
    the parked standby (standby_promote journal span present), the job
    must still complete losing nothing, and two seeded runs must leave
    an identical fault/recovery trail — promotion gets the same
    deterministic-replay guarantee as a cold respawn."""
    from dlrover_tpu.chaos.scenario import (
        JobLeg,
        Scenario,
        _read_journal,
        run_scenario,
    )

    def scenario():
        return Scenario(
            name="standby_kill", seed=424242,
            legs=[JobLeg(
                name="kill_promote", max_steps=14,
                faults=[{"point": "agent_kill_trainer", "action": "kill",
                         "args": {"sig": 9},
                         "match": {"step_gte": 8}, "times": 1}],
                train_args=["--ckpt-interval", "1000000",
                            "--mem-ckpt-interval", "2",
                            "--step-delay", "0.15"],
            )],
        )

    results = []
    for run in ("run_a", "run_b"):
        work = str(tmp_path / run)
        res = run_scenario(
            scenario(), work,
            env_extra=_scenario_env(tmp_path), deadline_s=140,
        )
        res.assert_invariants()
        assert res.legs[0].result["restart_count"] == 1
        assert res.legs[0].result["final_step"] == 14
        # the kill recovered from a warm shm snapshot, not from step 0.
        # The kill dispatches on the step the AGENT observed (>= 8), so
        # on a slow host it can land before the step-8 snapshot
        # (mem-ckpt-interval 2) is taken — warm recovery then resumes
        # from the previous snapshot, one interval behind
        assert res.legs[0].result["resumed_from"] >= 6
        # the respawn was a PROMOTION: the agent journaled the
        # standby_promote span around handing over the payload
        events = _read_journal(os.path.join(work, "journal"))
        promotes = [e for e in events
                    if e.get("name") == "standby_promote"]
        assert promotes, "no standby_promote span: respawn went cold"
        results.append(res)

    assert results[0].trail == results[1].trail
