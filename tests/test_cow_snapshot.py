"""COW (fork) shm snapshots (CheckpointEngine snapshot_mode="cow").

The 12 GB checkpoint headline path: on a single-core host the direct
arena write is memcpy-roofline-bound (~7 GB/s -> 1.6 s blocking for
12 GB), so the engine forks and the child does the copy while training
continues — blocking cost becomes the fork (page-table duplication,
milliseconds). Reference bar: 0.5 s save block at 18 GB
(docs/blogs/megatron_flash_checkpoint.md:159); the reference gets there
with a per-shard threadpool across many cores
(dlrover/python/elastic_agent/torch/ckpt_saver.py:542), COW is the
single-core-honest equivalent.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from dlrover_tpu.checkpoint.engine import CheckpointEngine


@pytest.fixture()
def engine(tmp_ipc_dir, tmp_path):
    eng = CheckpointEngine(
        str(tmp_path / "ckpt"), node_id=9, snapshot_mode="cow"
    )
    yield eng
    eng.close()


def _state(v: float, n: int = 1 << 20):
    return {
        "params": {"w": np.full(n, v, np.float32)},
        "mu": {"w": np.full(n, v + 0.5, np.float32)},
    }


@pytest.mark.timeout(120)
def test_cow_snapshot_roundtrip(engine):
    state = _state(3.0)
    assert engine.save_to_memory(1, state)  # warmup: arena creation
    assert engine.wait_snapshot(timeout=60)
    t0 = time.monotonic()
    assert engine.save_to_memory(2, state)
    block_s = time.monotonic() - t0
    assert engine.wait_snapshot(timeout=60)
    info = engine.last_snapshot_info
    # the blocking cost is the fork, not the 8 MB copy
    assert info["fork_s"] <= block_s + 0.01
    assert info.get("copy_s") is not None
    loaded = engine.load(_state(0.0))
    assert loaded is not None and loaded[0] == 2
    np.testing.assert_array_equal(loaded[1]["params"]["w"], 3.0)
    np.testing.assert_array_equal(loaded[1]["mu"]["w"], 3.5)


@pytest.mark.timeout(120)
def test_cow_is_point_in_time(engine):
    """Mutating the state right after save must not leak into the
    snapshot: the fork's COW pages preserve the at-save values even
    while the child is still copying."""
    state = _state(1.0)
    assert engine.save_to_memory(1, state)
    assert engine.wait_snapshot(timeout=60)
    assert engine.save_to_memory(2, state)
    # overwrite immediately — the child may still be copying
    state["params"]["w"][:] = 777.0
    state["mu"]["w"][:] = 778.0
    assert engine.wait_snapshot(timeout=60)
    loaded = engine.load(_state(0.0))
    assert loaded[0] == 2
    np.testing.assert_array_equal(loaded[1]["params"]["w"], 1.0)
    np.testing.assert_array_equal(loaded[1]["mu"]["w"], 1.5)


@pytest.mark.timeout(120)
def test_cow_storage_persist_sees_child_writes(engine, tmp_path):
    """save_to_storage must wait for the child before enqueueing the
    persist event, so the saver reads the new header, not the stale one."""
    state = _state(4.0)
    assert engine.save_to_memory(1, state)
    assert engine.save_to_storage(5, _state(5.0))
    assert engine.wait_for_persist(5, timeout=60)
    engine.shm_handler.clear()
    loaded = engine.load(_state(0.0))
    assert loaded[0] == 5
    np.testing.assert_array_equal(loaded[1]["params"]["w"], 5.0)


@pytest.mark.timeout(120)
def test_cow_back_to_back_saves_serialize(engine):
    """A second save while a child is mid-copy waits for the lock release
    instead of skipping; every snapshot lands in order."""
    for step in range(1, 5):
        assert engine.save_to_memory(step, _state(float(step)))
    assert engine.wait_snapshot(timeout=60)
    loaded = engine.load(_state(0.0))
    assert loaded[0] == 4
    np.testing.assert_array_equal(loaded[1]["params"]["w"], 4.0)
