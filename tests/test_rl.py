"""PPO/RLHF: GAE math, loss behavior, and a toy end-to-end improvement.

Reference analog: atorch/atorch/rl tests (trainer-level behavior).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.rl.ppo import (
    PPOConfig,
    PPOTrainer,
    gae_advantages,
    init_actor_critic,
    ppo_loss,
    sample,
)


def _np_gae(rewards, values, gamma, lam):
    B, T = rewards.shape
    next_v = np.concatenate([values[:, 1:], np.zeros((B, 1))], axis=1)
    deltas = rewards + gamma * next_v - values
    adv = np.zeros_like(deltas)
    run = np.zeros(B)
    for t in reversed(range(T)):
        run = deltas[:, t] + gamma * lam * run
        adv[:, t] = run
    return adv, adv + values


class TestGae:
    def test_matches_numpy_reference(self):
        rng = np.random.default_rng(0)
        r = rng.standard_normal((3, 7)).astype(np.float32)
        v = rng.standard_normal((3, 7)).astype(np.float32)
        adv, ret = gae_advantages(jnp.asarray(r), jnp.asarray(v),
                                  gamma=0.9, lam=0.8)
        adv_np, ret_np = _np_gae(r, v, 0.9, 0.8)
        np.testing.assert_allclose(np.asarray(adv), adv_np, atol=1e-5)
        np.testing.assert_allclose(np.asarray(ret), ret_np, atol=1e-5)


class TestSampleAndLoss:
    def setup_method(self):
        self.cfg = tfm.CONFIGS["tiny"]
        self.ppo = PPOConfig(gen_len=4)
        self.params = init_actor_critic(self.cfg, jax.random.PRNGKey(0))

    def test_sample_extends_prompts(self):
        prompts = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        out = sample(self.params, prompts, self.cfg, self.ppo,
                     jax.random.PRNGKey(1))
        assert out.shape == (2, 7)
        np.testing.assert_array_equal(np.asarray(out[:, :3]),
                                      np.asarray(prompts))
        assert (np.asarray(out[:, 3:]) < self.cfg.vocab_size).all()

    def test_loss_zero_advantage_policy_term(self):
        tokens = jnp.ones((2, 8), jnp.int32)
        from dlrover_tpu.rl.ppo import sequence_logprobs_and_values

        logp, values, _ = sequence_logprobs_and_values(
            self.params, tokens, self.cfg
        )
        batch = {
            "tokens": tokens,
            "old_logp": logp,
            "advantages": jnp.zeros_like(logp),
            "returns": values,
            "gen_mask": jnp.ones_like(logp),
        }
        loss, metrics = ppo_loss(batch=batch, params=self.params,
                                 cfg=self.cfg, ppo=self.ppo)
        # same params, zero advantage, returns==values -> ~zero loss
        assert abs(float(metrics["policy_loss"])) < 1e-5
        assert abs(float(metrics["value_loss"])) < 1e-5


class TestToyRlhf:
    def test_reward_improves(self):
        """Dense reward: fraction of generated tokens with low ids; PPO
        should push the policy toward them within a few iterations."""
        cfg = tfm.CONFIGS["tiny"]
        ppo = PPOConfig(gen_len=8, ppo_epochs=4, learning_rate=2e-2,
                        kl_coef=0.0)

        def reward_fn(tokens: np.ndarray) -> np.ndarray:
            gen = tokens[:, -ppo.gen_len:]
            return (gen < cfg.vocab_size // 8).mean(axis=1).astype(
                np.float32
            )

        trainer = PPOTrainer(cfg, ppo, reward_fn, jax.random.PRNGKey(0),
                             store_rollouts=True)
        rng = np.random.default_rng(0)
        scores = []
        for i in range(12):
            prompts = rng.integers(0, cfg.vocab_size, (16, 4)).astype(
                np.int32
            )
            m = trainer.train_step(prompts, jax.random.PRNGKey(100 + i))
            scores.append(m["score_mean"])
        early = np.mean(scores[:2])
        late = np.mean(scores[-2:])
        assert late > early + 0.2, scores
        assert len(trainer.buffer) == 12
