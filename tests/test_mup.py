"""muP coordinate check: logit scale stays width-invariant under training.

Reference analog: the coordinate-check methodology of atorch/atorch/mup
(and the muP paper): train a few steps at several widths; under muP the
activation/logit magnitudes stay O(1) in width, while standard
parametrization drifts with width.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.models import transformer as tfm
from dlrover_tpu.parallel.mup import lr_scale_tree, mup_optimizer


def _cfg(width: int, mup_base: int = 0) -> tfm.TransformerConfig:
    return tfm.TransformerConfig(
        vocab_size=256, d_model=width, n_layers=2,
        n_heads=width // 16, n_kv_heads=width // 16,
        d_ff=2 * width, max_seq_len=64, mup_base_width=mup_base,
    )


def _train_logit_rms(width: int, mup: bool, steps: int = 5,
                     lr: float = 2e-2) -> float:
    base = 64
    cfg = _cfg(width, mup_base=base if mup else 0)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 65), 0, cfg.vocab_size
    )
    opt = optax.adam(lr)
    if mup:
        opt = mup_optimizer(opt, tfm.logical_axes(cfg), base, width)
    state = opt.init(params)
    loss_fn = partial(tfm.loss_fn, cfg=cfg)

    @jax.jit
    def step(params, state):
        g = jax.grad(loss_fn)(params, {"tokens": tokens})
        updates, state = opt.update(g, state)
        return optax.apply_updates(params, updates), state

    for _ in range(steps):
        params, state = step(params, state)
    logits = tfm.forward(params, tokens[:, :-1], cfg)
    return float(jnp.sqrt(jnp.mean(logits.astype(jnp.float32) ** 2)))


class TestLrScaleTree:
    def test_matrix_vs_vector_scaling(self):
        cfg = _cfg(256)
        scales = lr_scale_tree(tfm.logical_axes(cfg), 64, 256)
        assert scales["layers"]["wq"] == 0.25       # embed x heads
        assert scales["layers"]["w_down"] == 0.25   # mlp x embed
        assert scales["lm_head"] == 0.25            # readout fan-in
        assert scales["embed"] == 1.0               # vocab x embed: vector
        assert scales["layers"]["ln1"] == 1.0
        assert scales["ln_f"] == 1.0

    def test_base_width_identity(self):
        cfg = _cfg(64)
        scales = lr_scale_tree(tfm.logical_axes(cfg), 64, 64)
        assert all(
            s == 1.0 for s in jax.tree_util.tree_leaves(scales)
        )


class TestCoordinateCheck:
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_mup_logits_width_invariant(self):
        """Width 64 -> 256: muP keeps the trained-logit scale far more
        stable than standard parametrization."""
        rms = {
            (w, mup): _train_logit_rms(w, mup)
            for w in (64, 256) for mup in (False, True)
        }
        drift_sp = rms[(256, False)] / rms[(64, False)]
        drift_mup = rms[(256, True)] / rms[(64, True)]
        # muP's drift across a 4x width change must be materially smaller
        assert drift_mup < drift_sp * 0.7, (rms, drift_sp, drift_mup)
        assert 0.2 < drift_mup < 2.5, rms
