"""Multi-node elastic training on localhost: 2 masters-worth of reality.

Two launcher processes (agents), one master, one jax.distributed world over
CPU+Gloo — training genuinely sharded across processes. The kill test is
the reference's headline scenario (SURVEY.md §5.3 elastic recovery): kill
one node's trainer mid-run, both agents re-rendezvous, training resumes
from a consistent checkpoint.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

# Slow tier: a genuine jax.distributed world over CPU+Gloo. This
# container's jax CPU backend cannot complete multi-process collectives
# (known since the telemetry PR — see CHANGES.md), so under tier-1 these
# four e2es burned ~60 s failing by timeout on every run without
# asserting anything. The slow tier keeps them collected by a plain
# `pytest tests/` on hosts whose backend supports the multi-process
# world (VERDICT.md: "move the slowest e2e bodies behind a tiered
# marker the driver still runs").
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")


def _env(tmp_path) -> dict:
    env = dict(os.environ)
    env.update(
        {
            "DLROVER_TPU_PLATFORM": "cpu",
            "DLROVER_TPU_DEVICE_COUNT": "4",
            "DLROVER_TPU_IPC_DIR": str(tmp_path / "ipc"),
            # cross-process event journal: master mints the trace id,
            # agents adopt it from the rendezvous payload, trainers
            # inherit it through the child env
            "DLROVER_TPU_JOURNAL_DIR": str(tmp_path / "journal"),
            "PYTHONPATH": REPO,
            # 4 virtual devices per process -> 8 global over 2 nodes
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        }
    )
    return env


def _start_master(tmp_path, env, min_nodes=2, max_nodes=2,
                  extra=()) -> tuple[subprocess.Popen, str]:
    port_file = str(tmp_path / "master_port")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.job_master",
         "--min-nodes", str(min_nodes), "--max-nodes", str(max_nodes),
         "--port-file", port_file, *extra],
        env=env, cwd=REPO, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        if os.path.exists(port_file) and open(port_file).read().strip():
            return proc, f"127.0.0.1:{open(port_file).read().strip()}"
        time.sleep(0.1)
    proc.kill()
    raise TimeoutError("master did not start")


def _launcher(tmp_path, env, node_id: int, train_args: list[str]
              ) -> subprocess.Popen:
    cmd = [
        sys.executable, "-m", "dlrover_tpu.run",
        "--master-addr", open(str(tmp_path / "master_addr")).read(),
        "--node-id", str(node_id), "--nnodes", "2",
        "--monitor-interval", "0.3", "--max-restarts", "2",
        EXAMPLE, "--",
        "--model", "tiny", "--seq", "128",
        "--global-batch", "8",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--result-file", str(tmp_path / f"result_{node_id}.json"),
        "--log-interval", "5",
        *train_args,
    ]
    return subprocess.Popen(
        cmd, env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _run_two_nodes(tmp_path, train_args, kill_after_ckpt=False,
                   timeout=420):
    env = _env(tmp_path)
    master, addr = _start_master(tmp_path, env)
    (tmp_path / "master_addr").write_text(addr)
    launchers = [
        _launcher(tmp_path, env, nid, train_args) for nid in (0, 1)
    ]
    killed = False
    try:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if all(p.poll() is not None for p in launchers):
                break
            if kill_after_ckpt and not killed \
                    and (tmp_path / "ckpt" / "latest").exists():
                out = subprocess.run(
                    ["pgrep", "-f", f"^{sys.executable} {EXAMPLE}"],
                    capture_output=True, text=True,
                )
                from dlrover_tpu.agent.standby import parked_standby_pids

                # aim at live trainers only, not parked warm standbys
                standbys = parked_standby_pids(str(tmp_path / "ipc"))
                pids = [int(p) for p in out.stdout.split()
                        if int(p) not in standbys]
                if pids:
                    os.kill(pids[-1], signal.SIGKILL)
                    killed = True
            time.sleep(0.5)
        outs = []
        for p in launchers:
            try:
                out, _ = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
        return launchers, outs, killed
    finally:
        for p in launchers:
            if p.poll() is None:
                p.kill()
        if master.poll() is None:
            try:
                os.killpg(master.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        subprocess.run(["pkill", "-9", "-f", EXAMPLE],
                       capture_output=True)


def _elastic_launcher(env, addr, tmp_path, nid: int,
                      nnodes: str = "2:3") -> subprocess.Popen:
    """Launcher for the elastic grow/shrink scenarios (min:max world)."""
    cmd = [
        sys.executable, "-m", "dlrover_tpu.run",
        "--master-addr", addr,
        "--node-id", str(nid), "--nnodes", nnodes,
        "--monitor-interval", "0.3", "--max-restarts", "2",
        # NB: the agent's --rdzv-timeout is how long it WAITS for a
        # round; the master's --rdzv-timeout is when a round COMPLETES
        # with fewer than max nodes. Setting them equal makes the
        # client deadline race the completion. 150 (not 90): a sibling
        # xdist worker's jax compiles can starve every child here for
        # tens of seconds on a one-core host.
        "--heartbeat-interval", "2", "--rdzv-timeout", "150",
        EXAMPLE, "--",
        "--model", "tiny", "--seq", "128",
        "--global-batch", "24",
        "--ckpt-dir", str(tmp_path / "ckpt"),
        "--ckpt-interval", "5",
        "--result-file", str(tmp_path / f"result_{nid}.json"),
        "--log-interval", "5",
        "--max-steps", "30", "--epochs", "50",
    ]
    return subprocess.Popen(
        cmd, env=env, cwd=REPO, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _drain(proc: subprocess.Popen, timeout: float = 30) -> str:
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
    return out


def _kill_all(launchers, master) -> None:
    for p in (launchers.values() if isinstance(launchers, dict)
              else launchers):
        if p.poll() is None:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    if master.poll() is None:
        try:
            os.killpg(master.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    subprocess.run(["pkill", "-9", "-f", EXAMPLE], capture_output=True)


@pytest.mark.timeout(500)
def test_two_node_training_completes(tmp_path):
    launchers, outs, _ = _run_two_nodes(
        tmp_path, ["--max-steps", "12"],
    )
    for p, out in zip(launchers, outs):
        assert p.returncode == 0, out[-3000:]
    result = json.load(open(tmp_path / "result_0.json"))
    assert result["final_step"] == 12
    assert result["num_nodes"] == 2
    assert not os.path.exists(tmp_path / "result_1.json")  # rank 1 silent


@pytest.mark.timeout(500)
def test_three_nodes_shrink_to_two_on_node_loss(tmp_path):
    """THE elastic headline: a 3-node world permanently loses a node
    (launcher+trainer killed); the master declares it dead, survivors
    re-rendezvous as a 2-node world, and training resumes from the
    sharded checkpoint RESHARDED from 12 devices onto 8."""
    env = _env(tmp_path)
    master, addr = _start_master(
        tmp_path, env, min_nodes=2, max_nodes=3,
        # short enough for a timely dead-node verdict, long enough that
        # a starved-but-live node's heartbeat (interval 2) can't miss
        # the window under a contended core
        extra=["--rdzv-timeout", "10", "--dead-window", "9"],
    )

    launchers = {
        nid: _elastic_launcher(env, addr, tmp_path, nid)
        for nid in (0, 1, 2)
    }
    killed = False
    try:
        deadline = time.time() + 360
        while time.time() < deadline:
            if all(p.poll() is not None
                   for nid, p in launchers.items() if nid != 2):
                break
            if not killed and (tmp_path / "ckpt" / "latest").exists():
                # permanently remove node 2: launcher AND its trainer
                os.killpg(launchers[2].pid, signal.SIGKILL)
                killed = True
            time.sleep(0.5)
        assert killed, "checkpoint never appeared"
        outs = {nid: _drain(launchers[nid]) for nid in (0, 1)}
        for nid in (0, 1):
            assert launchers[nid].returncode == 0, outs[nid][-4000:]
        result = json.load(open(tmp_path / "result_0.json"))
        assert result["final_step"] == 30
        assert result["num_nodes"] == 2       # the world actually shrank
        assert result["resumed_from"] > 0     # resharded restore
    finally:
        _kill_all(launchers, master)


@pytest.mark.timeout(500)
def test_two_nodes_grow_to_three_on_join(tmp_path):
    """The scale-UP half of elasticity: a third node joins mid-run; the
    running agents detect the membership change, checkpoint, restart as
    a 3-node world, and training finishes with all three."""
    env = _env(tmp_path)
    master, addr = _start_master(
        tmp_path, env, min_nodes=2, max_nodes=3,
        extra=["--rdzv-timeout", "8"],
    )

    launchers = {
        nid: _elastic_launcher(env, addr, tmp_path, nid)
        for nid in (0, 1)
    }
    joined = False
    try:
        deadline = time.time() + 360
        while time.time() < deadline:
            # break when every launcher spawned SO FAR has exited: a
            # pre-join startup failure must fail fast, not burn the
            # whole deadline
            if all(p.poll() is not None for p in launchers.values()):
                break
            if not joined and (tmp_path / "ckpt" / "latest").exists():
                # the 2-node world is training: bring in node 2
                launchers[2] = _elastic_launcher(env, addr, tmp_path, 2)
                joined = True
            time.sleep(0.5)
        assert joined, "checkpoint never appeared"
        outs = {nid: _drain(p) for nid, p in launchers.items()}
        for nid, p in launchers.items():
            assert p.returncode == 0, (nid, outs[nid][-4000:])
        result = json.load(open(tmp_path / "result_0.json"))
        assert result["final_step"] == 30
        assert result["num_nodes"] == 3       # the world actually grew
        assert result["resumed_from"] > 0     # restored mid-run
    finally:
        _kill_all(launchers, master)


@pytest.mark.timeout(500)
def test_two_node_kill_one_trainer_recovers(tmp_path):
    goodput_log = str(tmp_path / "goodput.jsonl")
    launchers, outs, killed = _run_two_nodes(
        tmp_path, ["--max-steps", "30", "--ckpt-interval", "5",
                   "--goodput-log", goodput_log],
        kill_after_ckpt=True,
    )
    assert killed, "never saw a checkpoint to kill after"
    for p, out in zip(launchers, outs):
        assert p.returncode == 0, out[-4000:]
    result = json.load(open(tmp_path / "result_0.json"))
    assert result["final_step"] == 30
    assert result["num_nodes"] == 2
    assert result["resumed_from"] > 0
    joint = "\n".join(outs)
    assert "resumed from step" in joint
    # goodput accounting over the CPU-mesh multinode failure scenario
    # (the reference's headline metric, measured for real in bench.py)
    from dlrover_tpu.utils.goodput import compute_goodput

    r = compute_goodput(goodput_log)
    assert r.n_steps == 30
    assert r.n_incarnations >= 2
    assert 0.0 < r.goodput <= 1.0
    # telemetry acceptance: the report over the journal this run produced
    # agrees with goodput's (total - productive) within 5%, and the trace
    # id propagated master -> agents -> trainers
    from dlrover_tpu.telemetry.report import build_report, load_events

    events = load_events(str(tmp_path / "journal"))
    assert events, "journal never written"
    traces = {e["trace"] for e in events if e.get("trace")}
    assert len(traces) == 1, f"expected one job trace, got {traces}"
    procs = {e["proc"] for e in events}
    assert len(procs) >= 2, f"journal only saw {procs}"
    names = {e["name"] for e in events}
    assert "rdzv_round" in names          # master-side span
    assert "node_restart" in names        # agent-side recovery span
    report = build_report(str(tmp_path / "journal"),
                          goodput_log=goodput_log)
    assert abs(report.lost_s - r.lost_s) <= 0.05 * max(r.lost_s, 0.1)
    assert report.categories["respawn"] > 0.0
