"""Mesh / partition / strategy / train-step tests on the 8-device CPU mesh.

Mirrors the reference's parallel-group layout assertions
(atorch/atorch/tests/common_tests/distributed_test.py:160) as sharding-spec
assertions on a virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.models import transformer as T
from dlrover_tpu.parallel import strategy as S
from dlrover_tpu.parallel.mesh import MeshSpec, build_mesh, data_parallel_size
from dlrover_tpu.parallel.partition import spec_for
from dlrover_tpu.trainer import compile_train

CFG = T.CONFIGS["tiny"]


def _compile(strat, mesh):
    return compile_train(
        strategy=strat,
        mesh=mesh,
        loss_fn=lambda p, b: T.loss_fn(p, b, CFG),
        init_params_fn=lambda rng: T.init_params(CFG, rng),
        logical_params=T.logical_axes(CFG),
        optimizer=optax.adamw(1e-3),
    )


class TestMesh:
    def test_resolve_fill(self):
        assert MeshSpec({"data": -1}).resolved(8) == {"data": 8}
        assert MeshSpec({"fsdp": 4, "tensor": -1}).resolved(8) == {
            "fsdp": 4, "tensor": 2,
        }

    def test_canonical_order(self):
        sizes = MeshSpec({"tensor": 2, "data": 4}).resolved(8)
        assert list(sizes) == ["data", "tensor"]

    def test_errors(self):
        with pytest.raises(ValueError):
            MeshSpec({"data": 3}).resolved(8)
        with pytest.raises(ValueError):
            MeshSpec({"data": -1, "fsdp": -1}).resolved(8)
        with pytest.raises(ValueError):
            MeshSpec({"bogus": 2}).resolved(8)

    def test_build(self):
        mesh = build_mesh({"fsdp": 4, "tensor": 2})
        assert mesh.shape == {"fsdp": 4, "tensor": 2}
        assert data_parallel_size(mesh) == 4


class TestPartition:
    def test_hybrid_dcn_mesh(self):
        """dcn_axes build a hybrid (multi-slice) mesh; on CPU test
        devices the slice topology is emulated by layout."""
        mesh = build_mesh(
            MeshSpec(axes={"data": 4, "tensor": 2}, dcn_axes={"data": 2})
        )
        assert mesh.shape == {"data": 4, "tensor": 2}

    def test_hybrid_dcn_strategy_trains(self):
        """A strategy whose data axis spans DCN compiles and steps."""
        strat = S.Strategy(
            name="dcn_dp",
            mesh_axes={"data": 4, "tensor": 2},
            dcn_axes={"data": 2},
            rules=[["batch", ["data", "fsdp"]],
                   ["heads", "tensor"], ["mlp", "tensor"],
                   ["kv_heads", "tensor"], ["vocab", "tensor"]],
        )
        assert S.Strategy.from_json(strat.to_json()).dcn_axes == {"data": 2}
        mesh = strat.build_mesh()
        ct = _compile(strat, mesh)
        state = ct.init(jax.random.PRNGKey(0))
        tok = jax.random.randint(
            jax.random.PRNGKey(1), (1, 4, 33), 0, CFG.vocab_size
        )
        _, metrics = ct.step(state, {"tokens": tok})
        assert np.isfinite(float(metrics["loss"]))

    def test_dcn_errors(self):
        with pytest.raises(ValueError, match="not among resolved"):
            build_mesh(MeshSpec(axes={"data": 8}, dcn_axes={"tensor": 2}))
        with pytest.raises(ValueError, match="does not divide"):
            build_mesh(MeshSpec(axes={"data": 8}, dcn_axes={"data": 3}))

    def test_missing_axis_replicates(self):
        mesh = build_mesh({"data": 8})
        spec = spec_for(("embed", "heads"), [("heads", "tensor")], mesh)
        assert spec == P()  # tensor axis absent -> fully replicated

    def test_axis_used_once(self):
        mesh = build_mesh({"fsdp": 8})
        spec = spec_for(
            ("embed", "mlp"), [("embed", "fsdp"), ("mlp", "fsdp")], mesh
        )
        assert spec == P("fsdp")  # second dim can't reuse the axis

    def test_multi_axis_dim(self):
        mesh = build_mesh({"data": 4, "fsdp": 2})
        spec = spec_for(("batch",), [("batch", ("data", "fsdp"))], mesh)
        assert spec == P(("data", "fsdp"))


class TestStrategies:
    @pytest.mark.parametrize("name,kwargs,expect_wq", [
        ("dp", {}, P()),
        ("fsdp", {}, P(None, "fsdp")),
        ("fsdp_tp", {"tensor_size": 2, "fsdp_size": 4},
         P(None, "fsdp", "tensor")),
        ("tp", {"tensor_size": 4}, P(None, None, "tensor")),
    ])
    def test_param_shardings(self, name, kwargs, expect_wq):
        strat = S.PRESETS[name](**kwargs)
        mesh = strat.build_mesh()
        ct = _compile(strat, mesh)
        state = ct.init(jax.random.PRNGKey(0))
        assert state.params["layers"]["wq"].sharding.spec == expect_wq

    def test_opt_state_follows_params(self):
        strat = S.fsdp(8)
        mesh = strat.build_mesh()
        ct = _compile(strat, mesh)
        state = ct.init(jax.random.PRNGKey(0))
        # adamw state: (ScaleByAdamState(count, mu, nu), ...) — mu follows
        mu = state.opt_state[0].mu
        assert mu["layers"]["wq"].sharding.spec == P(None, "fsdp")
        assert mu["embed"].sharding.spec == P("fsdp")

    def test_train_two_steps_loss_decreases(self):
        strat = S.fsdp(8)
        mesh = strat.build_mesh()
        ct = _compile(strat, mesh)
        state = ct.init(jax.random.PRNGKey(0))
        batch = jax.device_put(
            {"tokens": np.random.RandomState(0).randint(
                0, CFG.vocab_size, (1, 16, 33))},
            ct.batch_sharding,
        )
        state, m0 = ct.step(state, batch)
        state, m1 = ct.step(state, batch)
        assert float(m1["loss"]) < float(m0["loss"])
        assert int(state.step) == 2

    def test_serialization_roundtrip(self, tmp_path):
        s = S.fsdp_tp(tensor_size=2, fsdp_size=4, remat="dots")
        path = tmp_path / "strategy.json"
        s.save(str(path))
        s2 = S.Strategy.load(str(path))
        assert s2 == s

    def test_grad_accum_matches_large_batch(self):
        """accum=2 × micro=8 must match accum=1 × batch=16 (fixed global
        batch invariance — the ElasticTrainer contract). SGD so the update
        is linear in the gradient: Adam's first-step sign normalization
        would amplify bf16 forward noise to ±lr and mask the comparison."""
        strat = S.dp()
        mesh = strat.build_mesh()
        ct = compile_train(
            strategy=strat,
            mesh=mesh,
            loss_fn=lambda p, b: T.loss_fn(p, b, CFG),
            init_params_fn=lambda rng: T.init_params(CFG, rng),
            logical_params=T.logical_axes(CFG),
            optimizer=optax.sgd(0.1),
        )
        rng = np.random.RandomState(1)
        tokens = rng.randint(0, CFG.vocab_size, (16, 33))

        state_a = ct.init(jax.random.PRNGKey(7))
        batch_a = jax.device_put(
            {"tokens": tokens.reshape(1, 16, 33)}, ct.batch_sharding)
        state_a, _ = ct.step(state_a, batch_a)

        state_b = ct.init(jax.random.PRNGKey(7))
        batch_b = jax.device_put(
            {"tokens": tokens.reshape(2, 8, 33)}, ct.batch_sharding)
        state_b, _ = ct.step(state_b, batch_b)

        diffs = jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            state_a.params, state_b.params,
        )
        # tolerance: bf16 forward noise × lr (reduction order differs
        # between the scanned and unscanned accumulation)
        assert max(jax.tree.leaves(diffs)) < 2e-4, diffs
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow

    def test_remat_same_loss(self):
        base = S.dp()
        remat = S.dp()
        remat.remat = "full"
        mesh = base.build_mesh()
        tokens = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 8, 33))
        losses = []
        for strat in (base, remat):
            ct = _compile(strat, mesh)
            state = ct.init(jax.random.PRNGKey(0))
            _, m = ct.step(
                state, jax.device_put({"tokens": tokens}, ct.batch_sharding))
            losses.append(float(m["loss"]))
        assert losses[0] == pytest.approx(losses[1], rel=1e-5)


class TestDryRun:
    def test_pick(self):
        from dlrover_tpu.parallel import pick_strategy

        def build(strat):
            mesh = strat.build_mesh()
            ct = _compile(strat, mesh)
            state_shape = jax.eval_shape(
                lambda: ct.init(jax.random.PRNGKey(0)))
            batch = {"tokens": jax.ShapeDtypeStruct((1, 8, 33), jnp.int32)}
            return ct.step, (state_shape, batch)

        best, reports = pick_strategy(build, [S.fsdp(8), S.dp()],
                                      objective="first_fit")
        assert best.name == "fsdp"
        assert all(r.ok for r in reports)

    def test_bad_candidate_reported(self):
        from dlrover_tpu.parallel import pick_strategy

        def build(strat):
            raise RuntimeError("boom")

        bad = S.dp()
        with pytest.raises(RuntimeError, match="no candidate"):
            pick_strategy(build, [bad])


class TestTransformerVariants:
    @pytest.mark.parametrize("variant", ["llama", "gpt2"])
    def test_forward_shapes(self, variant):
        import dataclasses

        cfg = dataclasses.replace(CFG, variant=variant)
        params = T.init_params(cfg, jax.random.PRNGKey(0))
        logits = T.forward(
            params, jnp.zeros((2, 16), jnp.int32), cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_gqa(self):
        import dataclasses

        cfg = dataclasses.replace(CFG, n_kv_heads=2)
        params = T.init_params(cfg, jax.random.PRNGKey(0))
        assert params["layers"]["wk"].shape[2] == 2
        logits = T.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)
        assert logits.shape == (1, 8, cfg.vocab_size)

    def test_param_count_property(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        actual = sum(x.size for x in jax.tree.leaves(params))
        assert actual == CFG.param_count


class TestAutoStrategy:
    def _pick(self, hbm_bytes, cfg=None, batch=8, **kwargs):
        import optax

        from dlrover_tpu.parallel.auto import auto_strategy

        cfg = cfg or T.CONFIGS["tiny"]
        example_batch = {
            "tokens": np.zeros((1, batch, cfg.max_seq_len + 1), np.int32)
        }
        return auto_strategy(
            loss_fn_for=lambda s, m: T.make_loss_fn(cfg, s, m),
            init_params_fn=lambda rng: T.init_params(cfg, rng),
            logical_params=T.logical_axes(cfg),
            optimizer=optax.adamw(1e-3),
            example_batch=example_batch,
            hbm_capacity_bytes=hbm_bytes,
            **kwargs,
        )

    # slow tier (tier-1 envelope): full multi-candidate compile cycle —
    # tens of seconds each on XLA:CPU. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_cached_auto_strategy_reuses_and_rekeys(self, tmp_path):
        """The load_strategy analog: the second call reloads the tuned
        pick (no search — instant, no reports), and a cache written for
        a different device count is ignored."""
        import json
        import time

        from dlrover_tpu.parallel.auto import cached_auto_strategy

        from dlrover_tpu.parallel.strategy import dp, zero2

        cache = str(tmp_path / "strategy.json")
        cfg = T.CONFIGS["tiny"]
        kwargs = dict(
            loss_fn_for=lambda s, m: T.make_loss_fn(cfg, s, m),
            init_params_fn=lambda rng: T.init_params(cfg, rng),
            logical_params=T.logical_axes(cfg),
            optimizer=optax.adamw(1e-3),
            example_batch={"tokens": np.zeros((1, 8, 33), np.int32)},
            hbm_capacity_bytes=0,
            # this test pins CACHING semantics (reuse/rekey), not
            # candidate breadth — the selection tests below cover that;
            # two candidates instead of five keeps the three searches
            # this test runs off the suite's critical path
            candidates=[dp(), zero2()],
        )
        s1, reports = cached_auto_strategy(cache, **kwargs)
        assert reports  # a real search ran
        t0 = time.monotonic()
        s2, reports2 = cached_auto_strategy(cache, **kwargs)
        assert time.monotonic() - t0 < 1.0  # reload, not re-search
        assert reports2 == []
        assert s2 == s1
        # a cache for a different workload fingerprint (other model,
        # batch, budget, or world size) must not be reused
        data = json.load(open(cache))
        data["fingerprint"] = "someone-elses-workload"
        json.dump(data, open(cache, "w"))
        s3, reports3 = cached_auto_strategy(cache, **kwargs)
        assert reports3  # searched again
        assert json.load(open(cache))["devices"] == 8  # rewritten
        # changed batch shape -> different fingerprint -> fresh search
        kwargs2 = dict(kwargs)
        kwargs2["example_batch"] = {
            "tokens": np.zeros((1, 16, 33), np.int32)
        }
        _, reports4 = cached_auto_strategy(cache, **kwargs2)
        assert reports4

    # slow tier (tier-1 envelope): full multi-candidate compile cycle —
    # tens of seconds each on XLA:CPU. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_ample_memory_prefers_dp(self):
        # fastest objective: either replicated-param strategy may win
        # (zero1 distributes the optimizer's elementwise work, so its
        # estimate can edge out dp on tiny models — the math is equal)
        strategy, reports = self._pick(hbm_bytes=0)  # 0 = unlimited
        assert strategy.name in ("dp", "zero1", "zero2")
        assert reports[0].ok
        # first_fit keeps the strict preference order: dp wins outright
        strategy, _ = self._pick(hbm_bytes=0, objective="first_fit")
        assert strategy.name == "dp"

    # slow tier (tier-1 envelope): full multi-candidate compile cycle —
    # tens of seconds each on XLA:CPU. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_tight_memory_falls_to_sharded(self):
        """With a param-dominated model, a budget between FSDP's sharded
        footprint and DP's replicated one forces the sharded pick."""
        import dataclasses

        cfg = dataclasses.replace(
            T.CONFIGS["tiny"], d_model=512, n_layers=4, d_ff=1024,
            vocab_size=8192, n_heads=8, n_kv_heads=8,
        )
        _, reports = self._pick(hbm_bytes=0, cfg=cfg, batch=8)
        by_name = {r.strategy_name: r for r in reports}
        dp_need = by_name["dp"].hbm_bytes
        fsdp_need = by_name["fsdp"].hbm_bytes
        assert fsdp_need < dp_need, (dp_need, fsdp_need)
        budget = (dp_need + fsdp_need) // 2
        strategy, _ = self._pick(hbm_bytes=budget, cfg=cfg, batch=8)
        assert strategy.name in ("fsdp", "fsdp_tp")


class TestStrategyNumericEquivalence:
    # slow tier for COMPILE COST only (four full strategy compiles,
    # ~20s; tests/test_pipeline.py::test_matches_dp_loss carries the
    # cross-layout equivalence in tier-1). The bound is the
    # reduction-order-tolerant one: different shardings reassociate the
    # bf16-compute reduce trees on XLA:CPU (measured 0.1-0.3% here),
    # while a genuinely wrong sharding shifts the loss by O(1).
    @pytest.mark.slow
    def test_same_loss_across_strategies(self):
        """DP/FSDP/TP/FSDP+TP are layout choices, not math choices: the
        same params and batch produce the same loss on every mesh
        (within the reduction-order bound)."""
        import optax
        from functools import partial

        from dlrover_tpu.parallel import strategy as S
        from dlrover_tpu.trainer.train_step import compile_train

        cfg = T.CONFIGS["tiny"]
        tokens = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (1, 8, cfg.max_seq_len + 1), np.int32
        )
        losses = {}
        for strat in (S.dp(), S.fsdp(remat="none"), S.tp(tensor_size=2),
                      S.fsdp_tp(tensor_size=2, remat="none")):
            mesh = strat.build_mesh()
            compiled = compile_train(
                strategy=strat, mesh=mesh,
                loss_fn=T.make_loss_fn(cfg, strat, mesh),
                init_params_fn=lambda rng: T.init_params(cfg, rng),
                logical_params=T.logical_axes(cfg),
                optimizer=optax.adamw(1e-3),
            )
            state = compiled.init(jax.random.PRNGKey(0))
            batch = jax.device_put(
                {"tokens": tokens}, compiled.batch_sharding
            )
            _, metrics = compiled.step(state, batch)
            losses[strat.name] = float(jax.device_get(metrics["loss"]))
        from tests.test_pipeline import RTOL_CROSS_LAYOUT

        ref = losses["dp"]
        for name, loss in losses.items():
            assert loss == pytest.approx(ref, rel=RTOL_CROSS_LAYOUT), \
                losses


    def test_zero1_shards_opt_state_and_matches_dp(self):
        """ZeRO-1: Adam moments shard over the data axis (memory /8 on
        the 8-device mesh) while params stay replicated, and the losses
        match dp exactly — it is a layout choice, not an algorithm."""
        import dataclasses

        from dlrover_tpu.trainer.train_step import compile_train

        cfg = dataclasses.replace(T.CONFIGS["tiny"], dtype="float32")
        tokens = np.random.RandomState(5).randint(
            0, cfg.vocab_size, (1, 8, 33)
        )
        losses = {}
        shardings = {}
        for name in ("dp", "zero1"):
            strat = S.PRESETS[name]()
            mesh = strat.build_mesh()
            ct = compile_train(
                strategy=strat, mesh=mesh,
                loss_fn=T.make_loss_fn(cfg, strat, mesh),
                init_params_fn=lambda rng: T.init_params(cfg, rng),
                logical_params=T.logical_axes(cfg),
                optimizer=optax.adamw(1e-3),
            )
            state = ct.init(jax.random.PRNGKey(0))
            ls = []
            for _ in range(3):
                state, m = ct.step(
                    state,
                    jax.device_put({"tokens": tokens}, ct.batch_sharding),
                )
                ls.append(float(jax.device_get(m["loss"])))
            losses[name] = ls
            shardings[name] = ct.state_shardings
        assert losses["dp"] == pytest.approx(losses["zero1"], rel=1e-6)
        # params replicated in both; moments sharded only under zero1
        z_opt = [
            s.spec for s in jax.tree_util.tree_leaves(
                shardings["zero1"].opt_state,
                is_leaf=lambda x: hasattr(x, "spec"),
            )
        ]
        assert any(spec != P() for spec in z_opt), z_opt
        z_params = jax.tree_util.tree_leaves(
            shardings["zero1"].params,
            is_leaf=lambda x: hasattr(x, "spec"),
        )
        assert all(s.spec == P() for s in z_params)

    # slow tier (tier-1 envelope): full multi-candidate compile cycle —
    # tens of seconds each on XLA:CPU. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_zero2_matches_dp_and_reduce_scatters(self):
        """ZeRO-2: grads constrained to the moment layout — same losses
        as dp, and the compiled step shows the scatter pattern. XLA:CPU
        has no fused reduce-scatter op: it lowers the constraint as
        all-reduce + dynamic-slice (TPU fuses them), so the portable
        assertion is sharded-state machinery (all-gathers for the
        update) that plain dp's step does not contain."""
        import dataclasses

        from dlrover_tpu.trainer.train_step import compile_train

        cfg = dataclasses.replace(T.CONFIGS["tiny"], dtype="float32")
        tokens = np.random.RandomState(6).randint(
            0, cfg.vocab_size, (1, 8, 33)
        )
        losses = {}
        gathers = {}
        for name in ("dp", "zero2"):
            strat = S.PRESETS[name]()
            mesh = strat.build_mesh()
            ct = compile_train(
                strategy=strat, mesh=mesh,
                loss_fn=T.make_loss_fn(cfg, strat, mesh),
                init_params_fn=lambda rng: T.init_params(cfg, rng),
                logical_params=T.logical_axes(cfg),
                optimizer=optax.adamw(1e-3),
            )
            state = ct.init(jax.random.PRNGKey(0))
            batch = jax.device_put({"tokens": tokens}, ct.batch_sharding)
            hlo = ct.step.lower(state, batch).compile().as_text()
            gathers[name] = hlo.count("all-gather")
            ls = []
            for _ in range(3):
                state, m = ct.step(state, batch)
                ls.append(float(jax.device_get(m["loss"])))
            losses[name] = ls
        assert losses["dp"] == pytest.approx(losses["zero2"], rel=1e-6)
        assert gathers["dp"] == 0, gathers
        assert gathers["zero2"] > 0, gathers


class TestRematPolicies:
    # slow tier (tier-1 envelope): full multi-candidate compile cycle —
    # tens of seconds each on XLA:CPU. `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_blockwise_ce_matches_full(self):
        """ce_chunks must not change the loss or its gradients — it only
        changes what lands in HBM."""
        import dataclasses

        cfg = dataclasses.replace(CFG, dtype="float32")
        params = T.init_params(cfg, jax.random.PRNGKey(0))
        tok = jax.random.randint(
            jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size
        )
        mask = (jax.random.uniform(jax.random.PRNGKey(2), (4, 33)) > 0.2)
        for batch in [{"tokens": tok}, {"tokens": tok, "mask": mask}]:
            ref, ref_g = jax.value_and_grad(
                lambda p: T.loss_fn(p, batch, cfg)
            )(params)
            for chunks in [4, 7, 128]:  # 7 -> falls back to a divisor
                cfg_c = dataclasses.replace(cfg, ce_chunks=chunks)
                got, got_g = jax.value_and_grad(
                    lambda p: T.loss_fn(p, batch, cfg_c)
                )(params)
                np.testing.assert_allclose(
                    float(got), float(ref), rtol=1e-5,
                    err_msg=f"chunks={chunks}",
                )
                jax.tree.map(
                    lambda a, b: np.testing.assert_allclose(
                        np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
                    ),
                    got_g, ref_g,
                )

    def test_blockwise_ce_mup_scale(self):
        import dataclasses

        cfg = dataclasses.replace(
            CFG, dtype="float32", mup_base_width=32
        )
        params = T.init_params(cfg, jax.random.PRNGKey(0))
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (2, 17), 0, cfg.vocab_size
        )}
        ref = T.loss_fn(params, batch, cfg)
        cfg_c = dataclasses.replace(cfg, ce_chunks=8)
        got = T.loss_fn(params, batch, cfg_c)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_save_attn_same_loss_as_nothing(self):
        import dataclasses
        import optax
        from dlrover_tpu.trainer.train_step import compile_train

        tokens = np.random.RandomState(3).randint(
            0, T.CONFIGS["tiny"].vocab_size, (1, 8, 33)
        )
        losses = []
        for policy in ("nothing", "save_attn"):
            cfg = dataclasses.replace(
                T.CONFIGS["tiny"], remat_scan=True, remat_policy=policy
            )
            strat = S.dp()
            mesh = strat.build_mesh()
            ct = compile_train(
                strategy=strat, mesh=mesh,
                loss_fn=T.make_loss_fn(cfg, strat, mesh),
                init_params_fn=lambda rng: T.init_params(cfg, rng),
                logical_params=T.logical_axes(cfg),
                optimizer=optax.adamw(1e-3),
            )
            state = ct.init(jax.random.PRNGKey(0))
            state, m = ct.step(
                state,
                jax.device_put({"tokens": tokens}, ct.batch_sharding),
            )
            # a second step exercises gradients THROUGH the remat policy
            state, m = ct.step(
                state,
                jax.device_put({"tokens": tokens}, ct.batch_sharding),
            )
            losses.append(float(jax.device_get(m["loss"])))
        assert losses[0] == pytest.approx(losses[1], rel=2e-4), losses

    def test_offload_policy_grads(self):
        """offload_attn_ffn (activations to pinned host memory — the
        SelectiveOffloadingCheckpoint analog) must produce finite grads
        and the same loss as the non-offloaded policy."""
        import dataclasses

        tokens = {"tokens": jnp.asarray(np.random.RandomState(1).randint(
            0, 512, (2, 65)), jnp.int32)}
        losses = []
        for policy in ("save_attn_ffn", "offload_attn_ffn"):
            cfg = dataclasses.replace(
                T.CONFIGS["tiny"], remat_scan=True, remat_policy=policy)
            params = T.init_params(cfg, jax.random.PRNGKey(0))
            loss, g = jax.jit(jax.value_and_grad(
                lambda p: T.loss_fn(p, tokens, cfg=cfg)))(params)
            assert all(
                bool(jnp.all(jnp.isfinite(x.astype(jnp.float32))))
                for x in jax.tree_util.tree_leaves(g)
            )
            losses.append(float(loss))
        assert losses[0] == pytest.approx(losses[1], rel=1e-5), losses
    # slow tier (tier-1 envelope): among the heaviest bodies in this
    # file on XLA:CPU; core behavior stays covered by the lighter
    # tests in-tier. `pytest tests/` still runs it.
    @pytest.mark.slow

    def test_remat_interval_grad_parity(self):
        """Interleaved remat (remat_interval=2: only every other layer
        rematted, halving backward recompute) must produce the same
        gradients as per-layer remat, within the existing bf16 remat
        noise floor (measured: remat itself differs from no-remat by
        ~2.6e-3 on tiny)."""
        import dataclasses

        cfg1 = dataclasses.replace(
            T.CONFIGS["tiny"], remat_scan=True, remat_policy="nothing",
            n_layers=4,
        )
        cfg2 = dataclasses.replace(cfg1, remat_interval=2)
        cfg_bad = dataclasses.replace(cfg1, remat_interval=3)  # 4 % 3 != 0
        cfg_off = dataclasses.replace(cfg1, remat_scan=False,
                                      remat_interval=2)
        params = T.init_params(cfg1, jax.random.PRNGKey(0))
        tokens = {"tokens": jnp.asarray(np.random.RandomState(0).randint(
            0, 512, (2, 65)), jnp.int32)}
        g1 = jax.grad(lambda p: T.loss_fn(p, tokens, cfg=cfg1))(params)
        g2 = jax.grad(lambda p: T.loss_fn(p, tokens, cfg=cfg2))(params)
        diff = max(
            float(jnp.max(jnp.abs(a - b)))
            for a, b in zip(jax.tree_util.tree_leaves(g1),
                            jax.tree_util.tree_leaves(g2))
        )
        assert diff < 5e-3, diff
        with pytest.raises(ValueError, match="remat_interval"):
            T.loss_fn(params, tokens, cfg=cfg_bad)
        # interval without remat_scan must error, not silently ignore
        with pytest.raises(ValueError, match="remat_interval"):
            T.loss_fn(params, tokens, cfg=cfg_off)
