"""Per-host parallel persist + topology-changing verified restore
(DESIGN.md §20).

Covers the PR-9 tentpole end to end: the object-store storage contract,
replica-group dedup on the write path, quorum restore semantics
(partial-manifest and missing-writer steps skipped, per-shard rollback
to the replica twin), N→M→N restore bit-exactness for M<N and M>N, the
persist-ack RPC, the typed persist/restore timeout results, the canned
sharded chaos scenario's replay-identical trail, and the gateway
replica AOT cold-start wiring.

Multi-host saves are simulated with several solo-mode engines sharing a
checkpoint dir (the CPU backend cannot run multiprocess collectives in
this container; everything under test — storage, commit, verify,
reassembly — is process-count-agnostic).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.checkpoint import integrity
from dlrover_tpu.checkpoint.engine import (
    CheckpointEngine,
    PersistWait,
    RestorePrefetch,
    _storage_fallback_leaf,
)
from dlrover_tpu.checkpoint.sharded import (
    ShardedCheckpointEngine,
    assemble,
    storage_piece_registry,
)
from dlrover_tpu.common.storage import CheckpointStorage, PosixDiskStorage


# ------------------------------------------------------- storage contract


class TestStorageContract:
    """Semantics any CheckpointStorage backend must satisfy; run any new
    backend class through this by overriding ``storage``/``root``."""

    @pytest.fixture()
    def storage(self):
        return PosixDiskStorage()

    def test_write_parallel_matches_write(self, storage, tmp_path):
        blob = np.random.default_rng(0).bytes(3 << 20)
        a = str(tmp_path / "a.bin")
        b = str(tmp_path / "b.bin")
        storage.write(blob, a)
        storage.write_parallel(blob, b, chunk_bytes=1 << 20, workers=3)
        assert storage.read(a) == storage.read(b) == blob
        assert storage.size(b) == len(blob)

    def test_write_parallel_is_atomic(self, storage, tmp_path):
        path = str(tmp_path / "x.bin")
        storage.write_parallel(b"v1" * 100, path)
        storage.write_parallel(b"v2" * 100, path, chunk_bytes=1 << 20)
        assert storage.read(path) == b"v2" * 100
        # no tmp debris left behind
        assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    def test_read_range_semantics(self, storage, tmp_path):
        path = str(tmp_path / "r.bin")
        blob = bytes(range(256)) * 16
        storage.write(blob, path)
        assert storage.read_range(path, 0, 10) == blob[:10]
        assert storage.read_range(path, 100, 50) == blob[100:150]
        # short only at end-of-object (ranged-GET semantics)
        assert storage.read_range(path, len(blob) - 4, 100) == blob[-4:]

    def test_default_impls_fall_back_to_whole_blob(self, tmp_path):
        class MinimalStorage(CheckpointStorage):
            def __init__(self):
                self.blobs: dict[str, bytes] = {}

            def write(self, content, path):
                self.blobs[path] = (
                    content if isinstance(content, bytes)
                    else content.encode()
                )

            def read(self, path):
                return self.blobs[path]

            def exists(self, path):
                return path in self.blobs

            def listdir(self, path):
                return sorted(
                    p[len(path) + 1:] for p in self.blobs
                    if p.startswith(path + "/")
                )

            def makedirs(self, path):
                pass

            def delete(self, path):
                self.blobs.pop(path, None)

        s = MinimalStorage()
        s.write_parallel(b"hello world", "k")
        assert s.read("k") == b"hello world"
        assert s.read_range("k", 6, 5) == b"world"
        assert s.size("k") == 11


# ------------------------------------------------- multi-host save helper


def _host_pieces(data: np.ndarray, i: int, hosts: int,
                 twins: bool) -> tuple[dict, dict]:
    """Host ``i`` owns rows [i*k,(i+1)*k) as replica 0; with ``twins``
    it also carries host i-1's rows as the replica-1 ring twin."""
    rows, cols = data.shape
    k = rows // hosts
    holders = [(0, i)] + ([(1, (i - 1) % hosts)] if twins else [])
    pieces, index = {}, {}
    for replica, owner in holders:
        key = f"w::piece{replica}"
        pieces[key] = data[owner * k:(owner + 1) * k]
        index[key] = {
            "path": "w", "global_shape": [rows, cols],
            "dtype": "float32",
            "index": [[owner * k, (owner + 1) * k], [0, cols]],
            "replica": replica, "persist": True,
        }
    return pieces, index


def _save_hosts(ckpt_dir: str, legs, hosts: int, twins: bool = False):
    """N solo engines persist ``legs`` = [(step, data, skip), ...] in
    order; rank 0 joins each commit. Hosts in a leg's ``skip`` snapshot
    but never persist (died mid-save). One engine set serves every leg
    — engine construction (shm + IPC servers) dominates test wall time
    otherwise."""
    engines = [
        ShardedCheckpointEngine(ckpt_dir, node_id=i, node_rank=i,
                                world_size=hosts)
        for i in range(hosts)
    ]
    try:
        for step, data, skip in legs:
            for i, eng in enumerate(engines):
                pieces, index = _host_pieces(data, i, hosts, twins)
                eng.snapshot_pieces(step, pieces, index)
                if i != 0 and i not in skip:
                    eng._solo_saver._persist_step(step)
            if 0 not in skip:
                engines[0]._solo_saver._persist_step(
                    step, commit_block_s=0.0 if skip else 30.0
                )
    finally:
        for eng in engines:
            eng.shm_handler.close(unlink=True)
            eng.close()


STORAGE = PosixDiskStorage()


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _restore_rows(ckpt_dir: str, rows: int, cols: int,
                  m_hosts: int) -> tuple[int, np.ndarray, list[str]]:
    plan = integrity.resolve_restore_plan(STORAGE, ckpt_dir)
    assert plan is not None
    registry = storage_piece_registry(
        STORAGE, ckpt_dir, plan.step, plan.num_shards,
        bad_pieces=plan.bad_pieces,
    )
    bounds = [round(rows * j / m_hosts) for j in range(m_hosts + 1)]
    parts = [
        assemble([[bounds[j], bounds[j + 1]], [0, cols]],
                 np.dtype("float32"), registry["w"])
        for j in range(m_hosts)
    ]
    return plan.step, np.concatenate(parts), sorted(plan.bad_pieces)


class TestQuorumRestore:
    ROWS, COLS, HOSTS = 24, 8, 3

    def _data(self, step: int) -> np.ndarray:
        rng = np.random.default_rng(1000 + step)
        return rng.standard_normal((self.ROWS, self.COLS)).astype(
            np.float32)

    def test_replica_dedup_writes_each_shard_once(self, tmp_ipc_dir,
                                                  tmp_path):
        """replicas=1: every global piece index appears exactly once
        across all node files — no write amplification."""
        ckpt = str(tmp_path / "ckpt")
        _save_hosts(ckpt, [(3, self._data(3), set())], self.HOSTS)
        seen = []
        sdir = os.path.join(ckpt, "step-3")
        for i in range(self.HOSTS):
            meta = json.loads(
                open(os.path.join(sdir, f"node_{i}.meta.json")).read())
            for entry in meta["sharded_index"].values():
                seen.append(tuple(map(tuple, entry["index"])))
        assert len(seen) == len(set(seen)) == self.HOSTS

    def test_nonpersist_pieces_stay_out_of_storage(self, tmp_ipc_dir,
                                                   tmp_path,
                                                   monkeypatch):
        """Twin pieces exist in shm (full local coverage) but are
        stripped from the persisted bin when replicas=1."""
        ckpt = str(tmp_path / "ckpt")
        monkeypatch.setenv("DLROVER_TPU_CKPT_PERSIST_REPLICAS", "1")
        data = self._data(5)
        eng = ShardedCheckpointEngine(ckpt, node_id=0, node_rank=0,
                                      world_size=1)
        try:
            pieces, index = _host_pieces(data, 0, self.HOSTS, twins=True)
            # the ring twin is replica 1 -> persist=False at replicas=1
            index["w::piece1"]["persist"] = False
            eng.snapshot_pieces(5, pieces, index)
            eng._solo_saver._persist_step(5, commit_block_s=30.0)
            meta = json.loads(open(os.path.join(
                ckpt, "step-5", "node_0.meta.json")).read())
            assert list(meta["sharded_index"]) == ["w::piece0"]
            k = self.ROWS // self.HOSTS
            assert os.path.getsize(os.path.join(
                ckpt, "step-5", "node_0.bin")) == k * self.COLS * 4
            # shm snapshot still holds BOTH pieces (restart-in-place)
            raw = eng.shm_handler.header()
            assert set(raw["sharded_index"]) == {"w::piece0",
                                                 "w::piece1"}
        finally:
            eng.shm_handler.close(unlink=True)
            eng.close()

    def test_missing_writer_step_skipped(self, tmp_ipc_dir, tmp_path):
        """A host dead mid-save leaves no marker/ack: the step never
        commits and restore serves the previous one."""
        ckpt = str(tmp_path / "ckpt")
        _save_hosts(ckpt, [(3, self._data(3), set()),
                           (7, self._data(7), {2})], self.HOSTS)
        step, got, bad = _restore_rows(ckpt, self.ROWS, self.COLS, 2)
        assert step == 3 and bad == []
        assert _crc(got) == _crc(self._data(3))

    def test_partial_manifest_step_skipped(self, tmp_ipc_dir, tmp_path):
        """A commit manifest listing fewer writers than the world is
        incomplete — the quorum walk rejects it."""
        ckpt = str(tmp_path / "ckpt")
        _save_hosts(ckpt, [(3, self._data(3), set()),
                           (7, self._data(7), set())], self.HOSTS)
        sdir = os.path.join(ckpt, "step-7")
        marker = os.path.join(sdir, integrity.commit_marker(self.HOSTS))
        manifest = json.loads(open(marker).read())
        del manifest["shards"]["1"]
        with open(marker, "w") as f:
            json.dump(manifest, f)
        verdict = integrity.verify_step_quorum(STORAGE, sdir, self.HOSTS)
        assert verdict.fail_kind == "incomplete_manifest"
        step, got, _ = _restore_rows(ckpt, self.ROWS, self.COLS, 2)
        assert step == 3
        assert _crc(got) == _crc(self._data(3))

    def test_corrupt_shard_without_twin_rolls_whole_step(
            self, tmp_ipc_dir, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        _save_hosts(ckpt, [(3, self._data(3), set()),
                           (7, self._data(7), set())], self.HOSTS)
        path = os.path.join(ckpt, "step-7", "node_1.bin")
        blob = bytearray(open(path, "rb").read())
        blob[17] ^= 0x20
        with open(path, "wb") as f:
            f.write(bytes(blob))
        step, got, _ = _restore_rows(ckpt, self.ROWS, self.COLS, 2)
        assert step == 3
        assert _crc(got) == _crc(self._data(3))

    def test_per_shard_rollback_picks_replica_twin(self, tmp_ipc_dir,
                                                   tmp_path,
                                                   monkeypatch):
        """replicas=2: the corrupt primary's pieces restore from the
        ring twin — the step survives, newest data bit-exact."""
        monkeypatch.setenv("DLROVER_TPU_CKPT_PERSIST_REPLICAS", "2")
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR",
                           str(tmp_path / "journal"))
        ckpt = str(tmp_path / "ckpt")
        _save_hosts(ckpt, [(3, self._data(3), set()),
                           (7, self._data(7), set())], self.HOSTS,
                    twins=True)
        path = os.path.join(ckpt, "step-7", "node_1.bin")
        blob = bytearray(open(path, "rb").read())
        blob[5] ^= 0x01
        with open(path, "wb") as f:
            f.write(bytes(blob))
        step, got, bad = _restore_rows(ckpt, self.ROWS, self.COLS, 2)
        assert step == 7 and "1" in bad
        assert _crc(got) == _crc(self._data(7))
        events = [
            json.loads(line) for line in
            open(tmp_path / "journal" / "events.jsonl")
        ]
        rb = [e for e in events if e["name"] == "ckpt_shard_rollback"]
        assert rb and rb[0]["writer"] == "1" and rb[0]["step"] == 7

    def test_reshard_storage_fallback_leaf(self, tmp_ipc_dir, tmp_path):
        """The reshard path's missing-shard net: a leaf with no live
        copy assembles in full from the committed step."""
        ckpt = str(tmp_path / "ckpt")
        data = self._data(4)
        _save_hosts(ckpt, [(4, data, set())], self.HOSTS)
        box: list = []
        leaf = jax.ShapeDtypeStruct((self.ROWS, self.COLS), np.float32)
        got = _storage_fallback_leaf(STORAGE, ckpt, "w", leaf, box)
        assert got is not None
        np.testing.assert_array_equal(got, data)
        assert _storage_fallback_leaf(
            STORAGE, ckpt, "nope", leaf, box) is None


# ---------------------------------------------- topology-changing (jax)


def _owned_by(node: int, split: int):
    def owned(shard):
        return (shard.replica_id == 0
                and (shard.device.id < split) == (node == 0))
    return owned


class TestTopologyChangingRestore:
    """Save on N writers, restore onto smaller AND larger meshes,
    round-trip back — bit-exact at every hop."""

    def _mesh(self, n):
        from dlrover_tpu.parallel.mesh import build_mesh

        return build_mesh({"data": -1}, devices=jax.devices()[:n])

    def _state(self, mesh):
        s = {
            "w": jnp.arange(64, dtype=jnp.float32).reshape(16, 4),
            "b": jnp.arange(16, dtype=jnp.float32) * 0.5,
            "step": jnp.asarray(9, jnp.int32),
        }
        specs = {"w": P("data"), "b": P("data"), "step": P()}
        return {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in s.items()
        }, specs

    def test_n_to_m_to_n_bit_exact(self, tmp_ipc_dir, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        mesh8 = self._mesh(8)
        state, specs = self._state(mesh8)
        crcs = {k: _crc(np.asarray(jax.device_get(v)))
                for k, v in state.items()}
        e0 = ShardedCheckpointEngine(ckpt, node_id=0, node_rank=0,
                                     world_size=2,
                                     owned=_owned_by(0, 4))
        e1 = ShardedCheckpointEngine(ckpt, node_id=1, node_rank=1,
                                     world_size=2,
                                     owned=_owned_by(1, 4))
        try:
            assert e1.save_to_storage(9, state)
            assert e0.save_to_storage(9, state)
            assert e0.wait_for_persist(9, timeout=60)
        finally:
            for e in (e0, e1):
                e.shm_handler.close(unlink=True)
                e.close()

        # M < N: restore the 2-writer checkpoint onto 4 devices
        mesh4 = self._mesh(4)
        sh4 = {k: NamedSharding(mesh4, specs[k]) for k in state}
        em = ShardedCheckpointEngine(str(tmp_path / "ckpt"), node_id=5,
                                     world_size=1)
        try:
            loaded = em.load_sharded(state, sh4)
            assert loaded is not None and loaded[0] == 9
            small = loaded[1]
            for k in state:
                assert _crc(np.asarray(jax.device_get(small[k]))) \
                    == crcs[k], k
            # save from the shrunk world, then M > N: back onto 8
            ckpt2 = str(tmp_path / "ckpt2")
            e2 = ShardedCheckpointEngine(ckpt2, node_id=0, node_rank=0,
                                         world_size=1)
            try:
                assert e2.save_to_storage(10, small)
                assert e2.wait_for_persist(10, timeout=60)
                sh8 = {k: NamedSharding(mesh8, specs[k]) for k in state}
                e3 = ShardedCheckpointEngine(ckpt2, node_id=6,
                                             world_size=1)
                try:
                    back = e3.load_sharded(state, sh8)
                    assert back is not None and back[0] == 10
                    for k in state:
                        got = np.asarray(jax.device_get(back[1][k]))
                        assert _crc(got) == crcs[k], k
                        assert back[1][k].sharding.mesh.devices.size \
                            == 8
                finally:
                    e3.shm_handler.close(unlink=True)
                    e3.close()
            finally:
                e2.shm_handler.close(unlink=True)
                e2.close()
        finally:
            em.shm_handler.close(unlink=True)
            em.close()


# ------------------------------------------------------- persist-ack RPC


class TestPersistAckRPC:
    def test_ack_ledger_round_trip(self):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(port=0, rdzv_timeout=2.0)
        master.prepare()
        try:
            clients = [MasterClient(master.addr, i) for i in range(3)]
            entry = {"crc32": 7, "bytes": 11,
                     "pieces": {"w::p0": {"crc32": 7, "index": [[0, 4]],
                                          "replica": 0}}}
            for i, c in enumerate(clients[:2]):
                c.report_persist_ack(4, 3, dict(entry, crc32=i))
            st = clients[0].persist_status(4, 3)
            assert st.acked == 2 and not st.complete
            clients[2].report_persist_ack(4, 3, dict(entry, crc32=2))
            st = clients[0].persist_status(4, 3)
            assert st.complete and set(st.shards) == {"0", "1", "2"}
            assert st.shards["1"]["pieces"]["w::p0"]["index"] == [[0, 4]]
            # a different writer-world is a different ledger key
            assert not clients[0].persist_status(4, 2).complete
            for c in clients:
                c.close()
        finally:
            master.stop()


# ------------------------------------------------------ typed wait results


class TestTypedWaitResults:
    def test_wait_for_persist_timeout_is_typed_and_journaled(
            self, tmp_ipc_dir, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR",
                           str(tmp_path / "journal"))
        eng = CheckpointEngine(str(tmp_path / "ckpt"))
        try:
            res = eng.wait_for_persist(5, timeout=0.3)
            assert isinstance(res, PersistWait)
            assert not res and res.kind == "timeout"
            assert res.persisted_step == -1 and res.step == 5
            events = [
                json.loads(line) for line in
                open(tmp_path / "journal" / "events.jsonl")
            ]
            t = [e for e in events if e["name"] == "ckpt_persist_timeout"]
            assert t and t[0]["what"] == "persist" and t[0]["step"] == 5
        finally:
            eng.close()

    def test_wait_for_persist_ok_is_truthy(self, tmp_ipc_dir, tmp_path):
        eng = CheckpointEngine(str(tmp_path / "ckpt"))
        try:
            state = {"w": np.arange(8, dtype=np.float32)}
            assert eng.save_to_storage(3, state)
            res = eng.wait_for_persist(3, timeout=60)
            assert res and res.kind == "ok" and res.persisted_step >= 3
        finally:
            eng.close()

    def test_restore_prefetch_timeout_outcome(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR",
                           str(tmp_path / "journal"))

        class GlacialStorage(PosixDiskStorage):
            def listdir(self, path):
                time.sleep(1.5)
                return []

        pf = RestorePrefetch(str(tmp_path / "ckpt"), node_id=0,
                             storage=GlacialStorage())
        assert pf.join(timeout=0.2) is None
        assert pf.outcome == "timeout"
        events = [
            json.loads(line) for line in
            open(tmp_path / "journal" / "events.jsonl")
        ]
        t = [e for e in events if e["name"] == "ckpt_persist_timeout"]
        assert t and t[0]["what"] == "restore_prefetch"
        pf._done.wait(5)  # let the thread finish before teardown

    def test_restore_prefetch_ok_outcome(self, tmp_path):
        pf = RestorePrefetch(str(tmp_path / "none"), node_id=0)
        assert pf.join(timeout=10) is None
        assert pf.outcome == "empty"


# ------------------------------------------------- chaos canned scenario


class TestShardedChaosScenario:
    def test_replay_identical_trail_and_bit_exact_restore(
            self, tmp_ipc_dir, tmp_path):
        from dlrover_tpu.chaos.scenario import run_sharded_scenario

        r1 = run_sharded_scenario(str(tmp_path / "run1"), seed=4242)
        r1.assert_invariants()
        # the storage_read injection point left trail evidence
        points = {f[0] for f in r1.trail["faults"]}
        assert points == {"storage_write", "storage_read"}
        assert any(e[0] == "ckpt_shard_rollback"
                   for e in r1.trail["recovery"])
        r2 = run_sharded_scenario(str(tmp_path / "run2"), seed=4242)
        r2.assert_invariants()
        assert r1.trail == r2.trail

    def test_storage_read_injection_unit(self, tmp_path):
        from dlrover_tpu import chaos

        path = str(tmp_path / "f.bin")
        STORAGE.write(b"\x00" * 64, path)
        chaos.install({"seed": 1, "faults": [
            {"point": "storage_read", "action": "bit_flip", "times": 1},
            # consulted only once rule 1's budget is spent (fire()
            # stops at the first firing rule), i.e. from read 2 on
            {"point": "storage_read", "action": "missing", "times": 1},
        ]})
        try:
            flipped = STORAGE.read(path)
            assert flipped != b"\x00" * 64  # transient, read-side
            assert open(path, "rb").read() == b"\x00" * 64  # disk clean
            with pytest.raises(FileNotFoundError):
                STORAGE.read(path)
            assert STORAGE.read(path) == b"\x00" * 64  # budget spent
        finally:
            chaos.uninstall()


# ------------------------------------------- gateway AOT cold start


class TestGatewayAotColdStart:
    def test_replica_ready_journals_compile_cache_evidence(
            self, tmp_path, monkeypatch):
        from dlrover_tpu.gateway.pool import ReplicaPool, ReplicaState
        from dlrover_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )
        from dlrover_tpu.serving.engine import InferenceEngine

        monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cache"))
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR",
                           str(tmp_path / "journal"))
        cfg = TransformerConfig(vocab_size=64, n_layers=1, n_heads=2,
                                n_kv_heads=2, d_model=32,
                                max_seq_len=32)
        params = init_params(cfg, jax.random.PRNGKey(0))

        def factory():
            return InferenceEngine(params, cfg, slots=2, max_len=32)

        pool = ReplicaPool(factory, on_done=lambda w, r: None,
                           on_orphans=lambda o: None)
        try:
            pool.ensure(1)
            deadline = time.time() + 120
            while time.time() < deadline:
                if pool.ready_replicas():
                    break
                time.sleep(0.1)
            assert pool.ready_replicas()
            pool.ensure(2)
            while time.time() < deadline:
                if len(pool.ready_replicas()) == 2:
                    break
                time.sleep(0.1)
            assert len(pool.ready_replicas()) == 2
        finally:
            pool.stop()
        events = [
            json.loads(line) for line in
            open(tmp_path / "journal" / "events.jsonl")
        ]
        ready = sorted(
            (e for e in events if e["name"] == "gateway_replica_ready"),
            key=lambda e: e["replica"],
        )
        assert len(ready) == 2
        assert all(e["aot"] for e in ready)
        # the first replica compiled+published; the second loaded it
        assert ready[0]["aot_hit"] is False
        assert ready[1]["aot_hit"] is True
        assert ready[1]["aot_seconds"] < 2.0
