"""Master crash-failover (DESIGN.md §26): full-state snapshot v2,
epoch fencing, agent re-dial/reconcile/redelivery, and the master-kill
chaos acceptance.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import EnvKey


def _crash(master) -> None:
    """Abrupt master death for in-process tests: the RPC server stops
    answering and the state loop is frozen WITHOUT the final snapshot
    (a SIGKILL writes nothing)."""
    master._server.stop()
    master.node_manager.stop()
    if master.state_manager is not None:
        master.state_manager._stopped.set()


def _master(tmp_path, **kw):
    from dlrover_tpu.master.job_master import JobMaster

    kw.setdefault("job_name", "fo")
    kw.setdefault("state_dir", str(tmp_path / "state"))
    master = JobMaster(**kw)
    master.prepare()
    return master


# ------------------------------------------------------- snapshot v2 units


def test_ledger_groups_survive_restart_and_stay_separate(tmp_path):
    """The satellite pin: master restart lands BETWEEN a fabric
    writer's embedding ack and the dense rank-0 commit wait — the
    restored ledger keeps the groups separate and the dense step still
    commits."""
    m1 = _master(tmp_path)
    m1.servicer.handle(m.PersistAckReport(
        node_id="emb-0", step=4, num_shards=1,
        shard={"crc32": 7}, group="embedding", rid="e1",
    ))
    m1.servicer.handle(m.PersistAckReport(
        node_id=1, step=4, num_shards=2, shard={"crc32": 8}, rid="d1",
    ))
    m1.state_manager.snapshot()
    _crash(m1)

    m2 = _master(tmp_path)
    try:
        assert m2.master_epoch == m1.master_epoch + 1
        # embedding acks alone can never complete the dense commit
        dense = m2.servicer.handle(
            m.PersistStatusRequest(step=4, num_shards=2))
        assert not dense.complete and sorted(dense.shards) == ["1"]
        # ... and the late dense writer completes it on the NEW master
        m2.servicer.handle(m.PersistAckReport(
            node_id=0, step=4, num_shards=2, shard={"crc32": 9},
            rid="d0",
        ))
        dense = m2.servicer.handle(
            m.PersistStatusRequest(step=4, num_shards=2))
        assert dense.complete and sorted(dense.shards) == ["0", "1"]
        emb = m2.servicer.handle(
            m.PersistStatusRequest(step=4, num_shards=1,
                                   group="embedding"))
        assert emb.complete and sorted(emb.shards) == ["emb-0"]
    finally:
        m2.stop()


def test_rid_dedup_survives_restart(tmp_path):
    m1 = _master(tmp_path)
    m1.servicer.handle(m.FailureReport(node_id=3, rid="f-1"))
    m1.state_manager.snapshot()
    _crash(m1)
    m2 = _master(tmp_path)
    try:
        # the redelivered replay must not double-count
        m2.servicer.handle(m.FailureReport(node_id=3, rid="f-1"))
        assert m2.node_manager._failure_counts.get(3, 0) == 0
        m2.servicer.handle(m.FailureReport(node_id=3, rid="f-2"))
        assert m2.node_manager._failure_counts[3] == 1
    finally:
        m2.stop()


def test_rendezvous_round_monotonic_and_waiting_restored(tmp_path):
    m1 = _master(tmp_path, min_nodes=2, max_nodes=2)
    for nid, addr in ((0, "a:1"), (1, "b:1")):
        m1.servicer.handle(m.JoinRendezvousRequest(
            node_id=nid, addr=addr, local_devices=4))
    w = m1.servicer.handle(m.CommWorldRequest(node_id=0))
    assert w.completed and w.round == 1
    # node 0 re-joins (respawn) and the master dies mid-rendezvous
    m1.servicer.handle(m.JoinRendezvousRequest(
        node_id=0, addr="a:1", local_devices=4))
    m1.state_manager.snapshot()
    _crash(m1)

    m2 = _master(tmp_path, min_nodes=2, max_nodes=2)
    try:
        m2.servicer.handle(m.JoinRendezvousRequest(
            node_id=1, addr="b:1", local_devices=4))
        w2 = m2.servicer.handle(m.CommWorldRequest(node_id=0))
        assert w2.completed
        assert w2.round == 2  # continues the sequence, never reissued
        assert w2.master_epoch == m2.master_epoch
        assert sorted(w2.world) == [0, 1]
    finally:
        m2.stop()


def test_racing_snapshot_cannot_clobber_newer_state(tmp_path):
    """A loop-thread snapshot captured BEFORE a dispatch must not save
    AFTER (and thus clobber) a snapshot captured after the dispatch —
    capture+save is one atomic unit. Deterministic replay of a suite
    flake: the stale capture's save is parked until the newer snapshot
    has had every chance to win the write order."""
    m1 = _master(tmp_path, min_nodes=2, max_nodes=2)
    sm = m1.state_manager
    for nid, addr in ((0, "a:1"), (1, "b:1")):
        m1.servicer.handle(m.JoinRendezvousRequest(
            node_id=nid, addr=addr, local_devices=4))
    assert m1.servicer.handle(m.CommWorldRequest(node_id=0)).completed

    backend = sm._backend
    orig_save = backend.save
    stale_captured = threading.Event()
    newer_saved = threading.Event()
    gated = []

    def gated_save(state):
        if not gated:
            gated.append(True)
            stale_captured.set()
            newer_saved.wait(1.0)
        orig_save(state)

    backend.save = gated_save
    stale = threading.Thread(target=sm.snapshot)  # captures pre-rejoin
    stale.start()
    assert stale_captured.wait(5.0)
    m1.servicer.handle(m.JoinRendezvousRequest(   # invalidates round 1
        node_id=0, addr="a:1", local_devices=4))
    sm.snapshot()                                 # captures post-rejoin
    newer_saved.set()
    stale.join(10.0)
    backend.save = orig_save
    _crash(m1)

    m2 = _master(tmp_path, min_nodes=2, max_nodes=2)
    try:
        m2.servicer.handle(m.JoinRendezvousRequest(
            node_id=1, addr="b:1", local_devices=4))
        w2 = m2.servicer.handle(m.CommWorldRequest(node_id=0))
        assert w2.completed          # node 0's rejoin survived the race
        assert sorted(w2.world) == [0, 1]
    finally:
        m2.stop()


def test_compile_cache_spilled_and_served_warm(tmp_path):
    blob = b"\x00executable\xff" * 9
    m1 = _master(tmp_path)
    m1.servicer.handle(m.CompileCachePutRequest(
        node_id=0, key="n2t8/deadbeef", payload=blob,
        meta={"jax": "x"}))
    m1.state_manager.snapshot()
    _crash(m1)
    spill = tmp_path / "state" / "compile_cache"
    assert (spill / "n2t8_deadbeef.aot").exists()

    m2 = _master(tmp_path)
    try:
        got = m2.servicer.handle(
            m.CompileCacheGetRequest(node_id=0, key="n2t8/deadbeef"))
        assert got.found and got.payload == blob \
            and got.meta == {"jax": "x"}
    finally:
        m2.stop()


def test_corrupt_spilled_blob_drops_to_miss(tmp_path):
    m1 = _master(tmp_path)
    m1.servicer.handle(m.CompileCachePutRequest(
        node_id=0, key="n2t8/feed", payload=b"Z" * 64))
    m1.state_manager.snapshot()
    _crash(m1)
    path = tmp_path / "state" / "compile_cache" / "n2t8_feed.aot"
    path.write_bytes(b"Y" * 64)  # same size, wrong bytes: CRC catches
    m2 = _master(tmp_path)
    try:
        got = m2.servicer.handle(
            m.CompileCacheGetRequest(node_id=0, key="n2t8/feed"))
        assert not got.found  # a miss (recompile), never wrong bytes
    finally:
        m2.stop()


def test_autopilot_budget_restored_as_spent():
    from dlrover_tpu.autopilot.controller import AutopilotController
    from dlrover_tpu.autopilot.planner import Plan

    plan = Plan(name="p", pred_step_s=0.1, source="history",
                fingerprint="p")
    alt = Plan(name="q", pred_step_s=0.1, source="history",
               fingerprint="q", rank=1)
    c1 = AutopilotController(max_retunes=2, min_points=1,
                             action_streak=1)
    c1.arm(plan, [alt])
    assert c1.observe_step_time(1.0) is not None  # one retune fired
    state = c1.export_state()

    c2 = AutopilotController(max_retunes=2, min_points=1,
                             action_streak=1)
    c2.restore_state(state)
    assert c2.retunes_used == 1
    assert c2.armed and c2.plan.fingerprint == "q"
    # one more is within budget; the one after must be refused
    assert c2.observe_step_time(1.0) is not None
    assert c2.observe_step_time(1.0) is None
    assert c2.retunes_used == 2


def test_interval_tuner_ages_roundtrip():
    from dlrover_tpu.checkpoint.interval_tuner import IntervalTuner

    clock = [1000.0]
    t1 = IntervalTuner(clock=lambda: clock[0])
    t1.observe_failure()
    clock[0] += 100
    t1.observe_failure()
    t1.observe_snapshot_cost(2.0)
    t1.observe_step_time(0.5)
    state = t1.export_state()

    clock2 = [5.0]  # a fresh process: monotonic clock restarted
    t2 = IntervalTuner(clock=lambda: clock2[0])
    t2.restore_state(state)
    assert t2.mtbf_s() == pytest.approx(t1.mtbf_s(), rel=1e-6)
    assert t2.recommend() == t1.recommend()


def test_v1_snapshot_still_restores_datasets(tmp_path):
    from dlrover_tpu.master.state_store import (
        FileStateBackend,
        MasterStateManager,
    )

    m1 = _master(tmp_path)
    backend = FileStateBackend(str(tmp_path / "v1.json"))
    backend.save({"version": 1, "timestamp": time.time(),
                  "job_name": "fo",
                  "datasets": m1.task_manager.export_state()})
    mgr = MasterStateManager(m1, backend)
    assert mgr.restore()
    assert mgr.restored_epoch == 0  # pre-epoch snapshot: fresh fence
    _crash(m1)


def test_legacy_pre_checksum_snapshot_journals(tmp_path, monkeypatch):
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    from dlrover_tpu.master.state_store import FileStateBackend

    path = tmp_path / "legacy.json"
    path.write_text(json.dumps({"version": 1, "datasets": {}}))
    state = FileStateBackend(str(path)).load()
    assert state == {"version": 1, "datasets": {}}
    events = [json.loads(line) for line in
              open(tmp_path / "events.jsonl", encoding="utf-8")]
    legacy = [e for e in events
              if e["name"] == "state_legacy_snapshot"]
    assert len(legacy) == 1 and legacy[0]["path"] == str(path)


def test_state_manager_stop_joins_loop_thread(tmp_path):
    from dlrover_tpu.master.state_store import (
        FileStateBackend,
        MasterStateManager,
    )

    m1 = _master(tmp_path / "m")
    mgr = MasterStateManager(
        m1, FileStateBackend(str(tmp_path / "s.json")),
        interval_s=0.05, min_gap_s=0.0,
    )
    mgr.start()
    time.sleep(0.12)
    mgr.stop()
    assert not mgr._thread.is_alive()  # no periodic writer survives
    assert (tmp_path / "s.json").exists()  # the final snapshot landed
    _crash(m1)


# ---------------------------------------------------------- epoch fencing


def test_rpc_envelope_carries_epoch(tmp_path):
    from dlrover_tpu.common.rpc import RpcClient, RpcServer

    epoch = [3]
    server = RpcServer(lambda msg: m.OkResponse(), port=0,
                       epoch_fn=lambda: epoch[0])
    server.start()
    try:
        client = RpcClient(f"127.0.0.1:{server.port}")
        seen: list[int] = []
        client.on_epoch = seen.append
        client.call(m.KVStoreGetRequest(key="k"))
        epoch[0] = 4
        client.call(m.KVStoreGetRequest(key="k"))
        assert seen == [3, 4]
        client.close()
    finally:
        server.stop()


class _FenceTransport:
    """Scripted transport: returns HeartbeatResponse with the current
    epoch; records everything sent; raises while .down."""

    def __init__(self):
        self.epoch = 1
        self.down = False
        self.sent: list = []

    def call(self, msg):
        if self.down:
            raise ConnectionError("down")
        self.sent.append(msg)
        if isinstance(msg, m.NodeHeartbeat):
            return m.HeartbeatResponse(master_epoch=self.epoch)
        return m.OkResponse()

    def close(self):
        pass


def _client(transport):
    from dlrover_tpu.agent.master_client import MasterClient

    return MasterClient("127.0.0.1:1", 5, transport=transport)


def test_epoch_change_runs_reconcile(monkeypatch, tmp_path):
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    transport = _FenceTransport()
    client = _client(transport)
    client.report_heartbeat(0)          # adopt epoch 1: no reconcile
    assert client.master_epoch == 1
    assert not any(isinstance(s, m.NodeEventReport)
                   for s in transport.sent)

    transport.epoch = 2                  # master restarted
    client.report_heartbeat(0)
    assert client.master_epoch == 2
    reregs = [s for s in transport.sent
              if isinstance(s, m.NodeEventReport)]
    assert len(reregs) == 1 and reregs[0].status == "running"
    events = [json.loads(line) for line in
              open(tmp_path / "events.jsonl", encoding="utf-8")]
    rec = [e for e in events if e["name"] == "agent_reconcile"]
    assert len(rec) == 1
    assert (rec[0]["old_epoch"], rec[0]["new_epoch"]) == (1, 2)


def test_stale_epoch_is_fenced_off():
    transport = _FenceTransport()
    transport.epoch = 5
    client = _client(transport)
    client.report_heartbeat(0)
    transport.epoch = 3                  # zombie master answering late
    client.report_heartbeat(0)
    assert client.master_epoch == 5
    assert not any(isinstance(s, m.NodeEventReport)
                   for s in transport.sent)


def test_reconcile_forces_full_metrics_push():
    transport = _FenceTransport()
    client = _client(transport)
    fam = [{"name": "f", "type": "counter", "help": "", "buckets": [],
            "samples": [{"labels": {}, "value": 1.0}]}]
    client.report_metrics(fam)           # full (first push)
    client.report_metrics(fam)           # unchanged -> delta
    pushes = [s for s in transport.sent
              if isinstance(s, m.MetricsSnapshotRequest)]
    assert [p.is_delta for p in pushes] == [False, True]
    client.report_heartbeat(0)
    transport.epoch = 2
    client.report_heartbeat(0)           # reconcile: force_full
    client.report_metrics(fam)
    pushes = [s for s in transport.sent
              if isinstance(s, m.MetricsSnapshotRequest)]
    assert pushes[-1].is_delta is False


def test_redelivery_queue_replays_with_same_rid():
    transport = _FenceTransport()
    client = _client(transport)
    client.report_heartbeat(0)
    transport.down = True
    client.report_persist_ack(7, 2, {"crc32": 1})   # must not raise
    client.report_failure("exit code 9 (killed)")
    assert client.redelivery_pending == 2
    queued_rids = [q.rid for q in client._redelivery]
    transport.down = False
    client.report_heartbeat(0)           # reachable again: drain
    assert client.redelivery_pending == 0
    acks = [s for s in transport.sent
            if isinstance(s, m.PersistAckReport)]
    fails = [s for s in transport.sent
             if isinstance(s, m.FailureReport)]
    assert [a.rid for a in acks] + [f.rid for f in fails] == queued_rids


def test_redelivered_reports_carry_mint_time_span_context(
        monkeypatch, tmp_path):
    """ISSUE-16 satellite: a queued ack/failure report replayed after a
    master restart carries the span context of the work that PRODUCED
    it (captured at mint time), not a fresh one from the reconcile that
    flushed it — so incident trees survive a master restart."""
    from dlrover_tpu.telemetry.journal import get_journal

    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    monkeypatch.setenv(EnvKey.TRACE_ID, "rt")
    transport = _FenceTransport()
    client = _client(transport)
    client.report_heartbeat(0)
    transport.down = True
    with get_journal().span("ckpt_persist", step=7) as sid:
        mint_ctx = f"rt:{sid}"
        client.report_persist_ack(7, 2, {"crc32": 1})
    with get_journal().span("node_restart", kind="failure") as rid:
        incident_ctx = f"rt:{rid}"
        client.report_failure("exit code 9 (killed)")
    assert client.redelivery_pending == 2
    assert [q.sctx for q in client._redelivery] == [
        mint_ctx, incident_ctx]

    transport.down = False
    client.report_heartbeat(0)           # reconcile drains the queue
    assert client.redelivery_pending == 0
    # replayed OUTSIDE any live span, yet the original context survived
    [ack] = [s for s in transport.sent
             if isinstance(s, m.PersistAckReport)]
    [fail] = [s for s in transport.sent
              if isinstance(s, m.FailureReport)]
    assert ack.sctx == mint_ctx
    assert fail.sctx == incident_ctx


def test_redelivery_queue_bounded(monkeypatch):
    monkeypatch.setenv(EnvKey.REDELIVERY_QUEUE, "3")
    transport = _FenceTransport()
    transport.down = True
    client = _client(transport)
    for step in range(5):
        client.report_persist_ack(step, 1, {})
    assert client.redelivery_pending == 3
    assert [q.step for q in client._redelivery] == [2, 3, 4]


def test_maybe_redial_follows_port_file(monkeypatch, tmp_path):
    from dlrover_tpu.common.rpc import RpcClient, RpcServer
    from dlrover_tpu.common.storage import atomic_write_file

    port_file = tmp_path / "port"
    monkeypatch.setenv(EnvKey.MASTER_PORT_FILE, str(port_file))
    server = RpcServer(lambda msg: m.OkResponse(), port=0,
                       epoch_fn=lambda: 2)
    server.start()
    try:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(
            "127.0.0.1:1",  # a dead address
            5, transport=RpcClient("127.0.0.1:1", retries=1,
                                   deadline_s=1.0),
        )
        atomic_write_file(str(server.port), str(port_file))
        assert client.maybe_redial()
        assert client._client.addr == f"127.0.0.1:{server.port}"
        # the cloned client keeps the retry config and the epoch hook
        assert client._client._retries == 1
        client.kv_set("k", b"v")         # proves the new link works
        assert client.master_epoch == 2  # envelope observed post-clone
    finally:
        server.stop()


# ------------------------------------------------------- degraded link


def test_master_link_one_instant_plus_counter(monkeypatch, tmp_path):
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    from dlrover_tpu.agent.master_link import (
        MasterLink,
        _unreachable_total,
    )

    link = MasterLink(object(), component="agent", warn_every_s=60.0)
    base = _unreachable_total.labels("agent").value
    for _ in range(5):
        link.failed(ConnectionError("refused"))
    assert link.degraded
    assert _unreachable_total.labels("agent").value == base + 5
    link.ok()
    link.ok()                            # idempotent exit
    assert not link.degraded
    events = [json.loads(line) for line in
              open(tmp_path / "events.jsonl", encoding="utf-8")]
    modes = [(e["component"], e["state"]) for e in events
             if e["name"] == "degraded_mode"]
    assert modes == [("agent", "enter"), ("agent", "exit")]


# ------------------------------------------- the chaos acceptance (§26.4)


def test_master_kill_scenario_replay_identical(tmp_path):
    """The §26 acceptance: a REAL master subprocess SIGKILLed
    mid-rendezvous, mid-commit-wait, mid-retune and post-retune; the
    in-flight step commits, groups stay separate, the compile cache
    answers warm, the retune budget is charged exactly once, trainers
    never restart — and two seeded runs produce identical trails."""
    from dlrover_tpu.chaos.scenario import run_master_kill_scenario

    r1 = run_master_kill_scenario(str(tmp_path / "run1"), seed=4242)
    r1.assert_invariants()
    r2 = run_master_kill_scenario(str(tmp_path / "run2"), seed=4242)
    r2.assert_invariants()
    assert r1.trail == r2.trail


# ------------------------------------------------- fleetsim master restart


def test_fleetsim_master_restart_reconverges():
    from dlrover_tpu.fleetsim.profile import FleetProfile
    from dlrover_tpu.fleetsim.sim import FleetSimulator

    profile = FleetProfile(
        name="mr", seed=11, nodes=200, duration_s=40.0,
        failures=0, deaths=0, ckpt_interval_s=25.0,
        straggler_frac=0.0, master_restarts=1,
    )
    res = FleetSimulator(profile).run()
    assert res.master_recovery_s is not None
    # bounded by the (staggered) heartbeat cadence
    assert res.master_recovery_s <= profile.heartbeat_interval_s + 1.0
    counts = [n for _, n in res.reregistered_curve]
    assert counts == sorted(counts) and counts[-1] == profile.nodes
    kinds = {e[0] for e in res.trail["events"]}
    assert {"master_restart", "master_recovered"} <= kinds
    # the §26 fleetsim contract: the measurement is virtual-time and
    # the trail seeded — a replay is identical, recovery included
    res2 = FleetSimulator(profile).run()
    assert res2.trail == res.trail
    assert res2.master_recovery_s == res.master_recovery_s
