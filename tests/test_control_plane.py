"""Control-plane integration tests: real JobMaster + in-process clients.

Reference analog: the ``start_local_master`` fixture pattern
(dlrover/python/tests/test_utils.py:268) — boot a real master + servicer,
then drive it through real MasterClients. Covers rendezvous rounds,
membership change, dead-node shard recovery, heartbeat action delivery, and
the network-check bisection.
"""

from __future__ import annotations

import threading
import time

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.messages import DatasetShardParams
from dlrover_tpu.master.job_master import JobMaster


@pytest.fixture
def master_factory():
    masters = []

    def make(**kwargs) -> JobMaster:
        kwargs.setdefault("rdzv_timeout", 2.0)
        m = JobMaster(port=0, **kwargs)
        m.prepare()
        masters.append(m)
        return m

    yield make
    for m in masters:
        m.stop()


def client(master: JobMaster, node_id: int) -> MasterClient:
    return MasterClient(master.addr, node_id)


class TestRendezvous:
    def test_round_completes_with_topology_sort(self, master_factory):
        master = master_factory(min_nodes=3, max_nodes=3)
        clients = [client(master, i) for i in range(3)]
        # join out of order with topology keys that reverse node order
        keys = {0: "c", 1: "a", 2: "b"}
        for i, c in enumerate(clients):
            c.join_rendezvous(addr=f"127.0.0.1:{9000 + i}",
                              local_devices=4, topology_key=keys[i])
        world = clients[0].wait_comm_world(timeout=10)
        assert world.completed
        # rank order follows topology_key: node1(a)=0, node2(b)=1, node0(c)=2
        assert world.world == {1: 0, 2: 1, 0: 2}
        assert world.coordinator == "127.0.0.1:9001"
        assert world.total_devices == 12

    def test_node_unit_rounding_and_timeout(self, master_factory):
        master = master_factory(min_nodes=2, max_nodes=4, node_unit=2,
                                rdzv_timeout=1.0)
        clients = [client(master, i) for i in range(3)]
        for i, c in enumerate(clients):
            c.join_rendezvous(addr=f"127.0.0.1:{9100 + i}", local_devices=1)
        # 3 joined < max 4: completes after the waiting timeout, rounded
        # down to node_unit -> 2 nodes
        world = clients[0].wait_comm_world(timeout=10)
        assert len(world.world) == 2
        assert set(world.world.values()) == {0, 1}

    def test_rejoin_invalidates_round_and_waiting_count(self, master_factory):
        master = master_factory(min_nodes=2, max_nodes=2)
        c0, c1 = client(master, 0), client(master, 1)
        for i, c in enumerate((c0, c1)):
            c.join_rendezvous(addr=f"127.0.0.1:{9200 + i}", local_devices=1)
        assert c0.wait_comm_world(timeout=10).completed
        assert c0.num_nodes_waiting() == 0
        # node 1 restarts and rejoins: old round invalid, 1 waiting
        c1.join_rendezvous(addr="127.0.0.1:9301", local_devices=1)
        assert c0.num_nodes_waiting() >= 1
        assert not c0.get_comm_world().completed
        c0.join_rendezvous(addr="127.0.0.1:9300", local_devices=1)
        world = c1.wait_comm_world(timeout=10)
        assert world.completed and len(world.world) == 2
        assert world.round == 2


class TestDeadNodeRecovery:
    def test_dead_node_shards_recovered_and_survivors_restarted(
        self, master_factory
    ):
        master = master_factory(
            min_nodes=2, max_nodes=2, heartbeat_dead_window_s=1.0,
        )
        master.node_manager.stop()  # restart monitor with a fast interval
        master.node_manager._stopped = threading.Event()
        master.node_manager.start(interval_s=0.2)

        c0, c1 = client(master, 0), client(master, 1)
        for i, c in enumerate((c0, c1)):
            c.join_rendezvous(addr=f"127.0.0.1:{9400 + i}", local_devices=1)
            c.report_heartbeat()
        assert c0.wait_comm_world(timeout=10).completed

        c0.report_dataset_params(DatasetShardParams(
            dataset_name="d", dataset_size=100, shard_size=10, num_epochs=1,
        ))
        # node 1 takes two shards and dies silently
        t1 = c1.get_task("d")
        t2 = c1.get_task("d")
        assert t1.valid and t2.valid
        taken = {t1.task_id, t2.task_id}

        deadline = time.time() + 15
        got_restart = False
        recovered: set[int] = set()
        while time.time() < deadline:
            # node 0 keeps heartbeating; node 1 stays silent
            action = c0.report_heartbeat()
            if action == "restart":
                got_restart = True
            task = c0.get_task("d")
            if task.valid:
                if task.task_id in taken:
                    recovered.add(task.task_id)
                c0.report_task_result(task.task_id, "d")
            if got_restart and recovered == taken:
                break
            time.sleep(0.1)
        assert got_restart, "survivor never got the restart action"
        assert recovered == taken, "dead node's shards were not recovered"
        # the dead node's rendezvous membership is gone
        assert not c0.get_comm_world().completed

    def test_explicit_failure_report_recovers_shards(self, master_factory):
        master = master_factory(min_nodes=1, max_nodes=1)
        c0 = client(master, 0)
        c0.report_dataset_params(DatasetShardParams(
            dataset_name="d", dataset_size=20, shard_size=10, num_epochs=1,
        ))
        t1 = c0.get_task("d")
        assert t1.valid
        c0.recover_shards()
        t1b = c0.get_task("d")
        assert t1b.valid and t1b.task_id == t1.task_id


class TestNetworkCheckBisection:
    def _join_all(self, master, n):
        clients = [client(master, i) for i in range(n)]
        for i, c in enumerate(clients):
            c.join_rendezvous(
                addr=f"127.0.0.1:{9500 + i}", local_devices=1,
                rdzv_name="network-check",
            )
        for c in clients:
            assert c.wait_comm_world(
                rdzv_name="network-check", timeout=10
            ).completed
        return clients

    def test_round0_pairs_and_bad_node_isolated(self, master_factory):
        master = master_factory(min_nodes=4, max_nodes=4)
        clients = self._join_all(master, 4)

        groups0 = {}
        for i, c in enumerate(clients):
            g = c.get_network_check_group(0)
            assert g.ready and g.needed
            groups0[i] = g
        # adjacent pairs with in-group ranks and the partner's coordinator
        assert set(groups0[0].world) == {0, 1}
        assert set(groups0[2].world) == {2, 3}
        assert groups0[2].coordinator == "127.0.0.1:9502"

        # node 2 is faulty: its pair (2, 3) both fail round 0
        for i, c in enumerate(clients):
            c.report_network_check(0, succeeded=i not in (2, 3),
                                   elapsed_time=1.0)
        assert not clients[0].get_network_check_status().completed

        # round 1 re-pairs each failure with a good node
        groups1 = {}
        for i, c in enumerate(clients):
            g = c.get_network_check_group(1)
            assert g.ready and g.needed
            groups1[i] = g
        assert set(groups1[2].world) & {0, 1}, "bad node not re-paired"
        assert set(groups1[3].world) & {0, 1}, "bad node not re-paired"

        # node 3 passes with its good partner; node 2 fails again
        for i, c in enumerate(clients):
            c.report_network_check(1, succeeded=i != 2, elapsed_time=1.0)
        status = clients[0].get_network_check_status()
        assert status.completed
        assert status.abnormal_nodes == [2]

    def test_no_good_partner_cannot_exonerate(self, master_factory):
        """Both nodes of a broken pair fail round 1 too (no good partner to
        bisect with) -> both abnormal; none escape via a solo probe."""
        master = master_factory(min_nodes=2, max_nodes=2)
        clients = self._join_all(master, 2)
        for c in clients:
            assert c.get_network_check_group(0).ready
            c.report_network_check(0, succeeded=False, elapsed_time=1.0)
        # round 1 re-pairs the two failures with each other
        for i, c in enumerate(clients):
            g = c.get_network_check_group(1)
            assert g.ready and g.needed
            assert set(g.world) == {0, 1}
            c.report_network_check(1, succeeded=False, elapsed_time=1.0)
        status = clients[0].get_network_check_status()
        assert status.completed
        assert status.abnormal_nodes == [0, 1]

    def test_unpaired_bad_singleton_autofails(self, master_factory):
        """3 bad nodes, 0 good: the leftover singleton is auto-failed by
        the master instead of passing a collective-free solo probe."""
        master = master_factory(min_nodes=3, max_nodes=3)
        clients = self._join_all(master, 3)
        for c in clients:
            assert c.get_network_check_group(0).ready
            c.report_network_check(0, succeeded=False, elapsed_time=1.0)
        solo = 0
        for i, c in enumerate(clients):
            g = c.get_network_check_group(1)
            assert g.ready
            if not g.needed:
                solo += 1
                continue
            c.report_network_check(1, succeeded=False, elapsed_time=1.0)
        assert solo == 1
        status = clients[0].get_network_check_status()
        assert status.completed
        assert status.abnormal_nodes == [0, 1, 2]

    def test_recheck_generation_clears_stale_results(self, master_factory):
        """A new network-check rendezvous round discards the previous
        round's probe results even with identical node ids."""
        master = master_factory(min_nodes=2, max_nodes=2)
        clients = self._join_all(master, 2)
        for c in clients:
            c.get_network_check_group(0)
            c.report_network_check(0, succeeded=True, elapsed_time=1.0)
        assert clients[0].get_network_check_status().completed
        # same nodes re-join (launcher restart): a fresh check must probe
        for i, c in enumerate(clients):
            c.join_rendezvous(
                addr=f"127.0.0.1:{9700 + i}", local_devices=1,
                rdzv_name="network-check",
            )
        for c in clients:
            assert c.wait_comm_world(
                rdzv_name="network-check", timeout=10
            ).completed
        assert clients[0].get_network_check_group(0).ready
        assert not clients[0].get_network_check_status().completed

    def test_straggler_uses_local_time_not_pair_wallclock(
        self, master_factory
    ):
        """A slow node's healthy partner shares the pair's collective wall
        clock but not its local compute time — only the slow node flags."""
        master = master_factory(min_nodes=4, max_nodes=4)
        clients = self._join_all(master, 4)
        # pair (2,3): node 3's chip is slow, so BOTH report 10x wall clock,
        # but only node 3's local time is slow
        local = {0: 1.0, 1: 1.0, 2: 1.0, 3: 10.0}
        wall = {0: 1.2, 1: 1.2, 2: 10.2, 3: 10.2}
        for i, c in enumerate(clients):
            c.get_network_check_group(0)
            c.report_network_check(0, succeeded=True,
                                   elapsed_time=wall[i],
                                   local_time=local[i])
        status = clients[0].get_network_check_status()
        assert status.completed
        assert status.straggler_nodes == [3]

    def test_all_pass_single_round(self, master_factory):
        master = master_factory(min_nodes=2, max_nodes=2)
        clients = self._join_all(master, 2)
        for c in clients:
            assert c.get_network_check_group(0).ready
            c.report_network_check(0, succeeded=True, elapsed_time=1.0)
        g = clients[0].get_network_check_group(1)
        assert g.ready and not g.needed
        status = clients[0].get_network_check_status()
        assert status.completed and status.abnormal_nodes == []

    def test_odd_world_round0_folds_singleton_into_triple(
        self, master_factory
    ):
        """5 nodes: round-0 groups are [0,1],[2,3,4] — nobody probes solo
        (a collective-free solo probe would trivially pass)."""
        master = master_factory(min_nodes=5, max_nodes=5)
        clients = self._join_all(master, 5)
        sizes = []
        for c in clients:
            g = c.get_network_check_group(0)
            assert g.ready and g.needed
            sizes.append(len(g.world))
        assert sorted(sizes) == [2, 2, 3, 3, 3]

    def test_straggler_detection(self, master_factory):
        master = master_factory(min_nodes=4, max_nodes=4)
        clients = self._join_all(master, 4)
        for i, c in enumerate(clients):
            c.get_network_check_group(0)
            c.report_network_check(
                0, succeeded=True, elapsed_time=100.0 if i == 1 else 1.0
            )
        status = clients[0].get_network_check_status()
        assert status.completed
        assert status.straggler_nodes == [1]


class TestRelaunchHook:
    def test_hardware_failure_triggers_relaunch_hook(self, master_factory):
        from dlrover_tpu.common.constants import NodeEventType, NodeExitReason

        master = master_factory(min_nodes=1, max_nodes=1)
        relaunched = []
        master.node_manager._relaunch_hook = relaunched.append
        c0 = client(master, 0)
        c0.join_rendezvous(addr="127.0.0.1:9600", local_devices=1)
        c0.report_node_event(
            NodeEventType.MODIFIED, "failed",
            NodeExitReason.HARDWARE_ERROR, "exit code 211",
        )
        assert len(relaunched) == 1
        assert relaunched[0].node_id == 0
        assert relaunched[0].relaunch_count == 1
        # fatal software errors never relaunch
        c0.join_rendezvous(addr="127.0.0.1:9600", local_devices=1)  # revive
        c0.report_node_event(
            NodeEventType.MODIFIED, "failed",
            NodeExitReason.FATAL_ERROR, "exit code 1",
        )
        assert len(relaunched) == 1


class TestHangRecovery:
    def test_hang_restarts_once_then_fails(self, master_factory):
        master = master_factory(
            min_nodes=1, max_nodes=1, hang_timeout_s=0.5,
        )
        c0 = client(master, 0)
        c0.report_heartbeat()
        c0.report_step(5)  # training started, then goes silent
        outcome: list = []

        def run_master():
            try:
                outcome.append(master.run(
                    poll_interval_s=0.1, recovery_grace_s=2.0
                ))
            except BaseException as e:  # noqa: BLE001 - surface in asserts
                outcome.append(e)

        t = threading.Thread(target=run_master)
        t.start()
        # first hang window: the master asks for a restart, not a failure
        deadline = time.time() + 10
        got_restart = False
        while time.time() < deadline and not got_restart:
            if c0.report_heartbeat() == "restart":
                got_restart = True
            time.sleep(0.05)
        assert got_restart, "hang did not trigger a restart action"
        # still silent past the recovery grace: the job fails
        t.join(timeout=15)
        assert not t.is_alive()
        assert outcome == [False], outcome

    def test_report_once_then_wedge_hits_lifetime_cap(self, master_factory):
        """A worker that reports one step after each restart (replenishing
        the per-incident budget) and wedges again must not be restarted
        forever: the lifetime cap fails the job."""
        master = master_factory(
            min_nodes=1, max_nodes=1, hang_timeout_s=0.4,
        )
        c0 = client(master, 0)
        c0.report_heartbeat()
        c0.report_step(5)
        outcome: list = []

        def run_master():
            try:
                outcome.append(master.run(
                    poll_interval_s=0.05, recovery_grace_s=1.0,
                    max_hang_restarts=2,
                ))
            except BaseException as e:  # noqa: BLE001 - surface in asserts
                outcome.append(e)

        t = threading.Thread(target=run_master)
        t.start()
        restarts = 0
        step = 5
        deadline = time.time() + 30
        while t.is_alive() and time.time() < deadline:
            if c0.report_heartbeat() == "restart":
                restarts += 1
                step += 1
                c0.report_step(step)  # one report, then silent again
            time.sleep(0.05)
        t.join(timeout=5)
        assert not t.is_alive(), "master livelocked on a wedged worker"
        assert outcome == [False], outcome
        assert restarts == 2, restarts

    def test_import_api_surface(self):
        import dlrover_tpu

        assert callable(dlrover_tpu.compile_train)
        assert callable(dlrover_tpu.ElasticTrainer)
        assert callable(dlrover_tpu.CheckpointEngine)
        assert callable(dlrover_tpu.int8_matmul)
        assert callable(dlrover_tpu.DataServiceServer)
        assert callable(dlrover_tpu.StrategyEngineClient)
        assert callable(dlrover_tpu.flops_breakdown)
        assert dlrover_tpu.PRESETS["fsdp"]().name == "fsdp"
        with pytest.raises(AttributeError):
            dlrover_tpu.no_such_thing  # noqa: B018


class TestMasterHA:
    def test_state_survives_master_restart(self, master_factory, tmp_path):
        """A new master incarnation resumes the shard queues: undone and
        in-flight shards survive; no duplicate completions."""
        state_dir = str(tmp_path / "state")
        m1 = master_factory(min_nodes=1, max_nodes=1)
        from dlrover_tpu.master.state_store import (
            FileStateBackend,
            MasterStateManager,
        )

        sm1 = MasterStateManager(
            m1, FileStateBackend(state_dir + "/job.state.json"),
        )
        c = client(m1, 0)
        c.report_dataset_params(DatasetShardParams(
            dataset_name="d", dataset_size=40, shard_size=10, num_epochs=1,
        ))
        t1 = c.get_task("d")       # completed before the crash
        c.report_task_result(t1.task_id, "d")
        t2 = c.get_task("d")       # in flight at the crash
        assert t1.valid and t2.valid
        sm1.snapshot()
        m1.stop()

        m2 = master_factory(min_nodes=1, max_nodes=1)
        sm2 = MasterStateManager(
            m2, FileStateBackend(state_dir + "/job.state.json"),
        )
        assert sm2.restore()
        c2 = client(m2, 0)
        got = []
        while True:
            task = c2.get_task("d")
            if not task.valid:
                break
            got.append((task.start, task.end))
            c2.report_task_result(task.task_id, "d")
        # 3 remaining shards: the in-flight one (recovered) + 2 untouched
        assert len(got) == 3
        assert (t2.start, t2.end) in got
        assert (t1.start, t1.end) not in got
        assert m2.task_manager.completed_counts()["d"] == 4

    def test_restore_from_empty_backend_is_noop(self, tmp_path):
        from dlrover_tpu.master.state_store import (
            FileStateBackend,
            MasterStateManager,
        )

        m = JobMaster(port=0)
        sm = MasterStateManager(
            m, FileStateBackend(str(tmp_path / "nope.json")),
        )
        assert not sm.restore()


class TestStats:
    def test_partial_reports_merge_and_job_stats(self, master_factory):
        master = master_factory(min_nodes=1, max_nodes=1)
        c0, c1 = client(master, 0), client(master, 1)
        # agent-style host report, then trainer-style HBM report
        c0.report_resource(cpu_percent=55.0, used_memory_mb=2048,
                           tpu_chips=4)
        c0.report_resource(cpu_percent=0.0, used_memory_mb=0,
                           used_hbm_mb=9000)
        c1.report_resource(cpu_percent=70.0, used_memory_mb=4096)
        c0.report_step(42)

        stats = c0.get_job_stats()
        assert stats.global_step == 42
        by_id = {s.node_id: s for s in stats.nodes}
        assert by_id[0].cpu_percent == 55.0       # host report survived
        assert by_id[0].used_memory_mb == 2048
        assert by_id[0].used_hbm_mb == 9000       # merged from trainer
        assert by_id[0].tpu_chips == 4
        assert by_id[1].used_memory_mb == 4096
        # node model merged too
        nodes = {n.node_id: n for n in master.node_manager.all_nodes()}
        assert nodes[0].resource.used_hbm_mb == 9000
        assert nodes[0].resource.used_cpu == 55.0

    def test_resource_monitor_reports(self, master_factory):
        from dlrover_tpu.agent.resource_monitor import ResourceMonitor

        master = master_factory(min_nodes=1, max_nodes=1)
        c0 = client(master, 0)
        mon = ResourceMonitor(c0, interval_s=0.2, tpu_chips=8)
        mon.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                latest = master.servicer._stats.latest()
                if 0 in latest and latest[0].used_memory_mb > 0:
                    break
                time.sleep(0.1)
            sample = master.servicer._stats.latest()[0]
            assert sample.used_memory_mb > 0
            assert sample.tpu_chips == 8
        finally:
            mon.stop()

    def test_slow_node_detection(self, master_factory):
        master = master_factory(min_nodes=1, max_nodes=1)
        for _ in range(3):  # averaged over a window, not one sample
            for nid, cpu in [(0, 90.0), (1, 85.0), (2, 88.0), (3, 10.0)]:
                client(master, nid).report_resource(
                    cpu_percent=cpu, used_memory_mb=100
                )
        assert master.servicer._stats.slow_nodes() == [3]

    def test_dead_node_evicted_from_stats(self, master_factory):
        master = master_factory(min_nodes=1, max_nodes=1)
        client(master, 0).report_resource(cpu_percent=50.0,
                                          used_memory_mb=100)
        client(master, 1).report_resource(cpu_percent=50.0,
                                          used_memory_mb=100)
        master._on_node_dead(1)
        assert set(master.servicer._stats.latest()) == {0}


class TestKvAndBarrier:
    def test_kv_and_barrier(self, master_factory):
        master = master_factory(min_nodes=1, max_nodes=1)
        c0, c1 = client(master, 0), client(master, 1)
        c0.kv_set("k", b"v")
        assert c1.kv_get("k") == b"v"
        done = []

        def waiter():
            done.append(c1.barrier("b", world_size=2, timeout=10))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        assert c0.barrier("b", world_size=2, timeout=10)
        t.join(timeout=10)
        assert done == [True]
