"""Exit-code classification and failover decision tables.

Reference analog: training.py:356-360 + dist_job_manager.py:561.
"""

from __future__ import annotations

import pytest

from dlrover_tpu.agent.failure_policy import (
    EXIT_CODE_HARDWARE,
    EXIT_CODE_OOM,
    FailureAction,
    classify_exit,
    decide,
)
from dlrover_tpu.common.constants import NodeExitReason
from dlrover_tpu.common.node import Node
from dlrover_tpu.common.constants import NodeType


@pytest.mark.parametrize("code,reason", [
    # the full exit-code contract (failure_policy.py module docstring)
    (0, NodeExitReason.SUCCEEDED),
    (EXIT_CODE_OOM, NodeExitReason.OOM),        # 210
    (EXIT_CODE_HARDWARE, NodeExitReason.HARDWARE_ERROR),  # 211
    (-9, NodeExitReason.KILLED),
    (137, NodeExitReason.KILLED),       # 128+9  SIGKILL
    (139, NodeExitReason.KILLED),       # 128+11 SIGSEGV
    (-11, NodeExitReason.KILLED),
    (-15, NodeExitReason.PREEMPTED),
    (143, NodeExitReason.PREEMPTED),    # 128+15 SIGTERM
    (1, NodeExitReason.UNKNOWN),
    (17, NodeExitReason.UNKNOWN),
    (128, NodeExitReason.UNKNOWN),      # not above the signal base
    # >128 but not a valid signal number: a software error exiting 255
    # must NOT classify as "killed by signal 127"
    (255, NodeExitReason.UNKNOWN),
    (254, NodeExitReason.UNKNOWN),      # "signal 126" is not a signal
    (-200, NodeExitReason.UNKNOWN),     # out-of-range negative code
])
def test_classify(code, reason):
    assert classify_exit(code) == reason


@pytest.mark.parametrize("reason,restarts,max_r,action", [
    (NodeExitReason.UNKNOWN, 0, 3, FailureAction.RESTART_PROCESS),
    (NodeExitReason.OOM, 0, 3, FailureAction.RESTART_PROCESS),
    (NodeExitReason.KILLED, 2, 3, FailureAction.RESTART_PROCESS),
    (NodeExitReason.KILLED, 3, 3, FailureAction.GIVE_UP),
    (NodeExitReason.HARDWARE_ERROR, 0, 3, FailureAction.RELAUNCH_NODE),
    (NodeExitReason.HARDWARE_ERROR, 9, 3, FailureAction.RELAUNCH_NODE),
    (NodeExitReason.FATAL_ERROR, 0, 3, FailureAction.GIVE_UP),
])
def test_decide(reason, restarts, max_r, action):
    assert decide(reason, restarts, max_r) == action


def test_node_should_relaunch_policy():
    node = Node(node_type=NodeType.HOST, node_id=0, max_relaunch_count=2)
    assert node.should_relaunch(NodeExitReason.HARDWARE_ERROR)
    assert node.should_relaunch(NodeExitReason.OOM)
    assert not node.should_relaunch(NodeExitReason.FATAL_ERROR)
    node.relaunch_count = 2
    assert not node.should_relaunch(NodeExitReason.HARDWARE_ERROR)
