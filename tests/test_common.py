"""Tests for the common substrate: serde, rpc, shared-memory IPC, storage."""

import queue
import threading

import numpy as np
import pytest

from dlrover_tpu.common import serde
from dlrover_tpu.common.constants import NodeEventType, NodeExitReason
from dlrover_tpu.common.messages import (
    CommWorldResponse,
    JoinRendezvousRequest,
    KVStoreSetRequest,
    NodeEventReport,
    NodeMeta,
    RunningNodesResponse,
)
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedMemoryArena,
    SharedQueue,
)
from dlrover_tpu.common.rpc import RpcClient, RpcServer
from dlrover_tpu.common.storage import ClassMeta, PosixDiskStorage, build_storage


class TestSerde:
    def test_roundtrip_simple(self):
        msg = JoinRendezvousRequest(node_id=3, addr="h1:1234", local_devices=4)
        out = serde.decode(serde.encode(msg))
        assert out == msg

    def test_roundtrip_enum_and_bytes(self):
        msg = NodeEventReport(
            node_id=1,
            event_type=NodeEventType.DELETED,
            exit_reason=NodeExitReason.OOM,
        )
        out = serde.decode(serde.encode(msg))
        assert out.event_type is NodeEventType.DELETED
        assert out.exit_reason is NodeExitReason.OOM

        kv = KVStoreSetRequest(key="k", value=b"\x00\xffbin")
        assert serde.decode(serde.encode(kv)).value == b"\x00\xffbin"

    def test_roundtrip_int_keyed_dict(self):
        msg = CommWorldResponse(
            completed=True, world={0: 0, 3: 1}, coordinator="h:1"
        )
        out = serde.decode(serde.encode(msg))
        assert out.world == {0: 0, 3: 1}
        assert all(isinstance(k, int) for k in out.world)

    def test_roundtrip_nested_list(self):
        msg = RunningNodesResponse(
            nodes=[NodeMeta(node_id=1, rank=0), NodeMeta(node_id=2, rank=1)]
        )
        out = serde.decode(serde.encode(msg))
        assert out.nodes[1].node_id == 2
        assert isinstance(out.nodes[0], NodeMeta)

    def test_unknown_type_rejected(self):
        with pytest.raises(TypeError):
            serde.decode(b'{"type": "os.system", "data": {}}')


class TestRpc:
    def test_request_response(self):
        def handler(msg):
            if isinstance(msg, JoinRendezvousRequest):
                return CommWorldResponse(completed=True, world={msg.node_id: 0})
            return None

        server = RpcServer(handler, host="127.0.0.1")
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            resp = client.call(JoinRendezvousRequest(node_id=7))
            assert resp.completed and resp.world == {7: 0}
            # many sequential calls over one connection
            for _ in range(50):
                assert client.call(JoinRendezvousRequest(node_id=1)).completed
            client.close()
        finally:
            server.stop()

    def test_handler_error_propagates(self):
        def handler(msg):
            raise ValueError("boom")

        server = RpcServer(handler, host="127.0.0.1")
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            with pytest.raises(RuntimeError, match="boom"):
                client.call(JoinRendezvousRequest())
            client.close()
        finally:
            server.stop()

    def test_concurrent_clients(self):
        def handler(msg):
            return CommWorldResponse(completed=True, round=msg.node_id)

        server = RpcServer(handler, host="127.0.0.1")
        server.start()
        results = {}

        def worker(i):
            c = RpcClient(f"127.0.0.1:{server.port}")
            results[i] = c.call(JoinRendezvousRequest(node_id=i)).round
            c.close()

        try:
            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(8)
            ]
            [t.start() for t in threads]
            [t.join() for t in threads]
            assert results == {i: i for i in range(8)}
        finally:
            server.stop()


class TestRpcRobustness:
    """Hostile/corrupt peers must never take the server down — the
    master serves every node's control plane over this socket."""

    @staticmethod
    def _alive(server):
        client = RpcClient(f"127.0.0.1:{server.port}")
        try:
            return client.call(JoinRendezvousRequest(node_id=1)).completed
        finally:
            client.close()

    def test_garbage_bytes_do_not_kill_server(self):
        import socket as socket_mod

        server = RpcServer(
            lambda m: CommWorldResponse(completed=True), host="127.0.0.1"
        )
        server.start()
        try:
            for payload in (
                b"\x00" * 3,                    # truncated length prefix
                b"\xff\xff\xff\x7f",            # huge declared frame
                b"\x00\x00\x00\x05ab",          # declares 5 bytes, EOF at 2
            ):
                s = socket_mod.create_connection(
                    ("127.0.0.1", server.port), timeout=5
                )
                s.sendall(payload)
                s.close()
            assert self._alive(server)
        finally:
            server.stop()

    def test_malformed_json_and_unknown_type_return_errors(self):
        import socket as socket_mod

        from dlrover_tpu.common import serde
        from dlrover_tpu.common.rpc import RpcError, recv_frame, send_frame

        server = RpcServer(
            lambda m: CommWorldResponse(completed=True), host="127.0.0.1"
        )
        server.start()
        try:
            for bad in (b"not json at all",
                        b'{"type": "NoSuchMessageType"}',  # unknown type
                        b'{"kind": "x"}'):                 # no type key
                s = socket_mod.create_connection(
                    ("127.0.0.1", server.port), timeout=5
                )
                send_frame(s, bad)
                resp = serde.decode(recv_frame(s))
                assert isinstance(resp, RpcError) and resp.error, (
                    bad, resp
                )
                s.close()
            assert self._alive(server)
        finally:
            server.stop()

    def test_oversized_frame_gets_structured_error(self):
        import socket as socket_mod

        from dlrover_tpu.common import serde
        from dlrover_tpu.common.rpc import RpcError, recv_frame

        server = RpcServer(
            lambda m: CommWorldResponse(completed=True), host="127.0.0.1"
        )
        server.start()
        try:
            s = socket_mod.create_connection(
                ("127.0.0.1", server.port), timeout=5
            )
            s.sendall(b"\xff\xff\xff\x7f")  # 4.29 GB declared length
            resp = serde.decode(recv_frame(s))
            assert isinstance(resp, RpcError)
            assert "frame" in resp.error
            s.close()
            assert self._alive(server)
        finally:
            server.stop()


class TestSharedPrimitives:
    def test_shared_lock(self, tmp_ipc_dir):
        owner = SharedLock("l1", create=True)
        client = SharedLock("l1", create=False)
        try:
            assert client.acquire()
            assert not owner.acquire(blocking=False)
            assert client.release()
            assert owner.acquire(blocking=False)
            owner.release()
        finally:
            client.close()
            owner.close()

    def test_shared_queue(self, tmp_ipc_dir):
        owner = SharedQueue("q1", create=True)
        client = SharedQueue("q1", create=False)
        try:
            client.put({"step": 5, "kind": "save"})
            assert owner.qsize() == 1
            item = owner.get(timeout=1)
            assert item == {"step": 5, "kind": "save"}
            with pytest.raises(queue.Empty):
                client.get(block=False)
        finally:
            client.close()
            owner.close()

    def test_shared_dict(self, tmp_ipc_dir):
        owner = SharedDict("d1", create=True)
        client = SharedDict("d1", create=False)
        try:
            client.set("meta", {"offset": 128, "dtype": "float32"})
            client.update({"step": 9})
            snap = owner.get()
            assert snap["meta"]["offset"] == 128
            assert snap["step"] == 9
            assert client.get()["step"] == 9
        finally:
            client.close()
            owner.close()

    def test_shared_memory_survives_reopen(self):
        arena = SharedMemoryArena.open_or_create("t_arena", 1024)
        np.frombuffer(arena.buf, dtype=np.uint8)[:4] = [1, 2, 3, 4]
        arena.close()

        again = SharedMemoryArena.open("t_arena")
        assert again is not None
        assert list(np.frombuffer(again.buf, dtype=np.uint8)[:4]) == [1, 2, 3, 4]
        # growing reallocates
        bigger = SharedMemoryArena.open_or_create("t_arena", 4096)
        assert bigger.size >= 4096
        bigger.unlink()
        bigger.close()
        again.close()


class TestStorage:
    def test_posix_roundtrip(self, tmp_path):
        s = PosixDiskStorage()
        p = str(tmp_path / "a" / "b.bin")
        s.write(b"hello", p)
        assert s.read(p) == b"hello"
        assert s.exists(p)
        assert s.listdir(str(tmp_path / "a")) == ["b.bin"]
        s.delete(p)
        assert not s.exists(p)

    def test_class_meta_rebuild(self):
        meta = PosixDiskStorage().class_meta()
        rebuilt = build_storage(ClassMeta.from_dict(meta.to_dict()))
        assert isinstance(rebuilt, PosixDiskStorage)

    def test_build_storage_rejects_non_storage(self):
        meta = ClassMeta(module_path="os", class_name="system")
        with pytest.raises(TypeError):
            build_storage(meta)
