"""Partition tolerance (DESIGN.md §30): the ``net_partition`` chaos
domain, rack leases on both sides of the rack/root link, push-direction
epoch fencing, sticky degraded re-dial, the degraded-link staleness and
warn rate-limit bounds, the trail-invariant auditor, and the three
partition acceptance scenarios driven end to end (real subprocesses,
seeded chaos plans, replay-identical trails).
"""

from __future__ import annotations

import json
import os
import time

import pytest

from dlrover_tpu import chaos
from dlrover_tpu.chaos import partition
from dlrover_tpu.common import messages as m
from dlrover_tpu.common import serde
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.rpc import RpcClient
from dlrover_tpu.master.submaster import SubMaster
from dlrover_tpu.telemetry import audit


@pytest.fixture(autouse=True)
def _no_leaked_state():
    yield
    chaos.uninstall()
    partition.reset()


def _read(journal_dir) -> list[dict]:
    path = os.path.join(str(journal_dir), "events.jsonl")
    if not os.path.exists(path):
        return []
    return [json.loads(line)
            for line in open(path, encoding="utf-8") if line.strip()]


class _Loop:
    """In-process transport with a full serde round-trip each way."""

    def __init__(self, handler):
        self._handler = handler

    def call(self, msg):
        resp = self._handler(serde.decode(serde.encode(msg)))
        return serde.decode(serde.encode(resp))

    def close(self):
        pass


def _root(tmp_path, **kw):
    from dlrover_tpu.master.job_master import JobMaster

    kw.setdefault("job_name", "pt")
    kw.setdefault("state_dir", str(tmp_path / "state"))
    master = JobMaster(**kw)
    master.prepare()
    return master


# ------------------------------------------------- net_partition point


def test_partition_opens_heals_and_journals_once(monkeypatch, tmp_path):
    """A directed rule opens the edge at its first fired crossing,
    keeps dropping while the occurrence window is open, and heals at
    the first crossing that passes — one open + one heal journal
    instant per episode, carrying the opening fault's seq."""
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    chaos.install({"seed": 5, "faults": [
        {"point": "net_partition", "action": "drop",
         "match": {"src": "a", "dst": "b"}, "after": 1, "times": 2},
    ]})
    assert partition.check("a", "b") is None       # after=1 skips one
    assert partition.check("b", "a") is None       # directional: no match
    assert partition.check("a", "b") is not None   # opens
    assert partition.check("a", "b") is not None   # still open, no journal
    assert partition.check("a", "b") is None       # exhausted: heals
    assert partition.check("a", "b") is None       # healthy, no journal
    trans = [e for e in _read(tmp_path) if e["name"] == "net_partition"]
    assert [(e["state"], e["src"], e["dst"]) for e in trans] == \
        [("open", "a", "b"), ("heal", "a", "b")]
    assert trans[0]["seq"] == trans[1]["seq"]


def test_partition_symmetric_link_cuts_both_directions(monkeypatch,
                                                       tmp_path):
    """``match: {"link": "a|b"}`` is a symmetric split: crossings in
    BOTH directions fire (and both consume the one rule's occurrence
    window), each direction with its own open/heal episode."""
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    chaos.install({"seed": 5, "faults": [
        {"point": "net_partition", "action": "drop",
         "match": {"link": "a|b"}, "times": 4},
    ]})
    assert partition.check("a", "b") is not None
    assert partition.check("b", "a") is not None
    assert partition.check("a", "b") is not None
    assert partition.check("b", "a") is not None
    assert partition.check("a", "b") is None
    assert partition.check("b", "a") is None
    trans = [(e["state"], e["src"], e["dst"])
             for e in _read(tmp_path) if e["name"] == "net_partition"]
    assert trans == [("open", "a", "b"), ("open", "b", "a"),
                     ("heal", "a", "b"), ("heal", "b", "a")]


def test_partition_disabled_is_noop_and_clears_state():
    chaos.install({"seed": 5, "faults": [
        {"point": "net_partition", "action": "drop",
         "match": {"src": "a", "dst": "b"}, "times": 5},
    ]})
    assert partition.check("a", "b") is not None
    chaos.uninstall()
    assert partition.check("a", "b") is None
    assert not partition._open  # forgotten, not leaked to the next plan


# ---------------------------------------------- trail-invariant auditor


def _world_ev(name, rnd, world, rdzv="training"):
    return {"name": name, "rdzv": rdzv, "round": rnd,
            "world_hash": audit.world_hash(world),
            "world": audit.world_compact(world)}


def test_audit_clean_trail_passes():
    """A consistent trail — one membership per round, complete commit
    manifests with a quorate ledger, monotonic epochs, no deliveries
    from fenced incarnations — yields zero findings."""
    w1, w2 = {0: 0, 1: 1}, {0: 0}
    events = [
        _world_ev("rdzv_round", 1, w1),
        _world_ev("comm_world", 1, w1),
        {"name": "persist_ack", "step": 10, "group": "", "node": 0},
        {"name": "persist_ack", "step": 10, "group": "", "node": 1},
        {"name": "ckpt_commit", "step": 10, "group": "",
         "num_shards": 2, "shards": 2},
        {"name": "submaster_failover", "rack": "rackA",
         "old_epoch": 2, "new_epoch": 3},
        {"name": "push_fenced", "rack": "rackA", "epoch": 2,
         "current": 3},
        {"name": "rack_action", "rack": "rackA", "epoch": 3,
         "node": 1, "action": "restart"},
        _world_ev("rdzv_round", 2, w2),
    ]
    assert audit.audit_events(events) == []
    assert audit.assert_clean(events, "unit") == len(events)


@pytest.mark.parametrize("invariant,events", [
    ("unique_world", [_world_ev("rdzv_round", 1, {0: 0, 1: 1}),
                      _world_ev("comm_world", 1, {0: 0})]),
    ("duplicate_rank", [{"name": "comm_world", "rdzv": "training",
                         "round": 1, "world": "0:0,1:0"}]),
    ("round_monotonic", [_world_ev("rdzv_round", 2, {0: 0}),
                         _world_ev("rdzv_round", 2, {0: 0})]),
    ("committed_acks", [{"name": "ckpt_commit", "step": 5, "group": "",
                         "num_shards": 2, "shards": 1}]),
    ("committed_acks", [{"name": "persist_ack", "step": 5, "group": "",
                         "node": 0},
                        {"name": "ckpt_commit", "step": 5, "group": "",
                         "num_shards": 2, "shards": 2}]),
    ("epoch_monotonic", [{"name": "submaster_failover", "rack": "r",
                          "old_epoch": 2, "new_epoch": 3},
                         {"name": "submaster_failover", "rack": "r",
                          "old_epoch": 2, "new_epoch": 3}]),
    ("epoch_monotonic", [{"name": "rack_merge", "rack": "r",
                          "proc": "sub", "pid": 1, "epoch": 3},
                         {"name": "rack_merge", "rack": "r",
                          "proc": "sub", "pid": 1, "epoch": 2}]),
    ("fenced_action", [{"name": "push_fenced", "rack": "r",
                        "epoch": 2, "current": 3},
                       {"name": "rack_action", "rack": "r", "epoch": 2,
                        "node": 0, "action": "restart"}]),
])
def test_audit_detects_violation(invariant, events):
    findings = audit.audit_events(events)
    assert [f.invariant for f in findings] == [invariant]
    with pytest.raises(AssertionError, match=invariant):
        audit.assert_clean(events, "unit")


def test_audit_reader_tolerates_rotation_and_torn_lines(tmp_path):
    """The merged reader walks the ``.1`` rotation sibling first and
    skips a SIGKILLed writer's torn final line instead of crashing."""
    base = tmp_path / "events.jsonl"
    (tmp_path / "events.jsonl.1").write_text(
        json.dumps({"name": "first"}) + "\n")
    base.write_text(json.dumps({"name": "second"}) + "\n"
                    + '{"name": "torn", "ro')
    events = audit.read_journal(str(tmp_path))
    assert [e["name"] for e in events] == ["first", "second"]
    assert audit.audit_journal_dir(str(tmp_path)) == []


# ------------------------------------------- push-direction epoch fence


def test_push_fence_rejects_stale_epoch_and_journals(monkeypatch,
                                                     tmp_path):
    """A merged push from a superseded sub-master incarnation is
    rejected whole (fenced=True, nothing merged, one ``push_fenced``
    journal instant); the current incarnation and legacy epoch-0
    pushes pass."""
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path / "j"))
    root = _root(tmp_path)
    try:
        r1 = root.servicer.handle(
            m.SubMasterRegisterRequest(rack_id="rackA", addr="a:1"))
        r2 = root.servicer.handle(
            m.SubMasterRegisterRequest(rack_id="rackA", addr="a:2"))
        assert r2.epoch > r1.epoch
        stale = root.servicer.handle(m.RackMergedReport(
            rack_id="rackA", epoch=r1.epoch,
            heartbeats=[{"node_id": 7, "restart_count": 0}],
        ))
        assert stale.fenced and stale.actions == {}
        fresh = root.servicer.handle(
            m.RackMergedReport(rack_id="rackA", epoch=r2.epoch))
        assert not fresh.fenced
        legacy = root.servicer.handle(
            m.RackMergedReport(rack_id="rackB", epoch=0))
        assert not legacy.fenced
    finally:
        root.stop()
    events = _read(tmp_path / "j")
    fenced = [e for e in events if e["name"] == "push_fenced"]
    assert [(e["rack"], e["epoch"], e["current"]) for e in fenced] == \
        [("rackA", r1.epoch, r2.epoch)]
    assert audit.audit_events(events) == []


def test_root_expires_rack_lease_and_readmits_same_epoch(monkeypatch,
                                                         tmp_path):
    """Past RACK_LEASE_S without an accepted merge the root drops the
    rack from the registered census (one ``lease_expired`` tier=root
    instant) but KEEPS its epoch: lease expiry is not epoch
    invalidation, so the same healed incarnation's next push is
    accepted and re-admits the rack."""
    monkeypatch.setenv(EnvKey.RACK_LEASE_S, "0.2")
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path / "j"))
    root = _root(tmp_path)
    try:
        reg = root.servicer.handle(
            m.SubMasterRegisterRequest(rack_id="rackA", addr="a:1"))
        time.sleep(0.3)
        resp = root.servicer.handle(
            m.RackMergedReport(rack_id="rackA", epoch=reg.epoch))
        assert not resp.fenced  # same incarnation: welcomed back
        assert "rackA" in root.servicer._submaster_leases
    finally:
        root.stop()
    expired = [e for e in _read(tmp_path / "j")
               if e["name"] == "lease_expired" and e["tier"] == "root"]
    assert [(e["rack"], e["epoch"]) for e in expired] == \
        [("rackA", reg.epoch)]


# --------------------------------------- sub-master lease: fail closed


def test_submaster_lease_fail_closed_redirects_then_recovers(
        monkeypatch, tmp_path):
    """Past its lease a sub-master stops serving the mirrored comm
    world (redirect=True, joins dropped, ONE ``lease_expired``
    tier=rack instant per episode); the next accepted upstream push
    renews the lease, serving resumes, and a second lapse re-arms the
    episode journal."""
    monkeypatch.setenv(EnvKey.RACK_LEASE_S, "0.25")
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path / "j"))
    root = _root(tmp_path, min_nodes=1, max_nodes=1)
    sub = SubMaster("rackA", upstream_transport=_Loop(root.servicer.handle),
                    flush_interval_s=3600.0)
    try:
        sub.handle(m.JoinRendezvousRequest(node_id=0, addr="n0:1",
                                           local_devices=4))
        assert sub.flush()
        served = sub.handle(m.CommWorldRequest(node_id=0))
        assert served.completed and not served.redirect

        time.sleep(0.35)
        for _ in range(2):  # once-per-episode journal
            lapsed = sub.handle(m.CommWorldRequest(node_id=0))
            assert not lapsed.completed and lapsed.redirect
        rack_expired = [e for e in _read(tmp_path / "j")
                        if e["name"] == "lease_expired"
                        and e["tier"] == "rack"]
        assert len(rack_expired) == 1
        assert not sub._joins  # buffered joins are the root's to re-form

        # an accepted push is the lease renewal: buffer a heartbeat,
        # flush, and the mirror serves again (same epoch — lease
        # expiry invalidated nothing)
        sub.handle(m.NodeHeartbeat(node_id=0, restart_count=0))
        sub.flush()
        again = sub.handle(m.CommWorldRequest(node_id=0))
        assert again.completed and not again.redirect

        time.sleep(0.35)
        assert sub.handle(m.CommWorldRequest(node_id=0)).redirect
        rack_expired = [e for e in _read(tmp_path / "j")
                        if e["name"] == "lease_expired"
                        and e["tier"] == "rack"]
        assert len(rack_expired) == 2  # episode journal re-armed
    finally:
        root.stop()
        sub._up.close()


# ----------------------------------------------------- sticky re-dial


def test_sticky_redial_pins_to_fallback_until_rack_retry(monkeypatch,
                                                         tmp_path):
    """Pinned to the direct-to-root fallback, the client does NOT
    re-probe the rack port file before the jittered RACK_RETRY_S mark
    (no flapping back to a dead rack address); past the mark a
    republished rack file reclaims it, and the partition edge follows
    the target tier."""
    from dlrover_tpu.agent.master_client import MasterClient

    monkeypatch.setenv(EnvKey.RACK_RETRY_S, "5")
    rack_file = tmp_path / "rack.port"
    root_file = tmp_path / "root.port"
    root_file.write_text("20001")
    client = MasterClient(
        "127.0.0.1:10000", 0,
        transport=RpcClient("127.0.0.1:10000", link=("agent", "rack")),
        port_file=str(rack_file), fallback_port_file=str(root_file),
    )
    try:
        # rack file missing -> degrade to the root, arm the rack retry
        assert client.maybe_redial() is True
        assert client._client.addr == "127.0.0.1:20001"
        assert client._active_target == "fallback"
        assert client._client.link == ("agent", "root")
        armed = client._rack_retry_at - time.monotonic()
        assert 3.5 <= armed <= 6.5  # RACK_RETRY_S jittered +-20%

        # the rack comes back, but the pin holds until the retry mark
        rack_file.write_text("20002")
        assert client.maybe_redial() is False
        assert client._client.addr == "127.0.0.1:20001"

        # past the mark the rack file reclaims the client
        client._rack_retry_at = 0.0
        assert client.maybe_redial() is True
        assert client._client.addr == "127.0.0.1:20002"
        assert client._active_target == "primary"
        assert client._client.link == ("agent", "rack")

        # prefer_fallback (a fail-closed redirect) skips the rack
        # probe entirely, fresh rack file or not
        root_file.write_text("20003")
        rack_file.write_text("20004")
        assert client.maybe_redial(prefer_fallback=True) is True
        assert client._client.addr == "127.0.0.1:20003"
        assert client._active_target == "fallback"
        assert client._client.link == ("agent", "root")
    finally:
        client.close()


# ------------------------------- degraded link: warn bound + staleness


def test_degraded_warn_rate_limited_through_long_outage(monkeypatch,
                                                        tmp_path):
    """A five-minute outage ticking every 5s produces ONE degraded
    enter instant and warns only every DEGRADED_WARN_S — not one line
    per tick — then one exit instant on recovery."""
    from dlrover_tpu.agent import master_link as ml

    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    clock = {"t": 1000.0}
    monkeypatch.setattr(ml.time, "monotonic", lambda: clock["t"])
    warns = []
    monkeypatch.setattr(ml.logger, "warning",
                        lambda msg, *a, **k: warns.append(msg))
    link = ml.MasterLink(object(), component="warnunit",
                         warn_every_s=30.0)
    err = ConnectionError("partitioned")
    for i in range(61):  # ticks at t=0,5,...,300
        clock["t"] = 1000.0 + 5.0 * i
        link.failed(err)
    assert len(warns) == 11  # t=0,30,...,300 only
    link.ok()
    events = [e for e in _read(tmp_path)
              if e["name"] == "degraded_mode"
              and e.get("component") == "warnunit"]
    assert [e["state"] for e in events] == ["enter", "exit"]


def test_link_staleness_bound(monkeypatch, tmp_path):
    """``stale()`` flips only after LINK_STALE_S of continuous
    degradation (one state="stale" instant per episode) and resets
    with the link."""
    from dlrover_tpu.agent import master_link as ml

    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path))
    monkeypatch.setenv(EnvKey.LINK_STALE_S, "50")
    clock = {"t": 2000.0}
    monkeypatch.setattr(ml.time, "monotonic", lambda: clock["t"])
    link = ml.MasterLink(object(), component="staleunit",
                         warn_every_s=1e9)
    assert link.stale() is False  # healthy links are never stale
    link.failed(ConnectionError("partitioned"))
    clock["t"] += 49.0
    assert link.stale() is False
    clock["t"] += 2.0
    assert link.stale() is True
    assert link.stale() is True  # still one journal instant
    link.ok()
    assert link.stale() is False
    link.failed(ConnectionError("partitioned again"))
    clock["t"] += 51.0
    assert link.stale() is True  # second episode journals again

    def _stales():
        return [e for e in _read(tmp_path)
                if e["name"] == "degraded_mode"
                and e.get("component") == "staleunit"
                and e.get("state") == "stale"]
    assert len(_stales()) == 2


# ------------------------------------------- fleetsim partition waves


def test_fleetsim_partition_wave_recovery_and_burst():
    """A netsplit wave cuts a seeded fraction of the fleet, heals, and
    the reconnect stampede fans out under the production retry jitter:
    the run measures a positive recovery time and a reconnect burst
    p99, and two seeded runs replay the identical trail."""
    from dlrover_tpu.fleetsim.profile import FleetProfile
    from dlrover_tpu.fleetsim.sim import FleetSimulator

    p = FleetProfile(name="pwave", seed=11, nodes=200, duration_s=30.0,
                     failures=0, ckpt_interval_s=10.0, partitions=1,
                     partition_s=4.0, partition_frac=0.3)
    r1 = FleetSimulator(p).run()
    r2 = FleetSimulator(FleetProfile.from_json(p.to_json())).run()
    assert r1.trail == r2.trail
    kinds = {e[0] for e in r1.trail["events"]}
    assert {"partition", "heal", "partition_recovered"} <= kinds
    assert r1.partition_recovery_s is not None
    assert r1.partition_recovery_s > 0
    assert r1.reconnect_burst_p99 > 0


# ----------------------------------------------- acceptance scenarios


def test_zombie_submaster_scenario_replay_identical(tmp_path):
    """The §30 zombie acceptance: a real sub-master SIGSTOPped through
    its replacement resumes and pushes — the push bounces off the
    epoch fence exactly once, it steps down, trainers never restart,
    and two seeded runs produce identical canonical trails."""
    from dlrover_tpu.chaos.partition_scenarios import (
        run_zombie_submaster_scenario,
    )

    r1 = run_zombie_submaster_scenario(str(tmp_path / "run1"), seed=4242)
    r1.assert_invariants()
    r2 = run_zombie_submaster_scenario(str(tmp_path / "run2"), seed=4242)
    r2.assert_invariants()
    assert r1.trail == r2.trail


def test_asym_split_scenario_redelivery_ledger(tmp_path):
    """An asymmetric split (each direction cut in turn) heals through
    the redelivery queue: every ack lands exactly once (rid dedup
    absorbs the replay whose response was lost) and the partition
    transition ledger is exact."""
    from dlrover_tpu.chaos.partition_scenarios import (
        run_asym_split_scenario,
    )

    run_asym_split_scenario(str(tmp_path / "run"),
                            seed=4242).assert_invariants()


def test_rack_split_scenario_fails_closed_and_readmits(tmp_path):
    """A rack-wide split during rendezvous: the sub-master's lease
    lapses and it fails closed, agents complete the round via the
    direct-to-root redirect, the root expires the rack lease, and the
    healed same-epoch sub-master is re-admitted — zero restarts."""
    from dlrover_tpu.chaos.partition_scenarios import (
        run_rack_split_scenario,
    )

    run_rack_split_scenario(str(tmp_path / "run"),
                            seed=4242).assert_invariants()
