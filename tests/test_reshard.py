"""Elastic mesh resharding + the persistent compile cache (DESIGN.md §17).

Covers the three tentpole pieces in isolation and end to end:

- the master-side ``CompileCacheService`` (LRU bytes bound, coverage
  queries, fingerprint-mismatch-as-miss) and its RPC surface;
- the AOT executable round trip (``load_or_compile``: compile once,
  every later incarnation loads in ~0.1s and computes bit-identically)
  and the fallback-topology precompiler;
- ``reshard_state``: N -> N−1 -> N round-trips the train state
  bit-exactly (per-shard CRC via ``checkpoint/integrity.py``), through
  both the mesh-level remap and the engine's shm-snapshot path;
- the rendezvous shrink fast path (a node loss completes the round
  immediately as a ``reshard`` event, no waiting_timeout backoff);
- a chaos-harness kill scenario whose recovery trail shows ``reshard``
  + a cache-hit compile instead of a cold one.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.checkpoint.integrity import crc32_bytes
from dlrover_tpu.master.kv_store import CompileCacheService, topology_tag
from dlrover_tpu.parallel import compile_cache as cc
from dlrover_tpu.parallel.mesh import build_mesh, remap_spec, reshard_state


# ----------------------------------------------------- master-side service


class TestCompileCacheService:
    def test_put_get_evict(self):
        svc = CompileCacheService()
        key = f"{topology_tag(8, 2)}/abc"
        assert svc.put(key, b"blob", {"m": 1})
        assert svc.get(key) == (b"blob", {"m": 1})
        assert svc.evict(key)
        assert svc.get(key) is None
        assert not svc.evict(key)

    def test_lru_byte_bound_evicts_oldest(self):
        svc = CompileCacheService(max_bytes=100)
        svc.put("t8n2/a", b"x" * 40)
        svc.put("t8n2/b", b"x" * 40)
        svc.get("t8n2/a")            # refresh a: b becomes LRU
        svc.put("t8n2/c", b"x" * 40)  # 120 bytes -> evict b
        assert svc.get("t8n2/b") is None
        assert svc.get("t8n2/a") is not None
        assert svc.get("t8n2/c") is not None
        assert svc.stats()["bytes"] <= 100

    def test_oversized_entry_refused(self):
        svc = CompileCacheService(max_bytes=100, max_entry_bytes=50)
        assert not svc.put("t8n2/big", b"x" * 51)
        assert svc.stats()["entries"] == 0

    def test_coverage_is_a_topology_prefix_scan(self):
        svc = CompileCacheService()
        svc.put(f"{topology_tag(8, 2)}/a", b"1")
        svc.put(f"{topology_tag(4, 1)}/b", b"2")
        assert svc.covers(topology_tag(8, 2)) == 1
        assert svc.covers(topology_tag(4, 1)) == 1
        assert svc.covers(topology_tag(16, 4)) == 0


class TestCompileCacheRpc:
    def test_put_get_query_round_trip(self):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(port=0, rdzv_timeout=2.0)
        master.prepare()
        try:
            c = MasterClient(master.addr, 0)
            tag = topology_tag(8, 2)
            blob = bytes(range(256)) * 64  # binary payload over serde
            assert c.compile_cache_put(f"{tag}/k1", blob,
                                       {"inputs": {"model": "tiny"}})
            got = c.compile_cache_get(f"{tag}/k1")
            assert got is not None
            assert got[0] == blob
            assert got[1]["inputs"]["model"] == "tiny"
            assert c.compile_cache_get(f"{tag}/other") is None
            q = c.compile_cache_query(tag)
            assert q.covered and q.executables == 1
            assert not c.compile_cache_query(topology_tag(4, 1)).covered
            c.close()
        finally:
            master.stop()


# -------------------------------------------------- fingerprint + envelope


class TestFingerprint:
    def _fp(self, **over):
        kw = dict(num_nodes=2, total_devices=8,
                  mesh_axes={"data": 8}, model={"layers": 2},
                  strategy={"name": "dp"}, args_signature=[[8, 4]],
                  extra={})
        kw.update(over)
        return cc.compile_fingerprint(**kw)

    def test_stable_and_topology_prefixed(self):
        key1, inputs = self._fp()
        key2, _ = self._fp()
        assert key1 == key2
        assert key1.startswith(topology_tag(8, 2) + "/")
        assert inputs["jax"] == jax.__version__

    def test_every_input_changes_the_key(self):
        base, _ = self._fp()
        assert self._fp(model={"layers": 3})[0] != base
        assert self._fp(strategy={"name": "fsdp"})[0] != base
        assert self._fp(num_nodes=1)[0] != base
        assert self._fp(mesh_axes={"data": 4, "tensor": 2})[0] != base
        assert self._fp(args_signature=[[16, 4]])[0] != base


def _tiny_aot():
    """A small sharded+donated executable (compiles in well under 1s)."""
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    def step(w, x):
        y = jnp.tanh(x @ w)
        return w - 0.01 * y.sum() * w, (y * y).mean()

    jitted = jax.jit(step, in_shardings=(rep, sh),
                     out_shardings=(rep, rep), donate_argnums=(0,))

    def fresh_args():
        # donation consumes w on every call: hand out fresh buffers
        return (jax.device_put(jnp.arange(64.0).reshape(8, 8) / 64.0,
                               rep),
                jax.device_put(jnp.ones((8, 8)), sh))

    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=a.sharding),
        fresh_args())
    return jitted, abstract, fresh_args


class TestEnvelope:
    def test_round_trip_is_bit_identical(self):
        jitted, abstract, fresh_args = _tiny_aot()
        compiled = jitted.lower(*abstract).compile()
        _, ref = compiled(*fresh_args())
        blob = cc.serialize_executable_blob(compiled, {"k": 1})
        loaded = cc.load_executable_blob(blob, expect_inputs={"k": 1})
        assert loaded is not None
        _, got = loaded(*fresh_args())
        assert float(got) == float(ref)

    def test_corruption_and_mismatch_read_as_miss(self):
        jitted, abstract, _ = _tiny_aot()
        compiled = jitted.lower(*abstract).compile()
        blob = cc.serialize_executable_blob(compiled, {"k": 1})
        flipped = bytearray(blob)
        flipped[len(flipped) // 2] ^= 0x10
        assert cc.load_executable_blob(bytes(flipped)) is None
        # same digest, different recorded inputs -> fingerprint
        # mismatch -> miss (never a wrong program)
        assert cc.load_executable_blob(blob,
                                       expect_inputs={"k": 2}) is None
        assert cc.load_executable_blob(b"junk") is None


class TestLoadOrCompile:
    def test_miss_compiles_then_hit_loads(self, tmp_path):
        jitted, abstract, fresh_args = _tiny_aot()
        client = cc.CompileCacheClient(local_dir=str(tmp_path / "aot"))
        key, inputs = cc.compile_fingerprint(
            num_nodes=1, total_devices=8, mesh_axes={"data": 8},
            model={"t": "tiny_aot"}, strategy={"name": "dp"},
            args_signature=cc.abstract_signature(abstract),
        )
        first = cc.load_or_compile(
            key, inputs,
            compile_fn=lambda: jitted.lower(*abstract).compile(),
            cache=client)
        assert not first.cache_hit and first.source == "compiled"
        _, ref = first.fn(*fresh_args())
        second = cc.load_or_compile(
            key, inputs,
            compile_fn=lambda: pytest.fail("hit must not compile"),
            cache=client)
        assert second.cache_hit and second.source == "local"
        _, got = second.fn(*fresh_args())
        assert float(got) == float(ref)

    def test_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_AOT_CACHE", "0")
        jitted, abstract, _ = _tiny_aot()
        client = cc.CompileCacheClient(local_dir=str(tmp_path / "aot"))
        got = cc.load_or_compile(
            "t8n1/x", {},
            compile_fn=lambda: jitted.lower(*abstract).compile(),
            cache=client)
        assert not got.cache_hit and got.source == "disabled"
        assert not os.path.exists(str(tmp_path / "aot"))

    def test_local_prune_keeps_newest(self, tmp_path):
        client = cc.CompileCacheClient(local_dir=str(tmp_path / "aot"),
                                       max_local_files=10)
        base = time.time() - 100
        for i in range(4):
            client.put(f"t8n1/k{i}", b"blob%d" % i)
            # strictly ordered mtimes in the PAST (a future mtime would
            # make the freshly written file look oldest)
            os.utime(client._path(f"t8n1/k{i}"), (base + i, base + i))
        client.max_local_files = 2
        client._prune()
        files = sorted(os.listdir(str(tmp_path / "aot")))
        assert files == ["t8n1_k2.aot", "t8n1_k3.aot"]


class TestFallbackPrecompiler:
    def test_precompiles_and_publishes_smaller_world(self, tmp_path):
        client = cc.CompileCacheClient(local_dir=str(tmp_path / "aot"))
        built_for: list[int] = []

        def build_fn(n_nodes: int):
            if n_nodes != 1:
                return None  # only the 4-device single-node fallback
            built_for.append(n_nodes)
            devices = jax.devices()[:4]
            mesh = Mesh(np.array(devices).reshape(4), ("data",))
            sh = NamedSharding(mesh, P("data"))
            rep = NamedSharding(mesh, P())
            jitted = jax.jit(lambda w, x: (x @ w).sum(),
                             in_shardings=(rep, sh), out_shardings=rep)
            abstract = (jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                             sharding=rep),
                        jax.ShapeDtypeStruct((4, 8), jnp.float32,
                                             sharding=sh))
            key, inputs = cc.compile_fingerprint(
                num_nodes=n_nodes, total_devices=4,
                mesh_axes={"data": 4}, model={"t": "fb"},
                strategy={"name": "dp"},
                args_signature=cc.abstract_signature(abstract),
            )
            return key, inputs, (
                lambda: jitted.lower(*abstract).compile())

        pre = cc.FallbackPrecompiler(
            build_fn, world_sizes=[1, 3], cache=client, delay_s=0.0,
        ).start()
        assert pre.wait(timeout=120)
        assert pre.results[1] == "published"
        assert pre.results[3] == "infeasible"
        assert built_for == [1]
        # the published artifact is loadable and keyed by the topology
        key = [k for k in os.listdir(str(tmp_path / "aot"))]
        assert len(key) == 1 and key[0].startswith("n1t4_")
        # re-arming skips work: already cached
        again = cc.FallbackPrecompiler(
            build_fn, world_sizes=[1], cache=client, delay_s=0.0,
        ).start()
        assert again.wait(timeout=30)
        assert again.results[1] == "already_cached"


# --------------------------------------------------------- state reshard


def _sharded_state(mesh):
    """A TrainState-shaped pytree with mixed layouts: replicated step,
    data-sharded 'dp' leaf, tensor-ish 2D shard, odd-shaped leaf."""
    put = lambda arr, spec: jax.device_put(  # noqa: E731
        arr, NamedSharding(mesh, spec))
    axes = list(mesh.axis_names)
    first = axes[0]
    return {
        "step": put(jnp.asarray(7, jnp.int32), P()),
        "w_dp": put(jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
                    P(first)),
        "w_2d": put(jnp.arange(64, dtype=jnp.bfloat16).reshape(8, 8),
                    P(None, first)),
        "bias": put(jnp.arange(24, dtype=jnp.float32), P()),
    }


def _shard_crcs(state) -> dict[str, int]:
    """Per-LEAF CRC of the fully-gathered bytes: layout-independent
    identity (per-device shard boundaries legitimately move across a
    reshard; the bytes must not)."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        host = np.asarray(jax.device_get(leaf))
        out[str(path)] = crc32_bytes(host.tobytes())
    return out


class TestReshardState:
    def test_n_to_n_minus_1_to_n_is_bit_exact(self):
        mesh8 = build_mesh({"data": -1}, devices=jax.devices())
        mesh4 = build_mesh({"data": -1}, devices=jax.devices()[:4])
        state = _sharded_state(mesh8)
        before = _shard_crcs(state)

        shrunk = reshard_state(mesh8, mesh4, state)
        # every leaf actually lives on the 4-device mesh, same specs
        for leaf in jax.tree_util.tree_leaves(shrunk):
            assert leaf.sharding.mesh.devices.size == 4
        assert shrunk["w_dp"].sharding.spec == P("data")
        assert _shard_crcs(shrunk) == before

        back = reshard_state(mesh4, mesh8, shrunk)
        for leaf in jax.tree_util.tree_leaves(back):
            assert leaf.sharding.mesh.devices.size == 8
        assert _shard_crcs(back) == before
        # per-device shards on the restored mesh match the original
        # layout exactly too
        for name in ("w_dp", "w_2d"):
            orig = [crc32_bytes(np.asarray(s.data).tobytes())
                    for s in state[name].addressable_shards]
            rest = [crc32_bytes(np.asarray(s.data).tobytes())
                    for s in back[name].addressable_shards]
            assert orig == rest

    def test_dropped_axis_replicates(self):
        mesh = build_mesh({"data": 4, "tensor": 2},
                          devices=jax.devices())
        mesh_dp = build_mesh({"data": -1}, devices=jax.devices()[:4])
        assert remap_spec(P("tensor"), mesh_dp) == P()
        assert remap_spec(P(None, ("data", "tensor")), mesh_dp) \
            == P(None, "data")
        state = {
            "w": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                NamedSharding(mesh, P("data", "tensor"))),
        }
        before = _shard_crcs(state)
        moved = reshard_state(mesh, mesh_dp, state)
        assert moved["w"].sharding.spec == P("data")
        assert _shard_crcs(moved) == before

    def test_reshard_emits_metric_and_journal(self, tmp_path,
                                              monkeypatch):
        # get_journal() re-resolves when the dir env changes: no reset
        monkeypatch.setenv("DLROVER_TPU_JOURNAL_DIR", str(tmp_path))
        mesh8 = build_mesh({"data": -1}, devices=jax.devices())
        mesh4 = build_mesh({"data": -1}, devices=jax.devices()[:4])
        reshard_state(mesh8, mesh4, _sharded_state(mesh8))
        events = [json.loads(line) for line in
                  open(tmp_path / "events.jsonl")]
        reshards = [e for e in events if e["name"] == "reshard"]
        assert reshards and reshards[0]["leaves"] == 4
        assert reshards[0]["new_devices"] == 4

    def test_engine_reshard_uses_the_shm_snapshot(self, tmp_ipc_dir,
                                                  tmp_path):
        from dlrover_tpu.checkpoint.engine import CheckpointEngine

        mesh8 = build_mesh({"data": -1}, devices=jax.devices())
        mesh4 = build_mesh({"data": -1}, devices=jax.devices()[:4])
        state = _sharded_state(mesh8)
        before = _shard_crcs(state)
        eng = CheckpointEngine(str(tmp_path / "ckpt"))
        try:
            shrunk = eng.reshard_state(mesh8, mesh4, state, step=7)
            assert _shard_crcs(shrunk) == before
            for leaf in jax.tree_util.tree_leaves(shrunk):
                assert leaf.sharding.mesh.devices.size == 4
            # the reshard's snapshot doubles as the rollback point
            loaded = eng.load_raw()
            assert loaded is not None and loaded[0] == 7
        finally:
            eng.close()


# ------------------------------------------- rendezvous shrink fast path


class TestRendezvousShrinkFastPath:
    def test_node_loss_completes_immediately_as_reshard(self):
        from dlrover_tpu.master.rdzv_manager import RendezvousManager

        mgr = RendezvousManager(min_nodes=1, max_nodes=3,
                                waiting_timeout=30.0)
        for nid in (0, 1, 2):
            mgr.join(nid, f"n{nid}:1", 4)
        first = mgr.get_comm_world(0)
        assert first is not None and not first.reshard
        # node 2 dies; survivors re-join — the round must complete NOW
        # (no 30s backoff) and be marked a reshard event
        mgr.remove_node(2)
        mgr.join(0, "n0:2", 4)
        assert mgr.get_comm_world(0) is None  # partial: node 1 missing
        mgr.join(1, "n1:2", 4)
        t0 = time.monotonic()
        world = mgr.get_comm_world(0)
        assert time.monotonic() - t0 < 0.1
        assert world is not None and world.reshard
        assert set(world.world) == {0, 1}
        assert world.total_devices == 8

    def test_departed_member_rejoining_disables_both_fast_paths(self):
        from dlrover_tpu.master.rdzv_manager import RendezvousManager

        mgr = RendezvousManager(min_nodes=2, max_nodes=3,
                                waiting_timeout=0.5)
        for nid in (0, 1):
            mgr.join(nid, f"n{nid}:1", 4)
        time.sleep(0.6)
        assert mgr.get_comm_world(0) is not None
        mgr.remove_node(1)
        mgr.join(0, "n0:2", 4)
        mgr.join(1, "n1:2", 4)  # the "dead" node came back: full round
        assert mgr.get_comm_world(0) is None
        time.sleep(0.6)
        world = mgr.get_comm_world(0)
        assert world is not None and not world.reshard
        assert set(world.world) == {0, 1}

    def test_shrink_below_min_nodes_waits(self):
        from dlrover_tpu.master.rdzv_manager import RendezvousManager

        mgr = RendezvousManager(min_nodes=2, max_nodes=2,
                                waiting_timeout=0.3)
        for nid in (0, 1):
            mgr.join(nid, f"n{nid}:1", 4)
        time.sleep(0.4)
        assert mgr.get_comm_world(0) is not None
        mgr.remove_node(1)
        mgr.join(0, "n0:2", 4)
        time.sleep(0.4)
        assert mgr.get_comm_world(0) is None  # 1 < min_nodes: no world


# ------------------------------------------------- chaos: reshard trail


@pytest.mark.timeout(300)
def test_kill_recovery_trail_shows_reshard_and_warm_compile(tmp_path):
    """The tentpole end to end, under the chaos harness: the trainer is
    SIGKILLed mid-run; incarnation 0 published its executable, so the
    master's coverage query makes the recovery a *reshard* event (trail
    shows ``reshard``) and the promoted standby's "recompile" is a
    cache-hit load, not a cold XLA compile."""
    from dlrover_tpu.chaos.scenario import (
        JobLeg,
        Scenario,
        _read_journal,
        run_scenario,
    )

    scenario = Scenario(
        name="kill_reshard", seed=777,
        legs=[JobLeg(
            name="kill_warm", max_steps=12,
            faults=[{"point": "agent_kill_trainer", "action": "kill",
                     "args": {"sig": 9},
                     "match": {"step_gte": 6}, "times": 1}],
            train_args=["--ckpt-interval", "1000000",
                        "--mem-ckpt-interval", "2",
                        "--step-delay", "0.12"],
        )],
    )
    work = str(tmp_path / "run")
    res = run_scenario(
        scenario, work,
        env_extra={"DLROVER_TPU_PLATFORM": "cpu",
                   "DLROVER_TPU_DEVICE_COUNT": "1",
                   "DLROVER_TPU_STANDBY": "1"},
        deadline_s=160,
    )
    res.assert_invariants()
    assert res.legs[0].result["restart_count"] == 1
    assert res.legs[0].result["final_step"] == 12

    # the recovery trail records the reshard choice (1 node, no shrink)
    assert ["reshard", 1, False] in res.trail["recovery"]

    events = _read_journal(os.path.join(work, "journal"))
    compiles = [e for e in events if e.get("name") == "compile"]
    assert len(compiles) == 2, compiles
    # incarnation 0 compiled cold; the promoted standby loaded the
    # cached executable — recovery skipped the recompile cost class
    assert compiles[0].get("cache_hit") is False
    assert compiles[1].get("cache_hit") is True
    cache_events = [e for e in events
                    if e.get("name") == "compile_cache"]
    assert len(cache_events) == 2, cache_events
    assert cache_events[0]["hit"] is False  # inc 0: compile + publish
    assert cache_events[1]["hit"] is True   # promoted standby: load
    # the warm "recompile" is an executable load: ≥5x under the cold
    # XLA compile (the acceptance floor; local loads measure ~20-30x)
    assert cache_events[1]["dur"] <= cache_events[0]["dur"] / 5.0

    # and the lost-time report splits the categories accordingly
    from dlrover_tpu.telemetry.report import build_report

    rep = build_report(os.path.join(work, "journal"))
    assert rep.categories["recompile_cold"] > 0
    assert rep.categories["recompile_warm"] >= 0
    assert rep.categories["recompile_warm"] \
        <= rep.categories["recompile_cold"] / 5.0
