"""AGD/WSAM optimizers + profiler utilities.

Reference analog: atorch optimizer unit tests (convergence on toy
problems) and AProfiler's flop accounting.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.optimizers import agd, wsam
from dlrover_tpu.utils import profiler


def _quadratic(params, batch=None):
    # min at x = 3, y = -1
    return (params["x"] - 3.0) ** 2 + 2.0 * (params["y"] + 1.0) ** 2


class TestAGD:
    def test_converges_on_quadratic(self):
        params = {"x": jnp.asarray(0.0), "y": jnp.asarray(0.0)}
        opt = agd(learning_rate=0.1)
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            g = jax.grad(_quadratic)(params)
            updates, state = opt.update(g, state)
            return optax.apply_updates(params, updates), state

        for _ in range(300):
            params, state = step(params, state)
        assert abs(float(params["x"]) - 3.0) < 1e-2
        assert abs(float(params["y"]) + 1.0) < 1e-2

    def test_first_step_matches_adam_direction(self):
        """Step 1 uses diff = grad, so the update direction equals Adam's
        sign(g)-scaled step for large gradients."""
        params = {"x": jnp.asarray(0.0)}
        opt = agd(learning_rate=0.1, delta=1e-12)
        state = opt.init(params)
        g = {"x": jnp.asarray(4.0)}
        updates, _ = opt.update(g, state)
        np.testing.assert_allclose(float(updates["x"]), -0.1, atol=1e-5)

    def test_trains_tiny_transformer_step(self):
        from functools import partial

        from dlrover_tpu.models import transformer as tfm

        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 65), 0, cfg.vocab_size
        )
        opt = agd(learning_rate=1e-3)
        state = opt.init(params)
        loss_fn = partial(tfm.loss_fn, cfg=cfg)

        @jax.jit
        def step(params, state):
            loss, g = jax.value_and_grad(loss_fn)(
                params, {"tokens": tokens}
            )
            updates, state = opt.update(g, state)
            return optax.apply_updates(params, updates), state, loss

        losses = []
        for _ in range(8):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestWSAM:
    def test_converges_and_prefers_flat_minima(self):
        init, step = wsam(
            _quadratic, optax.sgd(0.1), rho=0.05, gamma=0.5
        )
        params = {"x": jnp.asarray(0.0), "y": jnp.asarray(0.0)}
        state = init(params)
        jit_step = jax.jit(lambda p, s: step(p, s, None))
        for _ in range(200):
            params, state, loss = jit_step(params, state)
        assert abs(float(params["x"]) - 3.0) < 5e-2
        assert abs(float(params["y"]) + 1.0) < 5e-2

    def test_gamma_zero_equals_base(self):
        init, step = wsam(_quadratic, optax.sgd(0.1), rho=0.1, gamma=0.0)
        params = {"x": jnp.asarray(0.0), "y": jnp.asarray(0.0)}
        state = init(params)
        params2 = {"x": jnp.asarray(0.0), "y": jnp.asarray(0.0)}
        params, state, _ = step(params, state, None)
        g = jax.grad(_quadratic)(params2)
        expected = jax.tree.map(lambda p, gi: p - 0.1 * gi, params2, g)
        np.testing.assert_allclose(
            float(params["x"]), float(expected["x"]), atol=1e-6
        )


class TestAdam8bit:
    def test_states_are_int8_above_threshold(self):
        from dlrover_tpu.optimizers import adam_8bit

        params = {"w": jnp.zeros((5000,)), "b": jnp.zeros((3,))}
        opt = adam_8bit(1e-3)
        state = opt.init(params)
        assert state.mu["w"].codes.dtype == jnp.int8
        assert state.nu["w"].codes.dtype == jnp.int8
        # 20 blocks of 256 cover 5000 elements
        assert state.mu["w"].codes.shape == (20, 256)
        # small leaves (biases/norms) keep fp32 moments — quantizing a
        # (3,) leaf into a 256-wide block would cost memory and precision
        assert state.mu["b"].dtype == jnp.float32
        assert state.mu["b"].shape == (3,)

    def test_tracks_fp32_adam(self):
        """A few steps of 8-bit Adam stay close to exact Adam."""
        from dlrover_tpu.optimizers import adam_8bit

        params_a = {"x": jnp.asarray([0.0, 0.0])}
        params_b = {"x": jnp.asarray([0.0, 0.0])}
        opt_a = adam_8bit(0.05, block_size=256)
        opt_b = optax.adam(0.05)
        sa, sb = opt_a.init(params_a), opt_b.init(params_b)

        def grad(p):
            return {"x": 2 * (p["x"] - jnp.asarray([3.0, -1.0]))}

        step_a = jax.jit(
            lambda p, s: (lambda u, s2: (optax.apply_updates(p, u), s2))(
                *opt_a.update(grad(p), s)
            )
        )
        step_b = jax.jit(
            lambda p, s: (lambda u, s2: (optax.apply_updates(p, u), s2))(
                *opt_b.update(grad(p), s)
            )
        )
        for _ in range(100):
            params_a, sa = step_a(params_a, sa)
            params_b, sb = step_b(params_b, sb)
        np.testing.assert_allclose(
            np.asarray(params_a["x"]), np.asarray(params_b["x"]),
            atol=0.05,
        )

    def test_converges_on_tiny_transformer(self):
        from functools import partial

        from dlrover_tpu.models import transformer as tfm
        from dlrover_tpu.optimizers import adam_8bit

        cfg = tfm.CONFIGS["tiny"]
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 65), 0, cfg.vocab_size
        )
        opt = adam_8bit(1e-2)
        state = opt.init(params)
        loss_fn = partial(tfm.loss_fn, cfg=cfg)

        @jax.jit
        def step(params, state):
            loss, g = jax.value_and_grad(loss_fn)(
                params, {"tokens": tokens}
            )
            updates, state = opt.update(g, state)
            return optax.apply_updates(params, updates), state, loss

        losses = []
        for _ in range(10):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestProfiler:
    def test_compiled_flops_matmul(self):
        a = jnp.ones((128, 128), jnp.float32)
        f = jax.jit(lambda a: a @ a)
        f(a)  # warm the cache
        flops = profiler.compiled_flops(f, a)
        # 2*n^3 matmul flops (allow backend fudge)
        assert flops == pytest.approx(2 * 128**3, rel=0.5)

    def test_profile_train_step(self):
        a = jnp.ones((64, 64), jnp.float32)

        @jax.jit
        def fake_step(state, batch):
            out = state @ batch
            return out, {"loss": out.sum()}

        state, stats = profiler.profile_train_step(
            fake_step, a, a, steps=5
        )
        assert stats.steps == 5
        assert stats.mean_s > 0
        assert stats.flops_per_step > 0

    def test_step_profiler_stats(self):
        prof = profiler.StepProfiler(
            flops_per_step=1e9, peak_flops=1e12, num_devices=1
        )
        import time as _time

        for _ in range(5):
            with prof.step():
                _time.sleep(0.001)
        s = prof.stats()
        assert s.steps == 5
        assert s.mean_s >= 0.001
        assert s.mfu is not None and 0 < s.mfu < 1


class TestFlopsBreakdown:
    """Analytic per-op-class FLOPs from the jaxpr (the AProfiler
    per-op formula table analog, atorch/utils/prof.py:482)."""

    def test_matmul_exact(self):
        from dlrover_tpu.utils.profiler import flops_breakdown

        a = jnp.zeros((64, 32))
        b = jnp.zeros((32, 48))
        bd = flops_breakdown(lambda a, b: a @ b, a, b)
        assert bd["dot_general"] == 2 * 64 * 32 * 48
        assert bd["total"] >= bd["dot_general"]

    def test_scan_multiplies_by_trip_count(self):
        from dlrover_tpu.utils.profiler import flops_breakdown

        def g(x, ws):
            return jax.lax.scan(lambda c, w: (c @ w, None), x, ws)[0]

        bd = flops_breakdown(g, jnp.zeros((8, 32)), jnp.zeros((5, 32, 32)))
        assert bd["dot_general"] == 5 * 2 * 8 * 32 * 32

    def test_grad_counts_backward_dots(self):
        from dlrover_tpu.utils.profiler import flops_breakdown

        b = jnp.zeros((32, 48))
        bd = flops_breakdown(
            jax.grad(lambda a: jnp.sum(a @ b)), jnp.zeros((64, 32))
        )
        # fwd + the single dA backward dot (dB not needed: b is closed
        # over, not differentiated), each 2*64*32*48
        assert bd["dot_general"] == pytest.approx(2 * 2 * 64 * 32 * 48)

    def test_model_dots_near_analytic(self):
        from dlrover_tpu.models import transformer as T
        from dlrover_tpu.utils.profiler import flops_breakdown

        cfg = T.CONFIGS["tiny"]
        params = T.init_params(cfg, jax.random.PRNGKey(0))
        tokens = {"tokens": jnp.zeros((2, 65), jnp.int32)}
        bd = flops_breakdown(
            lambda p: T.loss_fn(p, tokens, cfg=cfg), params
        )
        analytic = 2 * cfg.param_count * 2 * 64  # 2N per token forward
        # embedding gathers aren't dots, so the measured count sits a
        # bit under the parameter-based estimate
        assert 0.7 * analytic < bd["dot_general"] <= 1.1 * analytic
        assert bd["elementwise"] > 0 and bd["reduce"] > 0


class TestAdam4bit:
    def test_states_are_packed_nibbles(self):
        from dlrover_tpu.optimizers import adam_4bit

        params = {"w": jnp.zeros((5000,)), "b": jnp.zeros((3,))}
        opt = adam_4bit(1e-3)
        state = opt.init(params)
        # 40 blocks of 128, two codes per byte -> 64 bytes per block
        assert state.mu["w"].codes.dtype == jnp.int8
        assert state.mu["w"].codes.shape == (40, 64)
        # half the int8 footprint of adam_8bit for the same leaf
        assert state.mu["b"].dtype == jnp.float32

    def test_quantize_roundtrip_error_bounded(self):
        from dlrover_tpu.optimizers.low_bit import (
            _dequantize4,
            _quantize4,
        )

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(1024,)).astype(np.float32))
        for signed in (True, False):
            vals = jnp.abs(x) if not signed else x
            codes, scales = _quantize4(vals, 128, signed)
            back = _dequantize4(codes, scales, vals.shape, 128, signed)
            # quadratic codebook: coarse at the block max, fine near 0
            err = np.abs(np.asarray(back - vals))
            scale_of = np.repeat(np.asarray(scales), 128)[: vals.size]
            assert np.all(err <= 0.16 * scale_of + 1e-7)

    def test_tracks_fp32_adam(self):
        from dlrover_tpu.optimizers import adam_4bit

        params_a = {"x": jnp.zeros((256,))}
        params_b = {"x": jnp.zeros((256,))}
        target = jnp.asarray(
            np.random.default_rng(1).normal(size=(256,)).astype(
                np.float32)
        )
        opt_a = adam_4bit(0.05, min_quant_size=1)
        opt_b = optax.adam(0.05)
        sa, sb = opt_a.init(params_a), opt_b.init(params_b)

        def grad(p):
            return {"x": 2 * (p["x"] - target)}

        step_a = jax.jit(
            lambda p, s: (lambda u, s2: (optax.apply_updates(p, u), s2))(
                *opt_a.update(grad(p), s)
            )
        )
        step_b = jax.jit(
            lambda p, s: (lambda u, s2: (optax.apply_updates(p, u), s2))(
                *opt_b.update(grad(p), s)
            )
        )
        for _ in range(150):
            params_a, sa = step_a(params_a, sa)
            params_b, sb = step_b(params_b, sb)
        # both should be near the target; 4-bit tracks within tolerance
        assert float(jnp.abs(params_a["x"] - target).mean()) < 0.1
        np.testing.assert_allclose(
            np.asarray(params_a["x"]), np.asarray(params_b["x"]),
            atol=0.15,
        )
