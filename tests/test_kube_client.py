"""KubernetesClient against a stubbed API-server HTTP transport.

Round-2 verdict Missing #2 / Next #3: the cluster layer previously only
ever ran against in-memory fakes. These tests drive the REAL client —
urllib transport, JSON bodies, label-selector queries, streaming watch,
CR status subresource — through a stdlib HTTP server that imitates the
kube-apiserver surface the client uses, then run PodScaler, PodWatcher,
and the operator's CR sync loop over it end-to-end.

Reference analog: dlrover/python/tests exercising k8sClient against
mocked API responses (scheduler/kubernetes.py:121), and the Go
operator's envtest-style controller tests.
"""

from __future__ import annotations

import base64
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from dlrover_tpu.cluster.crd import (
    GROUP,
    VERSION,
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    ScalePlan,
)
from dlrover_tpu.cluster.kube_client import ApiError, KubernetesClient
from dlrover_tpu.cluster.operator import CrSync, ElasticJobOperator
from dlrover_tpu.cluster.scaler import PodScaler
from dlrover_tpu.cluster.watcher import PodEvent, PodWatcher


def _matches(selector: str, labels: dict) -> bool:
    for clause in filter(None, selector.split(",")):
        k, _, v = clause.partition("=")
        if labels.get(k) != v:
            return False
    return True


class _State:
    """In-memory cluster state behind the HTTP surface."""

    def __init__(self):
        self.lock = threading.Lock()
        self.pods: dict[tuple[str, str], dict] = {}
        self.services: dict[tuple[str, str], dict] = {}
        self.customs: dict[tuple[str, str, str], dict] = {}
        self.watchers: list[tuple[queue.Queue, str, str]] = []
        self.requests: list[tuple[str, str, str]] = []  # method, path, auth

    def notify(self, event_type: str, pod: dict) -> None:
        ns = pod["metadata"].get("namespace", "default")
        labels = pod["metadata"].get("labels", {})
        with self.lock:
            for q, wns, selector in self.watchers:
                if wns == ns and _matches(selector, labels):
                    q.put({"type": event_type, "object": pod})


def _handler(state: _State):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # noqa: D102 - silence
            pass

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n)) if n else {}

        def _record(self):
            state.requests.append((
                self.command, self.path,
                self.headers.get("Authorization", ""),
            ))

        # ---- routing helpers
        def _route(self):
            u = urlparse(self.path)
            parts = [p for p in u.path.split("/") if p]
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            return parts, q

        def do_GET(self):  # noqa: N802
            self._record()
            parts, q = self._route()
            if parts[:2] == ["api", "v1"] and parts[4] == "pods":
                ns = parts[3]
                if len(parts) == 6:
                    pod = state.pods.get((ns, parts[5]))
                    if pod is None:
                        return self._json(404, {"reason": "NotFound"})
                    return self._json(200, pod)
                selector = q.get("labelSelector", "")
                if q.get("watch") == "true":
                    return self._watch(ns, selector)
                with state.lock:
                    items = [
                        p for (pns, _), p in state.pods.items()
                        if pns == ns and _matches(
                            selector, p["metadata"].get("labels", {}))
                    ]
                return self._json(200, {"items": items})
            if parts[0] == "apis" and parts[1] == GROUP:
                ns, plural = parts[4], parts[5]
                if len(parts) == 7:
                    obj = state.customs.get((ns, plural, parts[6]))
                    if obj is None:
                        return self._json(404, {"reason": "NotFound"})
                    return self._json(200, obj)
                with state.lock:
                    items = [
                        o for (ons, op, _), o in state.customs.items()
                        if ons == ns and op == plural
                    ]
                return self._json(200, {"items": items})
            return self._json(404, {"reason": "NotFound"})

        def _watch(self, ns: str, selector: str) -> None:
            events: queue.Queue = queue.Queue()
            with state.lock:
                state.watchers.append((events, ns, selector))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                while True:
                    try:
                        ev = events.get(timeout=0.2)
                    except queue.Empty:
                        continue
                    line = (json.dumps(ev) + "\n").encode()
                    self.wfile.write(
                        f"{len(line):x}\r\n".encode() + line + b"\r\n"
                    )
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            finally:
                with state.lock:
                    state.watchers[:] = [
                        w for w in state.watchers if w[0] is not events
                    ]

        def do_POST(self):  # noqa: N802
            self._record()
            parts, _ = self._route()
            manifest = self._body()
            name = manifest["metadata"]["name"]
            if parts[:2] == ["api", "v1"]:
                ns, kind = parts[3], parts[4]
                if kind == "pods":
                    manifest["metadata"].setdefault("namespace", ns)
                    manifest.setdefault("status", {"phase": "Pending"})
                    with state.lock:
                        state.pods[(ns, name)] = manifest
                    state.notify("ADDED", manifest)
                else:
                    with state.lock:
                        state.services[(ns, name)] = manifest
                return self._json(201, manifest)
            if parts[0] == "apis":
                ns, plural = parts[4], parts[5]
                with state.lock:
                    state.customs[(ns, plural, name)] = manifest
                return self._json(201, manifest)
            return self._json(404, {})

        def do_DELETE(self):  # noqa: N802
            self._record()
            parts, _ = self._route()
            if parts[:2] == ["api", "v1"]:
                ns, kind, name = parts[3], parts[4], parts[5]
                store = state.pods if kind == "pods" else state.services
                with state.lock:
                    obj = store.pop((ns, name), None)
                if obj is None:
                    return self._json(404, {"reason": "NotFound"})
                if kind == "pods":
                    state.notify("DELETED", obj)
                return self._json(200, {})
            ns, plural, name = parts[4], parts[5], parts[6]
            with state.lock:
                gone = state.customs.pop((ns, plural, name), None)
            return self._json(200 if gone else 404, {})

        def do_PATCH(self):  # noqa: N802
            self._record()
            parts, _ = self._route()
            assert parts[-1] == "status"
            ns, plural, name = parts[4], parts[5], parts[6]
            patch = self._body()
            with state.lock:
                obj = state.customs.get((ns, plural, name))
                if obj is None:
                    return self._json(404, {"reason": "NotFound"})
                obj.setdefault("status", {}).update(
                    patch.get("status", {})
                )
            return self._json(200, obj)

    return Handler


@pytest.fixture
def api():
    state = _State()
    server = ThreadingHTTPServer(("127.0.0.1", 0), _handler(state))
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    client = KubernetesClient(
        f"http://127.0.0.1:{server.server_port}", token="stub-token",
    )
    yield state, client
    client.close()
    server.shutdown()
    server.server_close()


def _job(workers=2) -> ElasticJob:
    return ElasticJob(
        name="jobx",
        spec=ElasticJobSpec(replica_specs={
            "worker": ReplicaSpec(replicas=workers, image="img:1"),
        }),
    )


@pytest.mark.timeout(120)
class TestVerbs:
    def test_pod_crud_and_selector_listing(self, api):
        state, client = api
        client.create_pod("default", {
            "metadata": {"name": "p1", "labels": {"job": "a"}}})
        client.create_pod("default", {
            "metadata": {"name": "p2", "labels": {"job": "b"}}})
        assert [p["metadata"]["name"]
                for p in client.list_pods("default", "job=a")] == ["p1"]
        client.delete_pod("default", "p1")
        assert client.list_pods("default", "job=a") == []
        client.delete_pod("default", "p1")  # 404 tolerated
        assert client.get_pod("default", "nope") is None

    def test_bearer_token_sent(self, api):
        state, client = api
        client.list_pods("default", "")
        assert state.requests[-1][2] == "Bearer stub-token"

    def test_api_error_carries_status(self, api):
        _, client = api
        with pytest.raises(ApiError) as ei:
            client._request("GET", "/api/v1/namespaces/x/unknown")
        assert ei.value.status == 404

    def test_custom_resource_crud_and_status_patch(self, api):
        state, client = api
        mf = _job().to_manifest()
        client.create_custom("default", "elasticjobs", mf)
        got = client.get_custom("default", "elasticjobs", "jobx")
        assert got["spec"]["replicaSpecs"]["worker"]["replicas"] == 2
        client.patch_custom_status(
            "default", "elasticjobs", "jobx", {"phase": "Running"})
        got = client.get_custom("default", "elasticjobs", "jobx")
        assert got["status"]["phase"] == "Running"
        client.delete_custom("default", "elasticjobs", "jobx")
        assert client.get_custom("default", "elasticjobs", "jobx") is None


@pytest.mark.timeout(120)
class TestScalerOverRealTransport:
    def test_scale_up_and_down(self, api):
        state, client = api
        scaler = PodScaler(_job(), client, "master:5001")
        scaler.scale(ScalePlan(replica_resources={"worker": 3}))
        with state.lock:
            names = sorted(n for (_, n) in state.pods)
        assert names == ["jobx-worker-0", "jobx-worker-1", "jobx-worker-2"]
        pod = state.pods[("default", "jobx-worker-0")]
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env["DLROVER_TPU_MASTER_ADDR"] == "master:5001"
        scaler.scale(ScalePlan(replica_resources={"worker": 1}))
        with state.lock:
            assert len(state.pods) == 1


@pytest.mark.timeout(120)
class TestWatchStream:
    def test_events_flow_and_stop_does_not_wedge(self, api):
        state, client = api
        events: list[PodEvent] = []
        seen = threading.Event()

        def on_event(e: PodEvent):
            events.append(e)
            seen.set()

        watcher = PodWatcher(client, "default", "jobx", on_event,
                             interval_s=30.0)
        watcher.start()
        time.sleep(0.3)  # let the stream subscribe
        client.create_pod("default", {"metadata": {
            "name": "jobx-worker-0", "namespace": "default",
            "labels": {"job": "jobx", "group": "worker", "node-id": "0"},
        }})
        assert seen.wait(10), "watch event never arrived"
        assert events[0].kind == PodEvent.ADDED
        assert events[0].node_id == 0
        seen.clear()
        client.delete_pod("default", "jobx-worker-0")
        assert seen.wait(10), "delete event never arrived"
        assert events[-1].kind == PodEvent.DELETED
        t0 = time.monotonic()
        watcher.stop()
        assert time.monotonic() - t0 < 5, "stop wedged on the stream"


@pytest.mark.timeout(120)
class TestOperatorCrSync:
    def test_job_cr_drives_pods_and_status(self, api):
        state, client = api
        client.create_custom("default", "elasticjobs",
                             _job(workers=2).to_manifest())
        op = ElasticJobOperator(client, interval_s=600)
        sync = CrSync(client, op, "default")
        sync.sync_once()
        with state.lock:
            names = sorted(n for (_, n) in state.pods)
        assert names == ["jobx-master", "jobx-worker-0", "jobx-worker-1"]
        assert ("default", "jobx-master") in state.services
        got = client.get_custom("default", "elasticjobs", "jobx")
        assert got["status"]["phase"] == "Pending"

        # a ScalePlan CR resizes the workers exactly once
        client.create_custom(
            "default", "scaleplans",
            ScalePlan(job_name="jobx",
                      replica_resources={"worker": 3}).to_manifest())
        sync.sync_once()
        with state.lock:
            workers = [n for (_, n) in state.pods if "worker" in n]
        assert len(workers) == 3
        plan = client.get_custom("default", "scaleplans",
                                 "jobx-scaleplan")
        assert plan["status"]["phase"] == "Applied"

        # deleting the job CR tears everything down
        client.delete_custom("default", "elasticjobs", "jobx")
        sync.sync_once()
        with state.lock:
            assert not state.pods
        op.stop()


class TestKubeconfig:
    def test_token_and_namespace_resolution(self, tmp_path, api):
        state, client = api
        cfg = {
            "current-context": "dev",
            "contexts": [{"name": "dev", "context": {
                "cluster": "c1", "user": "u1", "namespace": "ns9"}}],
            "clusters": [{"name": "c1", "cluster": {
                "server": client.base_url}}],
            "users": [{"name": "u1", "user": {"token": "cfg-token"}}],
        }
        import yaml

        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump(cfg))
        kc = KubernetesClient.from_kubeconfig(str(path))
        assert kc.base_url == client.base_url
        assert kc.namespace == "ns9"
        kc.list_pods("ns9", "")
        assert state.requests[-1][2] == "Bearer cfg-token"
        kc.close()

    def test_base64_data_materialized_and_cleaned(self, tmp_path):
        ca_pem = b"-----BEGIN CERTIFICATE-----\nAA==\n-----END CERTIFICATE-----\n"
        cfg = {
            "current-context": "dev",
            "contexts": [{"name": "dev", "context": {
                "cluster": "c1", "user": "u1"}}],
            "clusters": [{"name": "c1", "cluster": {
                "server": "https://1.2.3.4:6443",
                "insecure-skip-tls-verify": True,
                "certificate-authority-data":
                    base64.b64encode(ca_pem).decode()}}],
            "users": [{"name": "u1", "user": {"token": "t"}}],
        }
        import os

        import yaml

        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump(cfg))
        kc = KubernetesClient.from_kubeconfig(str(path))
        assert kc._tmp_files
        assert all(os.path.exists(p) for p in kc._tmp_files)
        files = list(kc._tmp_files)
        kc.close()
        assert all(not os.path.exists(p) for p in files)

    def test_unknown_context_rejected(self, tmp_path):
        import yaml

        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump({
            "current-context": "missing", "contexts": [],
            "clusters": [], "users": [],
        }))
        with pytest.raises(ValueError, match="context"):
            KubernetesClient.from_kubeconfig(str(path))


@pytest.mark.timeout(120)
class TestScalePlanDurability:
    def test_resize_survives_subsequent_reconcile(self, api):
        """A CR-driven resize must stick: the periodic reconcile used to
        scale every group straight back to the original spec within one
        interval (review finding)."""
        state, client = api
        client.create_custom("default", "elasticjobs",
                             _job(workers=2).to_manifest())
        op = ElasticJobOperator(client, interval_s=600)
        sync = CrSync(client, op, "default")
        sync.sync_once()
        client.create_custom(
            "default", "scaleplans",
            ScalePlan(job_name="jobx",
                      replica_resources={"worker": 4}).to_manifest())
        sync.sync_once()
        op.reconcile("jobx")  # the periodic loop's pass
        with state.lock:
            workers = [n for (_, n) in state.pods if "worker" in n]
        assert len(workers) == 4, "reconcile reverted the CR resize"
        op.stop()

    def test_plan_before_job_stays_pending_then_applies(self, api):
        state, client = api
        client.create_custom(
            "default", "scaleplans",
            ScalePlan(job_name="jobx",
                      replica_resources={"worker": 3}).to_manifest())
        op = ElasticJobOperator(client, interval_s=600)
        sync = CrSync(client, op, "default")
        sync.sync_once()  # job CR not there yet
        plan = client.get_custom("default", "scaleplans",
                                 "jobx-scaleplan")
        assert plan.get("status", {}).get("phase") != "Applied"
        client.create_custom("default", "elasticjobs",
                             _job(workers=2).to_manifest())
        sync.sync_once()
        with state.lock:
            workers = [n for (_, n) in state.pods if "worker" in n]
        assert len(workers) == 3
        plan = client.get_custom("default", "scaleplans",
                                 "jobx-scaleplan")
        assert plan["status"]["phase"] == "Applied"
        op.stop()


class TestTokenRefresh:
    def test_rotated_token_file_is_reread(self, tmp_path, api):
        state, client = api
        tok = tmp_path / "token"
        tok.write_text("tok-1")
        kc = KubernetesClient(client.base_url, token_file=str(tok))
        kc.list_pods("default", "")
        assert state.requests[-1][2] == "Bearer tok-1"
        tok.write_text("tok-2")
        import os

        os.utime(tok, (time.time() + 5, time.time() + 5))
        kc.list_pods("default", "")
        assert state.requests[-1][2] == "Bearer tok-2"
        kc.close()


@pytest.mark.timeout(120)
class TestOrphanSweep:
    def test_pods_without_cr_are_cleaned_after_operator_restart(self, api):
        """A CR deleted while the operator was down leaves pods no diff
        can see (review finding): the sweep reaps them by label."""
        state, client = api
        client.create_custom("default", "elasticjobs",
                             _job(workers=1).to_manifest())
        op1 = ElasticJobOperator(client, interval_s=600)
        CrSync(client, op1, "default").sync_once()
        with state.lock:
            assert state.pods
        op1.stop()
        client.delete_custom("default", "elasticjobs", "jobx")

        # "restarted" operator: fresh sync state, no memory of jobx
        op2 = ElasticJobOperator(client, interval_s=600)
        CrSync(client, op2, "default").sync_once()
        with state.lock:
            assert not state.pods, "orphaned pods survived the sweep"
        assert ("default", "jobx-master") not in state.services
        op2.stop()
