"""Node relaunch end to end: hardware fault -> the master REPLACES the host.

Reference analog: _should_relaunch -> _relaunch_node -> PodScaler
(dist_job_manager.py:561,605). Locally: an in-process master wires
LocalProcessScaler as its relaunch hook; the trainer exits with the
hardware code (211), the agent persists the snapshot and exits with the
node-relaunch code, the master's hook respawns a fresh launcher for the
same node id, and the job completes from the restored checkpoint.
"""

from __future__ import annotations

import json
import os
import sys
import time

import pytest

from dlrover_tpu.cluster.crd import ScalePlan
from dlrover_tpu.cluster.scaler import LocalProcessScaler
from dlrover_tpu.master.job_master import JobMaster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")


@pytest.mark.timeout(300)
def test_hardware_fault_relaunches_node_and_completes(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("DLROVER_TPU_PLATFORM", "cpu")
    monkeypatch.setenv("DLROVER_TPU_DEVICE_COUNT", "1")
    monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
    monkeypatch.setenv("PYTHONPATH", REPO)

    master = JobMaster(min_nodes=1, max_nodes=1, rdzv_timeout=5.0)
    result_file = str(tmp_path / "result.json")
    scaler = LocalProcessScaler(
        master_addr="",  # filled after prepare()
        entrypoint=[
            "--monitor-interval", "0.3", "--max-restarts", "2",
            EXAMPLE, "--",
            "--model", "tiny", "--seq", "128", "--global-batch", "8",
            "--max-steps", "20",
            "--ckpt-dir", str(tmp_path / "ckpt"),
            "--result-file", result_file,
            "--log-interval", "5",
            "--crash-at-step", "6", "--crash-exit", "211",
            "--crash-once-file", str(tmp_path / "crashed.marker"),
        ],
    )
    master.node_manager._relaunch_hook = scaler.relaunch_node
    master.prepare()
    scaler._master_addr = master.addr
    try:
        scaler.scale(ScalePlan(replica_resources={"worker": 1}))
        ok = master.run(poll_interval_s=0.2, all_exited_grace_s=5.0)
        assert ok, "job did not finish successfully"
        result = json.load(open(result_file))
        assert result["final_step"] == 20
        # the replacement incarnation restored the breakpoint snapshot
        assert result["resumed_from"] >= 4
        assert os.path.exists(tmp_path / "crashed.marker")
        # exactly one relaunch was recorded on the node
        nodes = {n.node_id: n for n in master.node_manager.all_nodes()}
        assert nodes[0].relaunch_count == 1
    finally:
        scaler.stop_all()
        master.stop()
