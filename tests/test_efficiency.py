"""Efficiency observatory (ISSUE 7): live MFU, step-phase attribution,
on-demand profiler capture, exposition conformance.

Acceptance surface, hermetic on the CPU backend:

- a warm AOT compile-cache load returns the compiled program's FLOPs
  from the envelope WITHOUT re-invoking the compile function;
- the step-phase histograms observed by a real ``ElasticTrainer`` loop
  account for (approximately) the whole step wall time, and the
  journal carries ``metrics_sample``/``step_phase`` points;
- the straggler detector attributes a planted slow node's verdict to
  its dominant phase (journal evidence + ``straggler_phase`` gauge
  label);
- a profile request round-trips: request file -> K-step
  ``jax.profiler`` capture -> debug bundle containing a non-empty
  xplane trace; the master's ``ProfileRequest`` RPC queues the
  heartbeat action that arms it;
- the master's one-scrape exposition parses under a strict Prometheus
  text-format conformance parser (family grouping, meta-once,
  histogram bucket discipline);
- ``report --format json`` emits one document with the steady-state
  efficiency rows; the timeline renders journaled samples as counter
  tracks across a journal rotation.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.common import messages as m
from dlrover_tpu.common import serde
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.parallel import compile_cache as cc
from dlrover_tpu.telemetry import efficiency as eff
from dlrover_tpu.telemetry import journal as journal_mod
from dlrover_tpu.telemetry.anomaly import StragglerDetector
from dlrover_tpu.telemetry.exposition import render, render_grouped
from dlrover_tpu.telemetry.metrics import MetricsRegistry, registry
from dlrover_tpu.telemetry.report import build_report, load_events
from dlrover_tpu.telemetry.report import main as report_main
from dlrover_tpu.telemetry.timeline import build_trace


@pytest.fixture()
def journal_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(EnvKey.JOURNAL_DIR, str(tmp_path / "journal"))
    monkeypatch.delenv(EnvKey.JOURNAL_MAX_MB, raising=False)
    monkeypatch.setattr(journal_mod, "_cached", None)
    yield str(tmp_path / "journal")
    journal_mod._cached = None


@pytest.fixture()
def bundle_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(EnvKey.BUNDLE_DIR, str(tmp_path / "bundles"))
    yield str(tmp_path / "bundles")


# ------------------------------------------------------- FLOPs AOT cache


class TestFlopsCache:
    def test_warm_load_serves_cached_flops(self, tmp_path):
        """The envelope carries executable_stats; a warm hit feeds the
        MFU gauge without re-lowering (the compile_fn is NOT called)."""
        calls = []

        def compile_fn():
            calls.append(1)
            return jax.jit(lambda x: x @ x).lower(
                jax.ShapeDtypeStruct((32, 32), jnp.float32)
            ).compile()

        d = str(tmp_path / "aot")
        cold = cc.load_or_compile(
            "t1/kf", {"a": 1}, compile_fn,
            cache=cc.CompileCacheClient(local_dir=d),
        )
        assert not cold.cache_hit
        assert cold.flops > 0  # 2*32^3 up to backend accounting
        assert len(calls) == 1

        warm = cc.load_or_compile(
            "t1/kf", {"a": 1}, compile_fn,
            cache=cc.CompileCacheClient(local_dir=d),
        )
        assert warm.cache_hit
        assert len(calls) == 1  # no recompile, no re-lower
        assert warm.flops == cold.flops
        # and the loaded executable still runs
        y = warm.fn(jnp.ones((32, 32)))
        assert float(y[0, 0]) == 32.0

    def test_blob_stats_damage_reads_empty(self):
        assert cc.blob_stats(b"garbage") == {}
        compiled = jax.jit(lambda x: x + 1).lower(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        ).compile()
        blob = cc.serialize_executable_blob(compiled, {"k": 1},
                                            stats={"flops": 12.0})
        assert cc.blob_stats(blob) == {"flops": 12.0}
        # flip a payload byte: CRC must turn stats into a miss too
        corrupt = bytearray(blob)
        corrupt[-1] ^= 0xFF
        assert cc.blob_stats(bytes(corrupt)) == {}


# ------------------------------------------------------------ monitor math


class TestEfficiencyMonitor:
    def test_mfu_and_gauge_readback(self):
        mon = eff.EfficiencyMonitor(
            model="m-test", strategy="s-test", flops_per_step=1e9,
            peak_flops=1e12, num_devices=2, journal_every=0,
        )
        for i in range(1, 5):
            mon.end_step(i, 0.01)
        # 1e9 / 0.01 / (1e12 * 2) = 0.05
        assert mon.mfu() == pytest.approx(0.05, rel=1e-6)
        assert eff.live_mfu("m-test", "s-test") == pytest.approx(
            0.05, abs=1e-4
        )

    def test_host_blocked_fraction(self):
        mon = eff.EfficiencyMonitor(model="m-hb", strategy="s",
                                    journal_every=0)
        # host-bound step: data_wait dwarfs block
        mon.observe_phase("data_wait", 0.5)
        mon.observe_phase("block", 0.01)
        mon.end_step(1, 0.51)
        # device-bound step
        mon.observe_phase("data_wait", 0.001)
        mon.observe_phase("block", 0.5)
        mon.end_step(2, 0.501)
        assert mon.host_blocked_frac() == pytest.approx(0.5)

    def test_no_peak_no_gauge(self):
        mon = eff.EfficiencyMonitor(model="m-np", strategy="s",
                                    flops_per_step=1e9, peak_flops=None,
                                    journal_every=0)
        mon.end_step(1, 0.01)
        assert mon.mfu() is None
        assert eff.live_mfu("m-np", "s") is None


# ---------------------------------------------- trainer phase integration


@pytest.mark.timeout(180)
def test_phase_histograms_account_for_step_time(journal_dir):
    """Run a real (tiny) compiled train loop: the five phase histograms
    must account for ~the whole step wall, and the journal must carry
    the metrics_sample/step_phase points the report and timeline
    consume."""
    import optax

    from dlrover_tpu.models import transformer as T
    from dlrover_tpu.parallel import strategy as S
    from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer
    from dlrover_tpu.trainer.train_step import compile_train

    cfg = T.CONFIGS["tiny"]
    strat = S.dp()
    mesh = strat.build_mesh(jax.devices()[:1])
    compiled = compile_train(
        strategy=strat, mesh=mesh,
        loss_fn=lambda p, b: T.loss_fn(p, b, cfg),
        init_params_fn=lambda rng: T.init_params(cfg, rng),
        logical_params=T.logical_axes(cfg),
        optimizer=optax.adamw(1e-3),
    )

    def snap():
        out = {}
        for metric in registry().snapshot():
            if metric["name"] in ("dlrover_tpu_step_phase_seconds",
                                  "dlrover_tpu_train_step_seconds"):
                for s in metric["samples"]:
                    key = (metric["name"],
                           s["labels"].get("phase", ""))
                    out[key] = (s["sum"], s["count"])
        return out

    before = snap()
    trainer = ElasticTrainer(compiled, global_batch_size=2,
                             micro_batch_size=2, model_name="tiny")
    trainer.efficiency._journal_every = 2

    def batches():
        rng = np.random.default_rng(0)
        for _ in range(6):
            yield {"tokens": rng.integers(
                0, cfg.vocab_size, (1, 2, 33), dtype=np.int32)}

    trainer.run_batches(compiled.init(jax.random.PRNGKey(0)), batches())
    after = snap()

    def delta(name, phase=""):
        b = before.get((name, phase), (0.0, 0))
        a = after.get((name, phase), (0.0, 0))
        return a[0] - b[0], a[1] - b[1]

    step_sum, step_count = delta("dlrover_tpu_train_step_seconds")
    assert step_count == 6
    phase_sum = 0.0
    for phase in ("h2d", "dispatch", "block"):
        ps, pc = delta("dlrover_tpu_step_phase_seconds", phase)
        assert pc == 6, phase
        phase_sum += ps
    dw_sum, dw_count = delta("dlrover_tpu_step_phase_seconds",
                             "data_wait")
    assert dw_count == 6
    # h2d+dispatch+block tile the train_step wall (data_wait/ckpt sit
    # outside it); generous bounds — this is a wall-clock assertion
    assert phase_sum <= step_sum * 1.10 + 0.05
    assert phase_sum >= step_sum * 0.5

    events = load_events(os.path.join(journal_dir, "events.jsonl"))
    names = {e["name"] for e in events}
    assert "metrics_sample" in names and "step_phase" in names
    samples = [e for e in events if e["name"] == "metrics_sample"]
    assert all(set(s["phases"]) == set(eff.PHASES) for s in samples)
    # CPU backend has no known peak: mfu must be null, never wrong
    assert all(s["mfu"] is None for s in samples)


# ------------------------------------------------ straggler-phase verdict


def _trainer_snapshot(step_sum: float, step_count: int,
                      phase_s: dict[str, float] | None = None,
                      phase_count: int = 0) -> list[dict]:
    """A pushed registry snapshot: step histogram + phase histograms
    (cumulative, like a real trainer's)."""
    snap = [{
        "name": "dlrover_tpu_train_step_seconds",
        "type": "histogram", "help": "", "buckets": [1.0],
        "samples": [{"labels": {}, "buckets": [step_count, 0],
                     "sum": step_sum, "count": step_count}],
    }]
    if phase_s:
        snap.append({
            "name": "dlrover_tpu_step_phase_seconds",
            "type": "histogram", "help": "", "buckets": [1.0],
            "samples": [
                {"labels": {"phase": p},
                 "buckets": [phase_count, 0],
                 "sum": s, "count": phase_count}
                for p, s in phase_s.items()
            ],
        })
    return snap


class TestStragglerPhase:
    def test_verdict_carries_dominant_phase(self, journal_dir):
        det = StragglerDetector(min_points=2)
        cum: dict[int, list] = {}
        for rounds in range(4):
            for nid in range(4):
                step_s = 0.5 if nid == 2 else 0.1
                prev = cum.setdefault(nid, [0.0, 0, {}])
                prev[0] += step_s * 10
                prev[1] += 10
                # the slow node's time goes to data_wait; peers are
                # device-bound
                phases = {"data_wait": 0.4 if nid == 2 else 0.01,
                          "block": 0.05}
                for p, v in phases.items():
                    prev[2][p] = prev[2].get(p, 0.0) + v * 10
                det.observe_snapshot(nid, _trainer_snapshot(
                    prev[0], prev[1],
                    phase_s=prev[2], phase_count=prev[1],
                ))
        assert det.stragglers() == [2]
        events = load_events(os.path.join(journal_dir, "events.jsonl"))
        flagged = [e for e in events if e["name"] == "straggler_verdict"
                   and e["state"] == "flagged"]
        assert [(e["node"], e["phase"]) for e in flagged] == \
            [(2, "data_wait")]
        # the score gauge carries the phase label while flagged
        from dlrover_tpu.telemetry.anomaly import _score_gauge

        samples = {tuple(sorted(s["labels"].items())): s["value"]
                   for s in _score_gauge.samples()}
        key = (("node", "2"), ("straggler_phase", "data_wait"))
        assert samples[key] == pytest.approx(5.0, rel=0.01)

    def test_clear_resets_phase_label(self, journal_dir):
        det = StragglerDetector(min_points=2, window=8)
        cum: dict[int, list] = {}

        def feed(rounds, slow_id):
            for _ in range(rounds):
                for nid in range(4):
                    step_s = 0.5 if nid == slow_id else 0.1
                    prev = cum.setdefault(nid, [0.0, 0, {}])
                    prev[0] += step_s * 10
                    prev[1] += 10
                    prev[2]["ckpt"] = prev[2].get("ckpt", 0.0) + (
                        4.0 if nid == slow_id else 0.1)
                    det.observe_snapshot(nid, _trainer_snapshot(
                        prev[0], prev[1], phase_s=prev[2],
                        phase_count=prev[1],
                    ))

        feed(3, slow_id=1)
        assert det.stragglers() == [1]
        feed(12, slow_id=-1)  # recovery
        assert det.stragglers() == []
        events = load_events(os.path.join(journal_dir, "events.jsonl"))
        verdicts = [(e["state"], e.get("phase"))
                    for e in events if e["name"] == "straggler_verdict"]
        assert verdicts[0] == ("flagged", "ckpt")
        assert verdicts[-1][0] == "cleared"
        from dlrover_tpu.telemetry.anomaly import _score_gauge

        samples = {tuple(sorted(s["labels"].items())): s["value"]
                   for s in _score_gauge.samples()}
        # the stale flagged-phase series was zeroed on re-attribution
        assert samples.get((("node", "1"),
                            ("straggler_phase", "ckpt")), 0.0) == 0.0


# -------------------------------------------------------- profile capture


class TestProfileCapture:
    @pytest.mark.timeout(120)
    def test_request_to_bundle_roundtrip(self, journal_dir, bundle_dir):
        """request file -> K-step capture -> bundle with a non-empty
        xplane trace, journaled and counted."""
        reported = []
        mon = eff.EfficiencyMonitor(model="m-prof", strategy="s",
                                    node_id=7, journal_every=0,
                                    on_bundle=reported.append)
        assert eff.arm_profile_request(7, steps=2) is not None
        f = jax.jit(lambda x: x @ x)
        x = jnp.ones((64, 64))
        for i in range(1, 6):
            jax.block_until_ready(f(x))
            mon.end_step(i, 0.001)
        # request consumed, capture finished, no second capture
        assert not os.path.exists(eff.profile_request_path(7))
        bundles = glob.glob(os.path.join(bundle_dir, "bundle_*_profile_*"))
        assert len(bundles) == 1
        xplanes = glob.glob(os.path.join(bundles[0], "profile", "**",
                                         "*.xplane.pb"), recursive=True)
        assert xplanes and os.path.getsize(xplanes[0]) > 0
        manifest = json.load(open(os.path.join(bundles[0],
                                               "manifest.json")))
        assert manifest["attached"] == ["profile"]
        assert manifest["extra"]["steps"] == 2
        assert reported == bundles
        events = load_events(os.path.join(journal_dir, "events.jsonl"))
        caps = [e for e in events if e["name"] == "profile_capture"]
        assert len(caps) == 1 and caps[0]["steps"] == 2

    def test_profile_request_rpc_queues_heartbeat_action(self, tmp_path,
                                                         monkeypatch):
        """ProfileRequest -> NodeManager.send_action -> the node's next
        heartbeat delivers profile:<K> (the agent then arms the request
        file); unknown nodes are refused."""
        monkeypatch.delenv(EnvKey.METRICS_PORT, raising=False)
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(job_name="eff-test", port=0, min_nodes=1,
                           max_nodes=1)
        try:
            handle = master.servicer.handle
            assert handle(m.NodeHeartbeat(node_id=0)).action == ""
            resp = handle(serde.decode(serde.encode(
                m.ProfileRequest(node_id=0, steps=3))))
            assert isinstance(resp, m.ProfileResponse) and resp.armed
            assert handle(m.NodeHeartbeat(node_id=0)).action == \
                "profile:3"
            # delivered once
            assert handle(m.NodeHeartbeat(node_id=0)).action == ""
            refused = handle(m.ProfileRequest(node_id=9, steps=3))
            assert not refused.armed and refused.reason
        finally:
            master._server._server.server_close()

    def test_capture_error_is_contained(self, bundle_dir, monkeypatch):
        """A failing profiler must not take down the step loop."""
        mon = eff.EfficiencyMonitor(model="m-err", strategy="s",
                                    node_id=8, journal_every=0)
        eff.arm_profile_request(8, steps=1)
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        mon.end_step(1, 0.001)  # must not raise
        mon.end_step(2, 0.001)
        assert mon._capture_dir is None


# ------------------------------------------------- exposition conformance


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> dict[str, dict]:
    """Strict Prometheus text-format conformance parse.

    Enforces: HELP/TYPE precede a family's samples, TYPE exactly once,
    all of a family's samples contiguous (no interleaving), histogram
    series limited to _bucket/_sum/_count with cumulative monotonic
    buckets ending at le="+Inf" == _count. Returns family -> info.
    """
    families: dict[str, dict] = {}
    current: str | None = None

    def family_of(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[:-len(suffix)] \
                if sample_name.endswith(suffix) else None
            if base and families.get(base, {}).get("type") == "histogram":
                return base
        return sample_name

    for lineno, line in enumerate(text.splitlines(), 1):
        assert line.strip() == line and line, f"line {lineno}: whitespace"
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind, rest = line[2:6], line[7:]
            name, _, value = rest.partition(" ")
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": [],
                       "closed": False})
            assert not fam["samples"], \
                f"line {lineno}: meta after samples for {name}"
            if kind == "HELP":
                assert fam["help"] is None, f"duplicate HELP {name}"
                assert value, f"line {lineno}: empty HELP for {name}"
                fam["help"] = value
            else:
                assert fam["type"] is None, f"duplicate TYPE {name}"
                assert value in ("counter", "gauge", "histogram",
                                 "untyped"), value
                fam["type"] = value
            continue
        match = _SAMPLE_RE.match(line)
        assert match, f"line {lineno}: unparseable sample {line!r}"
        name, labels_text, value = match.groups()
        float("+inf" if value == "+Inf" else value)  # numeric
        labels = dict(_LABEL_RE.findall(labels_text or ""))
        fam_name = family_of(name)
        fam = families.get(fam_name)
        assert fam is not None and fam["type"] is not None, \
            f"line {lineno}: sample {name} before # TYPE"
        if current != fam_name:
            assert not fam["closed"], \
                f"line {lineno}: family {fam_name} interleaved"
            if current is not None:
                families[current]["closed"] = True
            current = fam_name
        if fam["type"] == "histogram":
            assert name.endswith(("_bucket", "_sum", "_count")), name
            if name.endswith("_bucket"):
                assert "le" in labels, f"line {lineno}: bucket sans le"
        else:
            assert name == fam_name
        fam["samples"].append((name, labels, value))

    for fam_name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        series: dict[tuple, list] = {}
        counts: dict[tuple, float] = {}
        for name, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            if name.endswith("_bucket"):
                series.setdefault(key, []).append(
                    (math.inf if labels["le"] == "+Inf"
                     else float(labels["le"]), float(value))
                )
            elif name.endswith("_count"):
                counts[key] = float(value)
        for key, buckets in series.items():
            les = [le for le, _ in buckets]
            values = [v for _, v in buckets]
            assert les == sorted(les), f"{fam_name}: le out of order"
            assert les[-1] == math.inf, f"{fam_name}: no +Inf bucket"
            assert values == sorted(values), \
                f"{fam_name}: non-cumulative buckets"
            assert values[-1] == counts.get(key), \
                f"{fam_name}: +Inf bucket != _count"
    return families


class TestExpositionConformance:
    def test_full_default_registry_parses(self):
        # the process registry holds every family the imported modules
        # registered (trainer, master, telemetry, ...); all must render
        # promtool-parseable with non-empty help
        text = render()
        families = parse_exposition(text)
        assert "dlrover_tpu_mfu" in families
        assert "dlrover_tpu_step_phase_seconds" in families
        for name, fam in families.items():
            assert fam["help"], f"{name} rendered without HELP"

    def test_grouped_master_scrape_parses(self):
        """The master's one-scrape shape: its own registry + per-node
        snapshots sharing families — grouped, meta emitted once."""
        master = MetricsRegistry()
        master.counter("dlrover_tpu_conf_total", "requests",
                       label_names=("kind",)).labels("a").inc(2)
        node = MetricsRegistry()
        node.counter("dlrover_tpu_conf_total", "requests",
                     label_names=("kind",)).labels("a").inc(5)
        node.histogram("dlrover_tpu_conf_seconds", "latency",
                       buckets=(0.5, 1.0)).observe(0.7)
        text = render_grouped([
            (master.snapshot(), {"role": "master"}),
            (node.snapshot(), {"node": "0", "role": "trainer"}),
            (node.snapshot(), {"node": "1", "role": "trainer"}),
        ])
        families = parse_exposition(text)
        assert len(families["dlrover_tpu_conf_total"]["samples"]) == 3
        # node-only family got its meta from the node snapshot
        assert families["dlrover_tpu_conf_seconds"]["help"] == "latency"
        assert text.count("# TYPE dlrover_tpu_conf_total") == 1

    def test_live_master_metrics_text_parses(self, tmp_path,
                                             monkeypatch):
        monkeypatch.delenv(EnvKey.METRICS_PORT, raising=False)
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(job_name="conf-test", port=0, min_nodes=1,
                           max_nodes=1)
        try:
            reg = MetricsRegistry()
            reg.counter("dlrover_tpu_conf_pushed_total", "pushed").inc(4)
            master.servicer.handle(m.MetricsSnapshotRequest(
                node_id=3, role="trainer", samples=reg.snapshot(),
            ))
            families = parse_exposition(master.metrics_text())
            assert "dlrover_tpu_conf_pushed_total" in families
            assert "dlrover_tpu_master_rpc_seconds" in families
        finally:
            master._server._server.server_close()


# --------------------------------------------- report + timeline surfaces


def _write_journal_line(f, **ev):
    f.write(json.dumps(ev) + "\n")


def _sample_event(t, step, mfu, proc="node0", **extra):
    return dict(t=t, trace="tr", span=f"ms{step}", name="metrics_sample",
                ev="p", proc=proc, pid=1, step=step, mfu=mfu,
                step_s=0.1, host_blocked_frac=0.25,
                phases={"data_wait": 0.01, "h2d": 0.002,
                        "dispatch": 0.003, "block": 0.08, "ckpt": 0.0},
                **extra)


class TestReportEfficiency:
    def _journal(self, path):
        t0 = 1000.0
        with open(path, "w") as f:
            for i, step in enumerate((5, 10, 15)):
                _write_journal_line(f, **_sample_event(
                    t0 + i, step, 0.5 + 0.1 * i))
                for phase, dur in (("data_wait", 0.01), ("block", 0.08)):
                    _write_journal_line(
                        f, t=t0 + i, trace="tr", span=f"sp{step}{phase}",
                        name="step_phase", ev="p", proc="node0", pid=1,
                        dur=dur, phase=phase, step=step)
            # incarnation 1 after a restart
            _write_journal_line(
                f, t=t0 + 10, trace="tr", span="nr1", name="node_restart",
                ev="p", proc="node0", pid=1, incarnation=1, dur=1.0)
            _write_journal_line(f, **_sample_event(t0 + 20, 20, 0.3))

    def test_efficiency_rows_per_incarnation(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        self._journal(path)
        report = build_report(path)
        assert len(report.efficiency) == 2
        inc0, inc1 = report.efficiency
        assert inc0["incarnation"] == 0 and inc0["samples"] == 3
        assert inc0["mfu_mean"] == pytest.approx(0.6, abs=1e-6)
        assert inc0["mfu_min"] == 0.5 and inc0["mfu_max"] == 0.7
        assert inc0["host_blocked_pct"] == 25.0
        assert inc0["phase_s"]["block"] == pytest.approx(0.08)
        assert inc0["phase_pct"]["block"] == pytest.approx(80.0)
        assert inc1["incarnation"] == 1
        assert inc1["mfu_mean"] == pytest.approx(0.3)

    def test_format_json_cli(self, tmp_path, capsys):
        path = str(tmp_path / "events.jsonl")
        self._journal(path)
        assert report_main(["--journal", path, "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert set(doc) >= {"total_s", "lost_s", "categories",
                            "incarnations", "efficiency"}
        assert doc["efficiency"][0]["mfu_mean"] == pytest.approx(0.6)
        # text mode renders the steady-state table
        assert report_main(["--journal", path]) == 0
        out = capsys.readouterr().out
        assert "steady-state efficiency" in out

    def test_timeline_counter_tracks_across_rotation(self, tmp_path):
        """metrics_sample points split across a journal rotation render
        as ph='C' counter events (mfu + stacked phase lanes)."""
        live = str(tmp_path / "events.jsonl")
        with open(live + ".1", "w") as f:
            _write_journal_line(f, **_sample_event(1000.0, 5, 0.5))
            _write_journal_line(
                f, t=1000.5, trace="tr", span="ts1", name="train_step",
                ev="p", proc="node0", pid=1, dur=0.1, step=5)
        with open(live, "w") as f:
            _write_journal_line(f, **_sample_event(1001.0, 10, 0.6))
        trace = build_trace([live])
        counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        mfu = [e for e in counters if e["name"] == "mfu"]
        assert [e["args"]["mfu"] for e in mfu] == [0.5, 0.6]
        phases = [e for e in counters
                  if e["name"] == "step_phase_seconds"]
        assert len(phases) == 2
        assert phases[0]["args"]["block"] == pytest.approx(0.08)
        # metrics_sample is a counter source, not a span lane
        assert not any(e.get("name") == "metrics_sample"
                       for e in trace["traceEvents"] if e["ph"] != "C")
        assert trace["otherData"]["n_counter_samples"] == 2


# -------------------------------------------------- live standalone e2e


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_profile_request_against_running_standalone_job(tmp_path):
    """The acceptance path end to end: a ProfileRequest RPC against a
    live ``dlrover_tpu.run --standalone`` job produces a debug bundle
    containing a non-empty xplane trace, without restarting the job."""
    import subprocess
    import sys
    import threading
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    example = os.path.join(repo, "examples", "train_transformer.py")
    bundles = str(tmp_path / "bundles")
    env = dict(os.environ)
    env.update({
        "DLROVER_TPU_PLATFORM": "cpu",
        "DLROVER_TPU_DEVICE_COUNT": "1",
        "DLROVER_TPU_IPC_DIR": str(tmp_path / "ipc"),
        "DLROVER_TPU_JOURNAL_DIR": str(tmp_path / "journal"),
        "DLROVER_TPU_BUNDLE_DIR": bundles,
        "DLROVER_TPU_STANDBY": "0",
        "PYTHONPATH": repo,
    })
    cmd = [
        sys.executable, "-m", "dlrover_tpu.run", "--standalone",
        "--monitor-interval", "0.3", "--heartbeat-interval", "0.5",
        example, "--",
        "--model", "tiny", "--global-batch", "8", "--seq", "128",
        "--max-steps", "2000", "--step-delay", "0.05",
        "--ckpt-dir", str(tmp_path / "ckpt"),
    ]
    proc = subprocess.Popen(cmd, env=env, cwd=repo, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    addr_holder: list[str] = []

    def _scan(stream):
        for line in stream:
            match = re.search(r"standalone master at (\S+)", line)
            if match and not addr_holder:
                addr_holder.append(match.group(1))

    threads = [threading.Thread(target=_scan, args=(proc.stderr,),
                                daemon=True),
               threading.Thread(target=_scan, args=(proc.stdout,),
                                daemon=True)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 120
        while not addr_holder and time.monotonic() < deadline:
            assert proc.poll() is None, "job exited before serving"
            time.sleep(0.2)
        assert addr_holder, "master address never logged"

        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(addr_holder[0], node_id=0)
        try:
            armed = False
            while time.monotonic() < deadline and not armed:
                # the node registers at its first heartbeat; retry
                armed = client.request_profile(0, steps=3).armed
                if not armed:
                    time.sleep(0.5)
            assert armed, "node 0 never became profilable"

            xplanes: list[str] = []
            while time.monotonic() < deadline and not xplanes:
                assert proc.poll() is None, "job exited mid-capture"
                xplanes = glob.glob(os.path.join(
                    bundles, "bundle_*_profile_*", "profile", "**",
                    "*.xplane.pb"), recursive=True)
                time.sleep(0.5)
            assert xplanes, "no xplane trace landed in a bundle"
            assert os.path.getsize(xplanes[0]) > 0
            listed = client.list_debug_bundles()
            assert any(b.reason == "profile" for b in listed)
        finally:
            client.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
        subprocess.run(["pkill", "-9", "-f", example],
                       capture_output=True)
        subprocess.run(
            ["pkill", "-9", "-f", "dlrover_tpu.master.job_master"],
            capture_output=True,
        )


# ------------------------------------------------------------ name lint


def test_metric_and_label_contract_lint():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_metric_names",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "native",
            "check_metric_names.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    names, problems = mod.scan()
    assert not problems, problems
    assert any(n.startswith("dlrover_tpu_mfu") for n in names)
    assert "dlrover_tpu_step_phase_seconds" in names
    assert mod.check_contract_labels() == []
    # a missing DESIGN.md entry for a contract family must be caught
    with tempfile.NamedTemporaryFile("w", suffix=".md") as f:
        f.write("nothing documented here\n")
        f.flush()
        missing = mod.check_documented(
            {"dlrover_tpu_mfu": ["x.py:1"]}, design_path=f.name)
        assert missing
