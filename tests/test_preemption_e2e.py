"""TPU preemption-notice path, end to end.

The scenario SURVEY §7 calls the hard part ("restart-in-place vs
preemption"): the platform announces the kill, the agent protects the
snapshot BEFORE dying (buddy replication over DCN + master notice), the
VM dies taking its shared memory with it, and the replacement host
restores from the buddy with ZERO storage reads — storage persistence is
disabled outright in this test, so a successful resume proves the buddy
path. Reference analog: the breakpoint-save semantics of
dlrover ckpt_saver.py:631 extended to advance notice.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import pytest

from dlrover_tpu.cluster.crd import ScalePlan
from dlrover_tpu.cluster.scaler import LocalProcessScaler
from dlrover_tpu.master.job_master import JobMaster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")


def _steps_logged(log: str) -> int:
    try:
        with open(log) as f:
            return sum(1 for line in f if '"step"' in line)
    except OSError:
        return 0


# slow tier: a REAL 2-node job — jax's CPU backend in this container
# cannot run multiprocess collectives ("Multiprocess computations aren't
# implemented on the CPU backend"), so every trainer spawn dies at state
# init and the test burns ~120s failing. Same disposition as
# tests/test_multinode_e2e.py and test_buddy's node-kill e2e; a plain
# `pytest tests/` (or any multi-host-capable backend) still runs it.
@pytest.mark.slow
@pytest.mark.timeout(300)
def test_preemption_notice_buddy_restore_no_storage(tmp_path, monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_PLATFORM", "cpu")
    monkeypatch.setenv("DLROVER_TPU_DEVICE_COUNT", "2")
    # children inherit the env: 2 virtual devices per node, dp=4
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=2")
    monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
    monkeypatch.setenv("PYTHONPATH", REPO)
    monkeypatch.setenv("DLROVER_TPU_BUDDY_INTERVAL", "0.3")
    notice_dir = tmp_path / "notices"
    notice_dir.mkdir()
    monkeypatch.setenv(
        "DLROVER_TPU_PREEMPTION_FILE",
        str(notice_dir / "preempt-{node_id}"),
    )

    master = JobMaster(min_nodes=2, max_nodes=2, rdzv_timeout=20.0)
    master.node_manager._preempt_dead_window_s = 3.0
    # agents heartbeat every 0.5s below; the derived-window floor
    # (2*interval+slack) must track that, not the 15s prod default
    master.node_manager._heartbeat_interval_s = 0.5
    log = str(tmp_path / "goodput.jsonl")
    result_file = str(tmp_path / "result.json")
    scaler = LocalProcessScaler(
        master_addr="",
        entrypoint=[
            "--monitor-interval", "0.3", "--max-restarts", "2",
            "--heartbeat-interval", "0.5",
            "--no-save-on-failure",          # storage stays EMPTY
            EXAMPLE, "--",
            "--model", "tiny", "--seq", "128", "--global-batch", "8",
            "--max-steps", "40",
            "--ckpt-dir", str(tmp_path / "ckpt"),
            "--ckpt-interval", "1000000",    # no periodic storage saves
            "--mem-ckpt-interval", "2",
            "--goodput-log", log,
            "--result-file", result_file,
            "--log-interval", "10",
            "--step-delay", "0.3",
        ],
    )
    master.node_manager._relaunch_hook = scaler.relaunch_node
    master.prepare()
    scaler._master_addr = master.addr
    try:
        scaler.scale(ScalePlan(replica_resources={"worker": 2}))
        # let training make progress and snapshots replicate
        deadline = time.time() + 120
        while _steps_logged(log) < 16 and time.time() < deadline:
            time.sleep(0.5)
        assert _steps_logged(log) >= 16, "training never progressed"

        # 1. the notice lands on node 0
        (notice_dir / "preempt-0").write_text("TERMINATE")
        # give the watcher (1s poll) time to replicate + report
        deadline = time.time() + 30
        while time.time() < deadline:
            nodes = {n.node_id: n for n in master.node_manager.all_nodes()}
            if nodes[0].preempting_since:
                break
            time.sleep(0.3)
        assert nodes[0].preempting_since, "master never got the notice"

        # 2. the VM dies: SIGKILL the whole launcher tree. The snapshot
        # meta dict and writer lock are unix-socket servers inside the
        # agent process, so the kill destroys the host's snapshot state
        # exactly like a preempted VM losing its memory — the relaunched
        # agent sees header()=None and must go to the buddy.
        proc = scaler._procs[0]
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        scaler._procs.pop(0, None)
        # the kill consumed the notice (a fresh replacement VM would not
        # see the old event)
        (notice_dir / "preempt-0").unlink()

        # 3. the master's short dead-window relaunches node 0; the fresh
        # agent restores from node 1's buddy server and the job finishes
        ok = master.run(poll_interval_s=0.2, all_exited_grace_s=5.0)
        assert ok, "job did not finish after preemption"
        result = json.load(open(result_file))
        assert result["final_step"] == 40
        # the replacement incarnation resumed from a replicated snapshot
        assert result["resumed_from"] >= 2
        # zero storage READS: nothing was persisted before completion
        # (the only step dir allowed is the final end-of-training save),
        # so the recovery could not have come from storage
        ckpt_dir = tmp_path / "ckpt"
        persisted = (
            [p for p in os.listdir(ckpt_dir) if p.startswith("step-")]
            if ckpt_dir.exists() else []
        )
        assert persisted in ([], ["step-40"]), (
            f"storage was written during recovery: {persisted}"
        )
        nodes = {n.node_id: n for n in master.node_manager.all_nodes()}
        assert nodes[0].relaunch_count == 1
        # re-registration cleared the preemption arm
        assert nodes[0].preempting_since == 0.0
    finally:
        scaler.stop_all()
        master.stop()


class TestWatcherUnit:
    def test_fires_once_on_file(self, tmp_path):
        from dlrover_tpu.agent.preemption import PreemptionWatcher

        fired = []
        f = tmp_path / "notice-3"
        w = PreemptionWatcher(
            lambda: fired.append(1), node_id=3,
            poll_interval_s=0.05,
            notice_file=str(tmp_path / "notice-{node_id}"),
        )
        assert w.enabled
        w.start()
        time.sleep(0.2)
        assert fired == []
        f.write_text("TERMINATE")
        deadline = time.time() + 5
        while not fired and time.time() < deadline:
            time.sleep(0.05)
        assert fired == [1]
        time.sleep(0.2)
        assert fired == [1]  # one-shot
        w.stop()

    def test_disabled_without_source(self):
        from dlrover_tpu.agent.preemption import PreemptionWatcher

        w = PreemptionWatcher(lambda: None, notice_file="",
                              notice_url="")
        assert not w.enabled

    def test_master_short_window_and_clear_on_reregister(self):
        from dlrover_tpu.master.node_manager import NodeManager

        dead = []
        nm = NodeManager(dead_window_s=1000.0, on_node_dead=dead.append,
                         preempt_dead_window_s=0.2,
                         heartbeat_interval_s=0.05)
        nm.ensure_node(0)
        nm.report_heartbeat(0)
        nm.report_preemption(0, deadline_s=30.0)
        time.sleep(0.4)
        nm._check_dead_nodes()
        assert dead == [0]
        # the replacement registers: armed flag cleared, normal window
        node = nm.ensure_node(0)
        assert node.preempting_since == 0.0

    def test_armed_window_spans_heartbeat_cadence(self):
        """Advisor r04: with the armed window == the heartbeat interval
        a still-alive node racing its own cadence (heartbeat delayed by
        the pre-kill prepare) was falsely declared dead mid-prepare.
        The effective window must span >=2 cadences + slack."""
        from dlrover_tpu.master.node_manager import NodeManager

        dead = []
        nm = NodeManager(dead_window_s=1000.0, on_node_dead=dead.append,
                         preempt_dead_window_s=0.2,
                         heartbeat_interval_s=0.2)
        assert nm._effective_preempt_window() >= 0.4
        nm.ensure_node(0)
        nm.report_heartbeat(0)
        nm.report_preemption(0, deadline_s=30.0)
        # a heartbeat lands a full cadence late (delayed by the
        # prepare) — inside the derived window, so the node lives
        time.sleep(0.3)
        nm._check_dead_nodes()
        assert dead == []
        # prod geometry: 15s cadence forces a >=30s armed window even
        # when the configured preempt window is shorter
        nm2 = NodeManager(preempt_dead_window_s=15.0,
                          heartbeat_interval_s=15.0)
        assert nm2._effective_preempt_window() >= 33.0

    def test_heartbeat_past_ttl_disarms_silence_does_not(self):
        """Survival evidence is a HEARTBEAT past the advertised kill
        window (live migration); mere elapsed time must NOT disarm —
        a node killed late in its window is silent exactly then
        (review findings, rounds 4a+4b)."""
        from dlrover_tpu.master.node_manager import NodeManager

        dead = []
        nm = NodeManager(dead_window_s=1000.0, on_node_dead=dead.append,
                         preempt_dead_window_s=0.2,
                         heartbeat_interval_s=0.05)
        nm.ensure_node(0)
        nm.report_heartbeat(0)
        nm.report_preemption(0, deadline_s=30.0)
        node = nm.all_nodes()[0]
        # silence past the TTL: the short window still applies -> dead
        node.preempting_since = time.time() - 10_000
        node.heartbeat_time = time.time() - 10.0
        nm._check_dead_nodes()
        assert dead == [0]
        # ...whereas a heartbeat past the TTL disarms
        nm.ensure_node(1)
        nm.report_heartbeat(1)
        nm.report_preemption(1, deadline_s=30.0)
        node1 = [n for n in nm.all_nodes() if n.node_id == 1][0]
        node1.preempting_since = time.time() - 10_000
        nm.report_heartbeat(1)
        assert node1.preempting_since == 0.0
        nm._check_dead_nodes()
        assert dead == [0]  # node 1 stays alive on the normal window

    def test_url_source_fires_on_maintenance_event(self):
        """The metadata-URL notice source (GCE maintenance-event
        convention): NONE means keep running, anything else fires."""
        import http.server
        import threading as th

        from dlrover_tpu.agent.preemption import PreemptionWatcher

        body = {"value": b"NONE"}

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                assert self.headers.get("Metadata-Flavor") == "Google"
                self.send_response(200)
                self.send_header("Content-Length",
                                 str(len(body["value"])))
                self.end_headers()
                self.wfile.write(body["value"])

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        th.Thread(target=srv.serve_forever, daemon=True).start()
        fired = []
        w = PreemptionWatcher(
            lambda: fired.append(1), poll_interval_s=0.05,
            notice_file="",
            notice_url=f"http://127.0.0.1:{srv.server_address[1]}/",
        )
        try:
            assert w.enabled
            w.start()
            time.sleep(0.3)
            assert fired == []          # NONE: no notice
            body["value"] = b"TERMINATE_ON_HOST_MAINTENANCE"
            deadline = time.time() + 5
            while not fired and time.time() < deadline:
                time.sleep(0.05)
            assert fired == [1]
        finally:
            w.stop()
            srv.shutdown()
            srv.server_close()
