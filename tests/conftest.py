"""Test configuration: hermetic 8-device CPU mesh.

Mirrors the reference's gloo-spawn multi-device testing pattern
(SURVEY.md §4): JAX on CPU with ``--xla_force_host_platform_device_count=8``
gives multi-device semantics without TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ipc_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
    return tmp_path
