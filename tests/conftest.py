"""Test configuration: hermetic 8-device CPU mesh.

Mirrors the reference's gloo-spawn multi-device testing pattern
(SURVEY.md §4): JAX on CPU with ``--xla_force_host_platform_device_count=8``
gives multi-device semantics without TPU hardware.
"""

import os

# Per-xdist-worker resource scoping: /dev/shm segment names and the
# default IPC dir both derive from DLROVER_TPU_SHM_PREFIX (read at
# dlrover_tpu.common.constants import time — this assignment must come
# first), so two workers' fixed node-id arenas (ckpt_node3 etc.) can
# never collide. Serial runs are untouched.
_xdist_worker = os.environ.get("PYTEST_XDIST_WORKER")
if _xdist_worker:
    os.environ["DLROVER_TPU_SHM_PREFIX"] = f"dlrover_tpu_{_xdist_worker}"

# Force CPU even when the outer environment points at real hardware
# (JAX_PLATFORMS=axon/tpu): tests must be hermetic and multi-device. A
# sitecustomize may already have imported jax to register a TPU plugin, so
# updating the env alone is not enough — update the live config too (safe:
# backends initialize lazily on first device query).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import signal  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "timeout(seconds): abort the test with a TimeoutError if it runs "
        "longer than the given number of seconds (SIGALRM-based; main "
        "thread only, like the reference's pytest-timeout usage)",
    )


# Modules that spawn the elastic example as subprocesses AND clean up
# with broad `pkill -f <example>` patterns: under OPT-IN xdist
# (`-n 2 --dist loadgroup`; serial is the default — see pytest.ini)
# those pkills would kill a SIBLING worker's children, so they all pin
# to one worker via xdist_group. Measured r5: two workers on this
# one-core host save only ~10% wall clock (jax compiles are CPU-bound)
# and the sibling's compiles can starve these very e2e jobs.
_E2E_GROUP_FILES = {
    "test_buddy.py", "test_chaos.py", "test_e2e.py", "test_goodput.py",
    "test_hang_detector.py", "test_multinode_e2e.py",
    "test_node_relaunch_e2e.py", "test_preemption_e2e.py",
    "test_soak.py",
}


def pytest_collection_modifyitems(items):
    for item in items:
        if os.path.basename(str(item.fspath)) in _E2E_GROUP_FILES:
            item.add_marker(pytest.mark.xdist_group("elastic_e2e"))


def _alarm_guard(item):
    """SIGALRM guard for one test phase, honoring ``@pytest.mark.timeout``.

    pytest-timeout is not vendored in this image; without this guard the
    mark would be silently inert and one wedged e2e subprocess could hang
    the whole suite forever. setitimer (not alarm) so fractional-second
    timeouts work.
    """
    marker = item.get_closest_marker("timeout")
    if marker is None or not hasattr(signal, "SIGALRM"):
        yield
        return
    seconds = float(marker.args[0]) if marker.args else float(
        marker.kwargs.get("seconds", 300)
    )
    if seconds <= 0:
        raise ValueError(
            f"{item.nodeid}: timeout mark must be positive, got {seconds}"
        )

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"{item.nodeid} exceeded its {seconds}s timeout mark"
        )

    old_handler = signal.signal(signal.SIGALRM, _on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old_handler)


# Cover every phase a test can wedge in — fixture setup and teardown hang
# just as hard as the call body (pytest-timeout covers all three too).
@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_setup(item):
    yield from _alarm_guard(item)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    yield from _alarm_guard(item)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_teardown(item):
    yield from _alarm_guard(item)


@pytest.fixture()
def tmp_ipc_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
    return tmp_path


@pytest.fixture(autouse=True)
def _kv_page_ledger_guard():
    """§31 conservation invariant, asserted after EVERY test that
    touched a serving engine: each physical KV page is exactly one of
    free or leased-with-positive-refcount, and the COW sharing index
    round-trips. Keyed off sys.modules so the ~90% of tests that never
    import the serving engine pay nothing. Replica threads may still be
    retiring when the test body returns, so one short retry absorbs
    in-flight teardown before the failure is real."""
    yield
    import sys
    import time as _time

    em = sys.modules.get("dlrover_tpu.serving.engine")
    if em is None:
        return
    bad = em.check_kv_ledgers()
    if bad:
        _time.sleep(0.05)
        bad = em.check_kv_ledgers()
    assert not bad, f"kv page ledger violated: {bad}"
