"""Test configuration: hermetic 8-device CPU mesh.

Mirrors the reference's gloo-spawn multi-device testing pattern
(SURVEY.md §4): JAX on CPU with ``--xla_force_host_platform_device_count=8``
gives multi-device semantics without TPU hardware.
"""

import os

# Force CPU even when the outer environment points at real hardware
# (JAX_PLATFORMS=axon/tpu): tests must be hermetic and multi-device. A
# sitecustomize may already have imported jax to register a TPU plugin, so
# updating the env alone is not enough — update the live config too (safe:
# backends initialize lazily on first device query).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ipc_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
    return tmp_path
