"""Warm recovery path (DESIGN.md §16): standby trainers, overlapped
restore, rendezvous fast re-admit, Young–Daly snapshot cadence.

The chaos-level determinism of standby promotion lives in
tests/test_chaos.py (two seeded runs, identical trails); this file
covers the mechanisms in isolation: the tuner's math/clamping/
hysteresis, the prefetch's failure ordering (a restore losing the race
to a second failure rolls back exactly like the inline path), the
park/promote handshake, and the unchanged-membership rendezvous fast
path.
"""

from __future__ import annotations

import json
import os
import sys
import textwrap
import time

import numpy as np
import pytest

import jax.numpy as jnp

from dlrover_tpu.checkpoint import engine as engine_mod
from dlrover_tpu.checkpoint.engine import (
    CheckpointEngine,
    start_restore_prefetch,
    take_restore_prefetch,
)
from dlrover_tpu.checkpoint.interval_tuner import IntervalTuner
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.storage import PosixDiskStorage
from dlrover_tpu.master.rdzv_manager import RendezvousManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ Young–Daly tuner


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _tuner(**kw) -> tuple[IntervalTuner, FakeClock]:
    clock = FakeClock()
    kw.setdefault("clock", clock)
    return IntervalTuner(**kw), clock


class TestIntervalTuner:
    def test_needs_min_failures_and_both_costs(self):
        tuner, clock = _tuner()
        assert tuner.recommend() is None
        tuner.observe_failure()
        clock.t = 600.0
        tuner.observe_failure()
        assert tuner.recommend() is None  # no costs yet
        tuner.observe_snapshot_cost(0.5)
        assert tuner.recommend() is None  # still no step time
        tuner.observe_step_time(0.1)
        assert tuner.recommend() is not None

    def test_young_daly_math(self):
        tuner, clock = _tuner()
        tuner.observe_failure(t=0.0)
        tuner.observe_failure(t=600.0)
        tuner.observe_snapshot_cost(0.5)
        tuner.observe_step_time(0.1)
        clock.t = 1200.0
        # MTBF = 1200/2 = 600s; T* = sqrt(2*0.5*600) = 24.49s
        # -> 245 steps at 0.1 s/step
        assert tuner.recommend() == 245

    def test_clamping(self):
        tuner, clock = _tuner(min_steps=10, max_steps=50)
        tuner.observe_failure(t=0.0)
        tuner.observe_failure(t=600.0)
        tuner.observe_snapshot_cost(0.5)
        tuner.observe_step_time(0.1)
        clock.t = 1200.0
        assert tuner.recommend() == 50  # 245 clamped to max
        # an absurdly cheap snapshot under a storm clamps low
        fast, fclock = _tuner(min_steps=10, max_steps=50)
        fast.observe_failure(t=0.0)
        fast.observe_failure(t=0.5)
        fast.observe_snapshot_cost(1e-5)
        fast.observe_step_time(1.0)
        fclock.t = 1.0
        assert fast.recommend() == 10  # tiny T* clamped to min

    def test_first_retune_applies_then_hysteresis_holds(self):
        tuner, clock = _tuner()
        tuner.observe_failure(t=0.0)
        tuner.observe_failure(t=600.0)
        tuner.observe_snapshot_cost(0.5)
        tuner.observe_step_time(0.1)
        clock.t = 1200.0
        assert tuner.maybe_retune() == 245
        assert tuner.current_steps == 245
        # a <25% drift is noise: no retune even though recommend moves
        clock.t = 1500.0  # MTBF 750 -> rec ~274 (+12%)
        assert tuner.recommend() == 274
        assert tuner.maybe_retune() is None
        assert tuner.current_steps == 245

    def test_moves_are_bounded_by_max_move_factor(self):
        tuner, clock = _tuner()
        tuner.observe_failure(t=0.0)
        tuner.observe_failure(t=10.0)
        tuner.observe_snapshot_cost(0.5)
        tuner.observe_step_time(0.1)
        clock.t = 20.0
        first = tuner.maybe_retune()  # MTBF 10 -> sqrt(10)=3.16s -> 32
        assert first == 32
        # failures stop: MTBF stretches enormously, but one retune can
        # at most double the interval
        clock.t = 3000.0
        assert tuner.recommend() > 64
        assert tuner.maybe_retune() == 64
        assert tuner.current_steps == 64

    def test_metrics_snapshot_feed(self):
        tuner, clock = _tuner()
        samples = [
            {"name": "dlrover_tpu_train_step_seconds",
             "type": "histogram",
             "samples": [{"sum": 10.0, "count": 100}]},
            {"name": "dlrover_tpu_ckpt_snapshot_seconds",
             "type": "histogram",
             "samples": [{"sum": 2.0, "count": 10}]},
        ]
        tuner.observe_metrics_snapshot(samples)
        tuner.observe_failure(t=0.0)
        tuner.observe_failure(t=800.0)
        clock.t = 1600.0
        # step 0.1s, snap 0.2s, MTBF 800 -> sqrt(320)=17.9s -> 179
        assert tuner.recommend() == 179

    def test_failures_age_out_of_the_window(self):
        tuner, clock = _tuner(window_s=100.0)
        tuner.observe_failure(t=0.0)
        tuner.observe_failure(t=1.0)
        tuner.observe_snapshot_cost(0.5)
        tuner.observe_step_time(0.1)
        clock.t = 500.0  # both failures long gone
        assert tuner.recommend() is None


# --------------------------------------------------- overlapped restore


def _state(step: int):
    return {
        "w": jnp.arange(32, dtype=jnp.float32) * (step + 1),
        "step": jnp.asarray(step, jnp.int32),
    }


@pytest.fixture()
def committed_engine(tmp_ipc_dir, tmp_path):
    """A solo engine with steps 5 and 10 durably committed."""
    ckpt = str(tmp_path / "ckpt")
    eng = CheckpointEngine(ckpt)
    for step in (5, 10):
        assert eng.save_to_storage(step, _state(step))
        assert eng.wait_for_persist(step, timeout=60)
    yield eng, ckpt
    # drop any prefetch a test left behind so the registry stays clean
    take_restore_prefetch(ckpt, eng.node_id)
    eng.close()


class TestRestorePrefetch:
    def test_load_consumes_the_prefetch(self, committed_engine,
                                        monkeypatch):
        eng, ckpt = committed_engine
        pf = start_restore_prefetch(ckpt)
        assert pf.join(timeout=30) is not None
        # the prefetched result alone must satisfy the load: a fresh
        # synchronous read would blow up here
        monkeypatch.setattr(
            engine_mod, "_read_storage_arrays",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("inline read used despite prefetch")),
        )
        loaded = eng._load_from_storage()
        assert loaded is not None and loaded[0] == 10
        np.testing.assert_array_equal(
            np.asarray(loaded[1]["w"]),
            np.arange(32, dtype=np.float32) * 11,
        )

    def test_idempotent_registration(self, committed_engine):
        eng, ckpt = committed_engine
        a = start_restore_prefetch(ckpt)
        b = start_restore_prefetch(ckpt)
        assert a is b
        assert take_restore_prefetch(ckpt, eng.node_id) is a
        assert take_restore_prefetch(ckpt, eng.node_id) is None

    def test_pinned_step_mismatch_discards_prefetch(self,
                                                   committed_engine):
        eng, ckpt = committed_engine
        pf = start_restore_prefetch(ckpt)
        assert pf.join(timeout=30)[0] == 10
        loaded = eng._load_from_storage(step=5)  # best-model style pin
        assert loaded is not None and loaded[0] == 5

    def test_prefetch_losing_race_to_second_failure_rolls_back(
            self, committed_engine):
        """The overlapped-restore failure ordering: a second failure
        corrupts the newest step before/while the prefetch reads it.
        The prefetch runs the same resolve_restore_step rollback as the
        inline path, so the restore lands on the newest VERIFIED step —
        never the corrupt bytes, never step 0."""
        eng, ckpt = committed_engine
        bin_path = os.path.join(ckpt, "step-10", "node_0.bin")
        blob = bytearray(open(bin_path, "rb").read())
        blob[7] ^= 0x40
        with open(bin_path, "wb") as f:
            f.write(blob)
        pf = start_restore_prefetch(ckpt)
        got = pf.join(timeout=30)
        assert got is not None and got[0] == 5  # rolled back, verified
        loaded = eng._load_from_storage()
        assert loaded is not None and loaded[0] == 5
        np.testing.assert_array_equal(
            np.asarray(loaded[1]["w"]),
            np.arange(32, dtype=np.float32) * 6,
        )

    def test_prefetch_error_falls_back_to_sync_read(self,
                                                    committed_engine):
        eng, ckpt = committed_engine

        class BrokenStorage(PosixDiskStorage):
            def read(self, path):  # noqa: ARG002
                raise OSError("nfs went away")

            def read_text(self, path):  # noqa: ARG002
                raise OSError("nfs went away")

        pf = start_restore_prefetch(ckpt, storage=BrokenStorage())
        assert pf.join(timeout=30) is None
        # the engine's own (healthy) storage still restores
        loaded = eng._load_from_storage()
        assert loaded is not None and loaded[0] == 10


# ------------------------------------------------ standby park/promote


class TestStandbyHandshake:
    def _manager(self, tmp_path, child_body: str, extra_env=None):
        from dlrover_tpu.agent.standby import StandbyManager

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["DLROVER_TPU_IPC_DIR"] = str(tmp_path / "ipc")
        env.update(extra_env or {})
        os.makedirs(env["DLROVER_TPU_IPC_DIR"], exist_ok=True)
        os.environ["DLROVER_TPU_IPC_DIR"] = env["DLROVER_TPU_IPC_DIR"]
        entry = [sys.executable, "-c", textwrap.dedent(child_body)]
        return StandbyManager(entry, node_id=0, base_env=env)

    def test_park_promote_delivers_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
        out = str(tmp_path / "out.json")
        mgr = self._manager(
            tmp_path,
            f"""
            import json, os
            from dlrover_tpu.agent.standby import park_if_standby
            park_if_standby()
            with open({out!r}, "w") as f:
                json.dump({{
                    "rank": os.environ.get("DLROVER_TPU_NODE_RANK"),
                    "coord": os.environ.get("DLROVER_TPU_COORDINATOR"),
                }}, f)
            """,
        )
        try:
            mgr.arm()
            deadline = time.time() + 60
            while time.time() < deadline and not mgr.is_warm():
                time.sleep(0.1)
            assert mgr.is_warm(), "standby never parked"
            proc = mgr.promote({
                EnvKey.NODE_RANK: "3",
                EnvKey.COORDINATOR: "127.0.0.1:9999",
            })
            assert proc is not None
            assert proc.wait(timeout=60) == 0
            got = json.load(open(out))
            assert got == {"rank": "3", "coord": "127.0.0.1:9999"}
            # consumed: a second promotion has nothing to hand over
            assert mgr.promote({EnvKey.NODE_RANK: "4"}) is None
        finally:
            mgr.discard()

    def test_dead_standby_promotes_to_none(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
        mgr = self._manager(tmp_path, "raise SystemExit(3)")
        try:
            mgr.arm()
            deadline = time.time() + 30
            while time.time() < deadline and mgr._proc.poll() is None:
                time.sleep(0.05)
            assert mgr.promote({EnvKey.NODE_RANK: "1"}) is None
            assert not mgr.is_warm()
        finally:
            mgr.discard()

    def test_prepare_signals_the_parked_child(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_IPC_DIR", str(tmp_path / "ipc"))
        mgr = self._manager(tmp_path, "import time; time.sleep(600)")
        try:
            mgr.arm()
            assert mgr.prepare(str(tmp_path / "ckpt"))
            prep = mgr._payload_path + ".prepare"
            assert json.load(open(prep))["ckpt_dir"] == \
                str(tmp_path / "ckpt")
        finally:
            mgr.discard()

    def test_disabled_by_env(self, monkeypatch):
        from dlrover_tpu.agent.standby import standby_enabled

        monkeypatch.delenv("DLROVER_TPU_STANDBY", raising=False)
        assert standby_enabled()
        monkeypatch.setenv("DLROVER_TPU_STANDBY", "0")
        assert not standby_enabled()


# -------------------------------------------- rendezvous fast re-admit


class TestRendezvousFastReadmit:
    def test_unchanged_membership_readmits_immediately(self):
        mgr = RendezvousManager(min_nodes=2, max_nodes=4,
                                waiting_timeout=0.5)
        mgr.join(0, "a:1", 1)
        mgr.join(1, "b:1", 1)
        assert mgr.get_comm_world(0) is None  # below max, no timeout yet
        time.sleep(0.6)
        first = mgr.get_comm_world(0)
        assert first is not None and first.round == 1
        # restart-in-place: the SAME two nodes rejoin
        mgr.join(0, "a:2", 1)
        assert mgr.get_comm_world(0) is None  # partial rejoin: wait
        mgr.join(1, "b:2", 1)
        t0 = time.monotonic()
        second = mgr.get_comm_world(0)
        assert second is not None and second.round == 2
        assert time.monotonic() - t0 < 0.1  # no backoff round
        assert second.node_addrs[0] == "a:2"  # fresh addrs adopted

    def test_true_membership_change_still_backs_off(self):
        mgr = RendezvousManager(min_nodes=2, max_nodes=4,
                                waiting_timeout=0.5)
        mgr.join(0, "a:1", 1)
        mgr.join(1, "b:1", 1)
        time.sleep(0.6)
        assert mgr.get_comm_world(0) is not None
        # node 1 is REMOVED (dead) — the fast path must disarm even
        # though the waiting set momentarily equals the old world
        mgr.remove_node(1)
        mgr.join(0, "a:2", 1)
        mgr.join(1, "b:2", 1)
        assert mgr.get_comm_world(0) is None  # full backoff round again
        time.sleep(0.6)
        got = mgr.get_comm_world(0)
        assert got is not None and got.round == 2

    def test_scale_up_join_disables_fast_path(self):
        mgr = RendezvousManager(min_nodes=2, max_nodes=4,
                                waiting_timeout=0.5)
        mgr.join(0, "a:1", 1)
        mgr.join(1, "b:1", 1)
        time.sleep(0.6)
        assert mgr.get_comm_world(0) is not None
        # a NEW node appears alongside the rejoining members: this is a
        # genuine membership change, wait for the round to gather
        mgr.join(0, "a:2", 1)
        mgr.join(1, "b:2", 1)
        mgr.join(2, "c:1", 1)
        assert mgr.get_comm_world(0) is None
        time.sleep(0.6)
        got = mgr.get_comm_world(0)
        assert got is not None and len(got.world) == 3


# ------------------------------------------- master tuner wiring (e2e)


def test_master_pushes_retune_through_paral_config(tmp_path,
                                                   monkeypatch):
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.job_master import JobMaster

    monkeypatch.setenv(EnvKey.SNAPSHOT_INTERVAL, "auto")
    master = JobMaster(port=0, rdzv_timeout=2.0)
    master.prepare()
    try:
        c = MasterClient(master.addr, 0)
        samples = [
            {"name": "dlrover_tpu_train_step_seconds",
             "type": "histogram",
             "samples": [{"sum": 10.0, "count": 100}]},
            {"name": "dlrover_tpu_ckpt_snapshot_seconds",
             "type": "histogram",
             "samples": [{"sum": 2.0, "count": 10}]},
        ]
        c.report_metrics(samples, role="trainer")
        assert c.get_paral_config().snapshot_interval == 0  # no MTBF yet
        c.report_failure("exit code 9 (killed)", restart_count=0)
        time.sleep(0.05)
        c.report_failure("exit code 9 (killed)", restart_count=1)
        c.report_metrics(samples, role="trainer")
        cfg = c.get_paral_config()
        assert cfg.snapshot_interval >= 1
        assert cfg.version >= 1
        assert not cfg.restart_required  # cadence hot-applies
        c.close()
    finally:
        master.stop()
