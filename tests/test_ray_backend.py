"""Ray scheduler backend: ActorScaler reconcile + actor watcher.

Reference analog: dlrover/python/master/scaler/ray_scaler.py +
master/watcher/ray_watcher.py behavior, tested the reference way — a fake
client records create/kill verbs so the reconcile loop runs hermetically
(SURVEY.md §4 MockRayJobArgs pattern).
"""

from __future__ import annotations

import threading

import pytest

from dlrover_tpu.cluster.crd import (
    ElasticJob,
    ElasticJobSpec,
    ReplicaSpec,
    ScalePlan,
)
from dlrover_tpu.cluster.ray_backend import (
    ActorScaler,
    ActorSpec,
    RayClient,
    actor_spec,
    actor_watcher,
)
from dlrover_tpu.cluster.watcher import PodEvent, wire_to_node_manager
from dlrover_tpu.common.constants import EnvKey, NodeExitReason, NodeStatus


class FakeRay(RayClient):
    def __init__(self):
        self.actors: dict[str, ActorSpec] = {}
        self.lock = threading.Lock()
        self.created: list[str] = []
        self.killed: list[str] = []

    def create_actor(self, spec: ActorSpec) -> None:
        with self.lock:
            self.actors[spec.name] = spec
            self.created.append(spec.name)

    def kill_actor(self, name: str) -> None:
        with self.lock:
            self.actors.pop(name, None)
            self.killed.append(name)

    def list_actors(self, name_prefix: str) -> list[dict]:
        with self.lock:
            return [
                {"name": n, "state": "ALIVE"}
                for n in self.actors if n.startswith(name_prefix)
            ]

    def die(self, name: str) -> None:
        """Out-of-band actor death (node preemption)."""
        with self.lock:
            self.actors.pop(name, None)


def _job(workers=3) -> ElasticJob:
    return ElasticJob(
        name="rayjob",
        spec=ElasticJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=workers, tpu_type="v5p",
                    tpu_topology="2x2x1", memory_mb=8192, cpu=4,
                )
            },
        ),
    )


class TestActorSpec:
    def test_env_contract_and_tpu_resource(self):
        spec = actor_spec(_job(), "worker", 7, "10.0.0.2:5001")
        assert spec.name == "rayjob-worker-7"
        assert spec.env[EnvKey.NODE_ID] == "7"
        assert spec.env[EnvKey.MASTER_ADDR] == "10.0.0.2:5001"
        assert spec.resources == {"tpu-v5p-host": 1.0}
        assert spec.num_cpus == 4.0
        assert spec.memory_mb == 8192

    def test_memory_override(self):
        spec = actor_spec(_job(), "worker", 1, "m:1",
                          memory_mb_override=16384)
        assert spec.memory_mb == 16384


class TestActorScaler:
    def test_scale_up_to_target(self):
        ray = FakeRay()
        s = ActorScaler(_job(), ray, "m:1")
        s.scale(ScalePlan(replica_resources={"worker": 3}))
        assert sorted(ray.actors) == [
            "rayjob-worker-0", "rayjob-worker-1", "rayjob-worker-2"
        ]

    def test_scale_down_kills_highest_and_marks_intentional(self):
        ray = FakeRay()
        s = ActorScaler(_job(), ray, "m:1")
        s.scale(ScalePlan(replica_resources={"worker": 3}))
        s.scale(ScalePlan(replica_resources={"worker": 1}))
        assert sorted(ray.actors) == ["rayjob-worker-0"]
        assert s.consume_intentional_removal(2)
        assert s.consume_intentional_removal(1)
        assert not s.consume_intentional_removal(1)  # consumed once
        assert not s.consume_intentional_removal(0)  # still alive

    def test_relaunch_recreates_and_clears_mark(self):
        ray = FakeRay()
        s = ActorScaler(_job(), ray, "m:1")
        s.scale(ScalePlan(replica_resources={"worker": 2}))
        s.scale(ScalePlan(relaunch_nodes=[1]))
        assert ray.killed == ["rayjob-worker-1"]
        assert "rayjob-worker-1" in ray.actors
        # replacement exists: a later genuine failure must not be masked
        assert not s.consume_intentional_removal(1)

    def test_oom_memory_bump_applies_on_relaunch(self):
        ray = FakeRay()
        s = ActorScaler(_job(), ray, "m:1")
        s.scale(ScalePlan(replica_resources={"worker": 2}))
        s.scale(ScalePlan(memory_mb={"0": 16384}, relaunch_nodes=[0]))
        assert ray.actors["rayjob-worker-0"].memory_mb == 16384
        # other nodes keep the spec default
        assert ray.actors["rayjob-worker-1"].memory_mb == 8192

    def test_dead_actor_backfilled_by_target_reconcile(self):
        ray = FakeRay()
        s = ActorScaler(_job(), ray, "m:1")
        s.scale(ScalePlan(replica_resources={"worker": 3}))
        ray.die("rayjob-worker-1")
        s.scale(ScalePlan(replica_resources={"worker": 3}))
        assert len(ray.actors) == 3
        # the backfill is a NEW node id (3), not a resurrection of 1 —
        # node identity is the master's business, not the scaler's
        assert "rayjob-worker-3" in ray.actors


class _StubNodeManager:
    def __init__(self):
        self.updates: list[tuple[int, str, str]] = []

    def update_status(self, node_id, status, reason):
        self.updates.append((node_id, status, reason))


class TestActorWatcher:
    def test_diff_events_and_failure_wiring(self):
        ray = FakeRay()
        job = _job()
        s = ActorScaler(job, ray, "m:1")
        nm = _StubNodeManager()
        events: list[PodEvent] = []
        handler = wire_to_node_manager(
            nm, was_intentional=s.consume_intentional_removal
        )
        w = actor_watcher(
            ray, job,
            lambda e: (events.append(e), handler(e)),
        )
        s.scale(ScalePlan(replica_resources={"worker": 2}))
        w.poll_once()
        assert {(e.kind, e.node_id) for e in events} == {
            ("added", 0), ("added", 1)
        }
        # out-of-band death -> node FAILED immediately
        ray.die("rayjob-worker-1")
        w.poll_once()
        assert (1, NodeStatus.FAILED, NodeExitReason.KILLED) in nm.updates
        # intentional scale-down -> DELETED, not failed
        s.scale(ScalePlan(replica_resources={"worker": 0}))
        w.poll_once()
        assert (0, NodeStatus.DELETED, NodeExitReason.SUCCEEDED) \
            in nm.updates
        assert not any(
            u for u in nm.updates
            if u[0] == 0 and u[1] == NodeStatus.FAILED
        )


def test_ray_cluster_client_requires_ray():
    from dlrover_tpu.cluster.ray_backend import RayClusterClient

    with pytest.raises(ImportError, match="ray"):
        RayClusterClient()
