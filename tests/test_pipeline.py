"""Pipeline parallelism (parallel/pipeline.py) on the 8-device CPU mesh.

Mirrors the reference's pipeline coverage (PiPPy stage split,
atorch/atorch/auto/opt_lib/pipeline_parallel_optimization.py:56) as numeric
equivalence: the GPipe schedule must compute exactly what the plain layer
scan computes, stages must actually shard the layer stack, and a jitted
train step over pipeline × data must run and learn.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.models import transformer as T
from dlrover_tpu.parallel import strategy as S
from dlrover_tpu.trainer import compile_train

CFG = dataclasses.replace(
    T.CONFIGS["tiny"], n_layers=4, dtype="float32"
)

# Reduction-order-tolerant cross-layout bound: different shardings
# reassociate the bf16-compute matmul/reduce trees (XLA:CPU codegen
# differs per layout), perturbing a single-step loss by a few bf16 ulps
# — measured 0.1-1.2% on this jax build. 4x bf16 eps (2^-8) bounds that
# with margin while still failing on a genuinely wrong sharding or a
# resharding bug, which shift the loss by O(1). Resharding correctness
# (DESIGN.md §17) leans on exactly this equivalence.
RTOL_CROSS_LAYOUT = 4 * 2.0 ** -8


def _batch(key, b=8, s=32):
    return {
        "tokens": jax.random.randint(key, (b, s + 1), 0, CFG.vocab_size)
    }


class TestPipelineNumerics:
    def test_forward_matches_scan(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        tokens = _batch(jax.random.PRNGKey(1))["tokens"][:, :-1]
        ref = T.forward(params, tokens, CFG)
        for stages, mb in [(2, 2), (2, 4), (4, 4), (4, 8)]:
            cfg_pp = dataclasses.replace(
                CFG, pipeline_stages=stages, pipeline_microbatches=mb
            )
            got = T.forward(params, tokens, cfg_pp)
            np.testing.assert_allclose(
                np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5,
                err_msg=f"stages={stages} mb={mb}",
            )

    def test_grads_match_scan(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        batch = _batch(jax.random.PRNGKey(1))
        cfg_pp = dataclasses.replace(CFG, pipeline_stages=2)
        ref = jax.grad(lambda p: T.loss_fn(p, batch, CFG))(params)
        got = jax.grad(lambda p: T.loss_fn(p, batch, cfg_pp))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            ref, got,
        )

    def test_layer_indivisible_raises(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        tokens = _batch(jax.random.PRNGKey(1))["tokens"][:, :-1]
        cfg_pp = dataclasses.replace(CFG, pipeline_stages=3)
        with pytest.raises(ValueError, match="divisible"):
            T.forward(params, tokens, cfg_pp)

    def test_moe_rejected(self):
        cfg = dataclasses.replace(
            T.CONFIGS["tiny-moe"], pipeline_stages=2
        )
        params = T.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((4, 16), jnp.int32)
        with pytest.raises(NotImplementedError, match="pipeline \\+ MoE"):
            T.forward(params, tokens, cfg)


class TestPipelineStrategy:
    def test_stage_weights_sharded(self):
        strat = S.pipeline(pipeline_size=4, data_size=2)
        mesh = strat.build_mesh()
        specs = strat.specs(T.logical_axes(CFG), mesh)
        assert specs["layers"]["wq"] == P("pipeline")
        assert specs["embed"] == P()  # embed replicated (no fsdp axis)

    def test_train_step_pipeline_x_data(self):
        strat = S.pipeline(pipeline_size=2, data_size=4)
        mesh = strat.build_mesh()
        ct = compile_train(
            strategy=strat,
            mesh=mesh,
            loss_fn=T.make_loss_fn(CFG, strat, mesh),
            init_params_fn=lambda rng: T.init_params(CFG, rng),
            logical_params=T.logical_axes(CFG),
            optimizer=optax.adamw(1e-2),
        )
        state = ct.init(jax.random.PRNGKey(0))
        # layer weights live only on their stage's devices
        wq = state.params["layers"]["wq"]
        assert wq.sharding.spec == P("pipeline")
        losses = []
        for i in range(8):
            batch = jax.tree.map(
                lambda x: x[None], _batch(jax.random.PRNGKey(i))
            )
            state, metrics = ct.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    # slow tier for COMPILE COST only (two full strategy compiles; the
    # cheaper test_matches_dp_loss carries this equivalence in tier-1);
    # the bound is the reduction-order-tolerant RTOL_CROSS_LAYOUT.
    @pytest.mark.slow
    def test_mixed_3d_trains_and_matches_dp(self):
        """pipeline × tensor × data on all 8 devices: stage weights shard
        on both the pipeline and tensor axes, loss matches pure dp."""
        strat = S.mixed(pipeline_size=2, tensor_size=2, data_size=2)
        mesh = strat.build_mesh()
        specs = strat.specs(T.logical_axes(CFG), mesh)
        assert specs["layers"]["wq"] == P("pipeline", None, "tensor")
        ct = compile_train(
            strategy=strat,
            mesh=mesh,
            loss_fn=T.make_loss_fn(CFG, strat, mesh),
            init_params_fn=lambda rng: T.init_params(CFG, rng),
            logical_params=T.logical_axes(CFG),
            optimizer=optax.sgd(1e-2),
        )
        state = ct.init(jax.random.PRNGKey(0))
        batch = jax.tree.map(
            lambda x: x[None], _batch(jax.random.PRNGKey(42))
        )
        _, metrics = ct.step(state, batch)

        strat_dp = S.dp()
        mesh_dp = strat_dp.build_mesh()
        ct_dp = compile_train(
            strategy=strat_dp,
            mesh=mesh_dp,
            loss_fn=T.make_loss_fn(CFG, strat_dp, mesh_dp),
            init_params_fn=lambda rng: T.init_params(CFG, rng),
            logical_params=T.logical_axes(CFG),
            optimizer=optax.sgd(1e-2),
        )
        state_dp = ct_dp.init(jax.random.PRNGKey(0))
        _, metrics_dp = ct_dp.step(state_dp, batch)
        assert float(metrics["loss"]) == pytest.approx(
            float(metrics_dp["loss"]), rel=RTOL_CROSS_LAYOUT
        )

    # tier-1 again (the numerics pass): the reduction-order-tolerant
    # bound above absorbs XLA:CPU's per-layout codegen divergence, and
    # this is the cheapest of the cross-layout equivalence tests —
    # resharding correctness depends on this equivalence holding.
    def test_matches_dp_loss(self):
        """Same params + batch: pipeline×data loss == dp loss (within
        the reduction-order bound)."""
        strat_pp = S.pipeline(pipeline_size=2, data_size=4)
        strat_dp = S.dp()
        results = {}
        for name, strat in [("pp", strat_pp), ("dp", strat_dp)]:
            mesh = strat.build_mesh()
            ct = compile_train(
                strategy=strat,
                mesh=mesh,
                loss_fn=T.make_loss_fn(CFG, strat, mesh),
                init_params_fn=lambda rng: T.init_params(CFG, rng),
                logical_params=T.logical_axes(CFG),
                optimizer=optax.sgd(1e-2),
            )
            state = ct.init(jax.random.PRNGKey(0))
            batch = jax.tree.map(
                lambda x: x[None], _batch(jax.random.PRNGKey(42))
            )
            _, metrics = ct.step(state, batch)
            results[name] = float(metrics["loss"])
        assert results["pp"] == pytest.approx(results["dp"],
                                              rel=RTOL_CROSS_LAYOUT)


class TestInterleavedSchedule:
    """Interleaved (circular) pipeline: the 1F1B-class schedule
    (reference pipeline_parallel_optimization.py:56's schedule family),
    SPMD-roll form."""

    def test_forward_matches_scan(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        tokens = _batch(jax.random.PRNGKey(1))["tokens"][:, :-1]
        ref = T.forward(params, tokens, CFG)
        # M > P cases exercise the grouped-injection generalization
        # (microbatches flow in M/P groups of P through the ring)
        for stages, mb, il in [(2, 2, 2), (4, 4, 1), (2, 4, 2),
                               (2, 8, 2)]:
            cfg_pp = dataclasses.replace(
                CFG, pipeline_stages=stages,
                pipeline_microbatches=mb, pipeline_interleave=il,
            )
            got = T.forward(params, tokens, cfg_pp)
            np.testing.assert_allclose(
                np.asarray(ref), np.asarray(got), rtol=2e-5, atol=2e-5,
                err_msg=f"stages={stages} mb={mb} interleave={il}",
            )

    def test_grads_match_scan(self):
        params = T.init_params(CFG, jax.random.PRNGKey(0))
        batch = _batch(jax.random.PRNGKey(1))
        ref = jax.grad(lambda p: T.loss_fn(p, batch, CFG))(params)
        for mb in (2, 4):  # M == P and the grouped M = 2P schedule
            cfg_pp = dataclasses.replace(
                CFG, pipeline_stages=2, pipeline_microbatches=mb,
                pipeline_interleave=2,
            )
            got = jax.grad(lambda p: T.loss_fn(p, batch, cfg_pp))(params)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
                ),
                ref, got,
            )

    def test_microbatch_constraint(self):
        from dlrover_tpu.parallel.pipeline import pipeline_apply

        with pytest.raises(ValueError,
                           match="microbatches divisible by stages"):
            pipeline_apply(
                lambda h, w: h, jnp.zeros((8, 3)),
                jnp.zeros((6, 4)), num_stages=2, num_microbatches=3,
                interleave=2,
            )

    def test_chunk_divisibility(self):
        from dlrover_tpu.parallel.pipeline import pipeline_apply

        with pytest.raises(ValueError, match="interleave"):
            pipeline_apply(
                lambda h, w: h, jnp.zeros((6, 3)),
                jnp.zeros((4, 4)), num_stages=2, num_microbatches=2,
                interleave=4,
            )

    # slow tier (tier-1 envelope): the heaviest body in this file —
    # the full (P, v, M/P) matrix compiles many schedule variants;
    # single-point parity stays covered in-tier by grads_match_scan /
    # interleaved_matches_dp_loss_small / interleaved_preset_trains.
    # `pytest tests/` still runs it.
    @pytest.mark.slow
    def test_schedule_parity_matrix(self):
        """Raw pipeline_apply vs plain layer chain across the full
        grouped-injection shape matrix (P, v, M/P groups) — tiny
        matmul layers so the whole matrix costs seconds."""
        from dlrover_tpu.parallel.pipeline import pipeline_apply

        L, D = 16, 4
        ws = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.5

        def layer(h, w):
            return jnp.tanh(h @ w)

        def chain(w_, h):
            out, _ = jax.lax.scan(lambda c, w: (layer(c, w), None), h, w_)
            return out

        for P in (2, 4):
            for v in (1, 2, 4):
                if L % (P * v):
                    continue
                for k in (1, 2, 3, 4):
                    M = k * P
                    x = jax.random.normal(jax.random.PRNGKey(1), (M, D))
                    ref = chain(ws, x)
                    got = pipeline_apply(
                        layer, ws, x, num_stages=P,
                        num_microbatches=M, interleave=v,
                    )
                    np.testing.assert_allclose(
                        np.asarray(ref), np.asarray(got),
                        rtol=1e-5, atol=1e-5,
                        err_msg=f"P={P} v={v} M={M}",
                    )
                    # grads double the compile bill, and AD mirrors the
                    # schedule mechanically — k in {1, 2} (the M == P
                    # legacy case + one grouped case per (P, v)) pins it
                    if k > 2:
                        continue
                    gr = jax.grad(lambda w_: chain(w_, x).sum())(ws)
                    gg = jax.grad(
                        lambda w_: pipeline_apply(
                            layer, w_, x, num_stages=P,
                            num_microbatches=M, interleave=v,
                        ).sum()
                    )(ws)
                    np.testing.assert_allclose(
                        np.asarray(gr), np.asarray(gg),
                        rtol=1e-4, atol=1e-5,
                        err_msg=f"grad P={P} v={v} M={M}",
                    )

    def test_bubble_fraction_shrinks(self):
        from dlrover_tpu.parallel.pipeline import bubble_fraction

        gpipe = bubble_fraction(4, 4, 1)
        il2 = bubble_fraction(4, 4, 2)
        il4 = bubble_fraction(4, 4, 4)
        assert gpipe == pytest.approx(3 / 7)
        assert il2 == pytest.approx(3 / 11)
        assert il4 == pytest.approx(3 / 19)
        assert il4 < il2 < gpipe

    def test_interleaved_preset_trains(self):
        strat = S.pipeline(pipeline_size=2, data_size=4, interleave=2)
        mesh = strat.build_mesh()
        ct = compile_train(
            strategy=strat,
            mesh=mesh,
            loss_fn=T.make_loss_fn(CFG, strat, mesh),
            init_params_fn=lambda rng: T.init_params(CFG, rng),
            logical_params=T.logical_axes(CFG),
            optimizer=optax.adamw(1e-2),
        )
        state = ct.init(jax.random.PRNGKey(0))
        losses = []
        for i in range(8):
            batch = jax.tree.map(
                lambda x: x[None], _batch(jax.random.PRNGKey(i))
            )
            state, metrics = ct.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_interleaved_matches_dp_loss_small(self):
        """ISSUE-10 satellite: the interleaved/dp cross-layout
        equivalence BACK in tier-1 — PR 5 parked the full-size variant
        for compile cost; this representative case runs the same two
        strategy compiles at seq 16 (~9s for the pair on this host, vs
        tens of seconds at seq 32), so cross-layout numerics stay
        enforced every run. NB the geometry is divergence-sensitive:
        XLA:CPU's per-layout reassociation measures 0.74% here but
        >2% at d_model=32 or vocab=256 — shrink the SEQUENCE, not the
        width, to stay inside RTOL_CROSS_LAYOUT with margin."""
        cfg = CFG
        strat_il = S.pipeline(pipeline_size=2, data_size=4, interleave=2)
        strat_dp = S.dp()
        tokens = jax.random.randint(
            jax.random.PRNGKey(42), (8, 17), 0, cfg.vocab_size
        )
        results = {}
        for name, strat in [("il", strat_il), ("dp", strat_dp)]:
            mesh = strat.build_mesh()
            ct = compile_train(
                strategy=strat,
                mesh=mesh,
                loss_fn=T.make_loss_fn(cfg, strat, mesh),
                init_params_fn=lambda rng: T.init_params(cfg, rng),
                logical_params=T.logical_axes(cfg),
                optimizer=optax.sgd(1e-2),
            )
            state = ct.init(jax.random.PRNGKey(0))
            batch = {"tokens": tokens[None]}
            _, metrics = ct.step(
                state, jax.device_put(batch, ct.batch_sharding)
            )
            results[name] = float(metrics["loss"])
        assert results["il"] == pytest.approx(results["dp"],
                                              rel=RTOL_CROSS_LAYOUT)

    # slow tier for COMPILE COST only (see test_matches_dp_loss, which
    # carries the cross-layout equivalence in tier-1); the bound is the
    # reduction-order-tolerant RTOL_CROSS_LAYOUT.
    @pytest.mark.slow
    def test_interleaved_matches_dp_loss(self):
        strat_il = S.pipeline(pipeline_size=2, data_size=4, interleave=2)
        strat_dp = S.dp()
        results = {}
        for name, strat in [("il", strat_il), ("dp", strat_dp)]:
            mesh = strat.build_mesh()
            ct = compile_train(
                strategy=strat,
                mesh=mesh,
                loss_fn=T.make_loss_fn(CFG, strat, mesh),
                init_params_fn=lambda rng: T.init_params(CFG, rng),
                logical_params=T.logical_axes(CFG),
                optimizer=optax.sgd(1e-2),
            )
            state = ct.init(jax.random.PRNGKey(0))
            batch = jax.tree.map(
                lambda x: x[None], _batch(jax.random.PRNGKey(42))
            )
            _, metrics = ct.step(state, batch)
            results[name] = float(metrics["loss"])
        assert results["il"] == pytest.approx(results["dp"],
                                              rel=RTOL_CROSS_LAYOUT)
